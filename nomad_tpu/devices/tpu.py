"""The TPU device plugin — this framework's nvidia-plugin analog.

Reference shape: devices/gpu/nvidia/device.go:1 (fingerprint loop,
attributes, Reserve → visibility env vars, Stats); retargeted at the
hardware this framework is named for.

Detection, in order:
  * NOMAD_TPU_DEVICE_MOCK=<n> — n mock chips (tests, and the demo path
    on machines without TPUs; the nvidia reference has the same fake
    mode in its test harness)
  * /dev/accel<N> device files (PCIe TPUs) or /dev/vfio/<N>

Stats are per-instance gauges. Real per-chip utilization requires
libtpu's monitoring socket, which is not wired here; the plugin reports
device-file presence/health and a monotonic uptime so `alloc status`
and `node status` always have live numbers, and the mock mode reports
synthetic utilization so dashboards can be built against the schema.
"""

from __future__ import annotations

import glob
import os
import time

from ..structs.structs import NodeDeviceInstance, NodeDeviceResource

_START = time.monotonic()


class TPUDevice:
    """Fingerprint / reserve / stats for local TPU chips."""

    name = "tpu"
    vendor = "google"

    def __init__(self, config: dict | None = None) -> None:
        config = config or {}
        self.dev_glob = config.get("dev_glob", "/dev/accel*")
        self.mock = int(
            config.get("mock", os.environ.get("NOMAD_TPU_DEVICE_MOCK", 0))
        )
        self.chip_name = config.get("chip", "v5e")

    # -- plugin API (reference plugins/device/device.go) ----------------

    def fingerprint(self) -> list[NodeDeviceResource]:
        if self.mock:
            instances = [
                NodeDeviceInstance(id=f"tpu-{i}", healthy=True)
                for i in range(self.mock)
            ]
            attrs = {"hbm_gib": 16, "mock": "true"}
        else:
            paths = sorted(glob.glob(self.dev_glob)) or sorted(
                glob.glob("/dev/vfio/[0-9]*")
            )
            if not paths:
                return []
            instances = [
                NodeDeviceInstance(id=os.path.basename(p), healthy=True)
                for p in paths
            ]
            attrs = {"count": len(instances)}
        return [
            NodeDeviceResource(
                vendor=self.vendor,
                type="tpu",
                name=self.chip_name,
                instances=instances,
                attributes=attrs,
            )
        ]

    def reserve(self, instance_ids: list[str]) -> dict:
        """Visibility env for a task granted these instances (reference:
        nvidia Reserve → CUDA_VISIBLE_DEVICES). TPU workloads read
        TPU_VISIBLE_DEVICES (libtpu) as chip ordinals."""
        ordinals = []
        for inst in instance_ids:
            tail = inst.rsplit("-", 1)[-1].lstrip("accel")
            ordinals.append(tail if tail.isdigit() else inst)
        return {
            "env": {
                "TPU_VISIBLE_DEVICES": ",".join(ordinals),
                "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,1,{max(1, len(ordinals))}",
            }
        }

    def stats(self) -> dict:
        """instance id -> {stat: value}."""
        uptime = round(time.monotonic() - _START, 1)
        out: dict[str, dict] = {}
        for group in self.fingerprint():
            for i, inst in enumerate(group.instances):
                stats = {
                    "healthy": 1 if inst.healthy else 0,
                    "uptime_seconds": uptime,
                }
                if self.mock:
                    # deterministic synthetic load so dashboards render
                    stats["duty_cycle_pct"] = (int(uptime) * 7 + i * 13) % 100
                    stats["hbm_used_mb"] = 1024 + i * 256
                out[inst.id] = stats
        return out
