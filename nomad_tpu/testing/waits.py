"""Event-driven test waits.

The repeat-offender flaky tests on a loaded 1-core box were all
sleep-polls: `while not cond(): time.sleep(0.05)` burns the very CPU the
condition is waiting on (each poll walks allocs under the GIL) and
re-checks on a fixed cadence regardless of when the state actually
changed. :func:`wait_for_state` instead subscribes to the servers' event
brokers (stream/event_broker.py — every store write publishes) and
re-checks the condition the moment a matching event lands, with a slow
periodic fallback re-check for transitions that publish no event
(leadership changes, snapshot restores, filesystem side effects).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Optional

from ..stream import SubscriptionClosedError
from ..stream.event_broker import KEY_ALL, TOPIC_ALL


def _brokers_of(servers: Iterable) -> list:
    """Accepts core Servers, ClusterServers, or EventBrokers."""
    out = []
    for s in servers:
        broker = getattr(s, "event_broker", None)
        if broker is None:
            inner = getattr(s, "server", None)  # ClusterServer wraps Server
            broker = getattr(inner, "event_broker", None)
        out.append(broker if broker is not None else s)
    return out


def wait_for_state(
    servers: Iterable,
    cond: Callable[[], bool],
    topics: Optional[dict] = None,
    timeout_s: float = 30.0,
    fallback_interval_s: float = 0.5,
) -> bool:
    """Block until cond() is true, re-checking on every matching state
    event from ANY of the given servers' event brokers.

    The per-broker subscription poll uses a short slice so multiple
    brokers multiplex on one thread; `fallback_interval_s` bounds how
    stale the condition check can get when no events fire at all.
    Returns True when the condition held, False on timeout (mirrors the
    wait_until helpers it replaces, so assertions read identically).
    """
    if cond():
        return True
    topics = topics or {TOPIC_ALL: [KEY_ALL]}
    brokers = _brokers_of(servers)
    subs = [b.subscribe(topics) for b in brokers]
    slice_s = max(0.05, fallback_interval_s / max(1, len(subs)))
    deadline = time.monotonic() + timeout_s
    try:
        while time.monotonic() < deadline:
            woke = False
            live = 0
            for i, sub in enumerate(subs):
                if sub is None:
                    continue
                live += 1
                try:
                    if sub.next(timeout_s=slice_s):
                        woke = True
                except SubscriptionClosedError:
                    # fell off the ring (or broker restarted): resubscribe
                    # rather than abandoning the wait
                    try:
                        subs[i] = brokers[i].subscribe(topics)
                    except Exception:
                        subs[i] = None
                if cond():
                    return True
            if live == 0:
                # every subscription dead (broker closed, no servers):
                # fall back to paced polling, never a zero-sleep spin
                time.sleep(slice_s)
            if not woke and cond():  # fallback re-check (event-less writes)
                return True
        return cond()
    finally:
        for sub in subs:
            if sub is not None:
                sub.close()
