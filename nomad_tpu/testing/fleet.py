"""A simulated node fleet: thousands of client agents on a few threads.

Robustness work needs a fleet the test host can't afford to run as real
``Client`` instances (each real client is ~6 threads plus driver
machinery; 10k of them is 60k threads). :class:`SimFleet` keeps the
*protocol* surface of a client — real ``Node.register`` RPCs through the
admission door, real heartbeats re-arming real wheel TTLs, real blocking
alloc watches — while multiplexing every node onto a small cooperative
driver pool (PR 10's ``_SpotFleet`` pattern, generalized): a heap of
``(due, node, action)`` entries that a handful of threads drain in
deadline order.

What is real vs simulated:

  * registration, heartbeat, and alloc-watch traffic is REAL RPC into
    the cluster under test (``rpc_self``, with server failover) — the
    server-side wheel, watch hub, register batcher, and node door see
    exactly the call pattern a real fleet produces;
  * the node's workload side (task runners, fingerprinting, alloc
    health) is absent — fleet scenarios gate on control-plane survival,
    not task execution;
  * a handful of ``real_watchers`` hold genuine long-poll
    ``Node.get_client_allocs`` queries on dedicated threads, while every
    other node probes the leader's watch hub in-process (O(1)) — 10k
    parked watcher threads would measure the host's thread scheduler,
    not the server.

``run_fleet_scale`` is the scenario harness: registration storm through
the node door, steady-state heartbeats + job traffic, a mass partition
(wheel expiry storm → batched down-marks), and a mass reconnect
(admission + register batcher), with the raft-entry accounting and
latency/CPU gates the ROADMAP's fleet-scale item calls for.
"""

from __future__ import annotations

import heapq
import logging
import math
import random
import threading
import time
from typing import Optional

from .. import metrics, mock
from ..structs.structs import NODE_STATUS_DOWN, NODE_STATUS_READY

logger = logging.getLogger("nomad_tpu.testing")

ACT_REGISTER = 0
ACT_HEARTBEAT = 1
ACT_WATCH = 2


class _SimNode:
    __slots__ = ("node", "ttl", "alive", "watch_index")

    def __init__(self, node) -> None:
        self.node = node
        self.ttl = 10.0
        self.alive = True
        self.watch_index = 0


class _RealWatcher(threading.Thread):
    """One genuine blocking-query loop (the real client's
    ``_watch_allocs`` shape) — the subset of the fleet that exercises
    the server's long-poll path end to end."""

    def __init__(self, fleet: "SimFleet", node_id: str,
                 timeout_s: float = 2.0) -> None:
        super().__init__(name=f"fleet-watch-{node_id[:8]}", daemon=True)
        self.fleet = fleet
        self.node_id = node_id
        self.timeout_s = timeout_s
        self.rounds = 0
        self.alloc_rounds = 0
        self.errors = 0

    def run(self) -> None:
        index = 0
        while not self.fleet._stop.is_set():
            try:
                res = self.fleet._rpc(
                    "Node.get_client_allocs",
                    {
                        "node_id": self.node_id,
                        "min_index": index + 1,
                        "timeout_s": self.timeout_s,
                    },
                )
            except Exception:
                self.errors += 1
                self.fleet._stop.wait(0.5)
                continue
            index = max(index, res["index"])
            self.rounds += 1
            if res["allocs"]:
                self.alloc_rounds += 1


class SimFleet:
    def __init__(
        self,
        cluster,
        size: int,
        seed: int,
        *,
        driver_threads: int = 4,
        hb_frac: float = 0.5,
        watch_period_s: float = 2.0,
        real_watchers: int = 0,
        latency_cap: int = 5000,
    ) -> None:
        self.cluster = cluster
        self.size = size
        self.hb_frac = hb_frac
        self.watch_period_s = watch_period_s
        self._rng = random.Random(seed ^ 0xF1EE7)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._heap: list[tuple[float, int, str, int]] = []
        self._seq = 0
        self._sims: dict[str, _SimNode] = {}
        self.registered: set[str] = set()
        self.dead_at: dict[str, float] = {}
        # counters (under _lock)
        self.throttled = 0
        self.register_errors = 0
        self.hb_errors = 0
        self.watch_advances = 0
        # heartbeat RPC latency reservoir (bounded, seed-deterministic)
        self._lat_cap = latency_cap
        self._lats: list[float] = []
        self._hb_count = 0
        self._stop = threading.Event()
        self._drivers = [
            threading.Thread(
                target=self._drive, name=f"fleet-driver-{i}", daemon=True
            )
            for i in range(max(1, driver_threads))
        ]
        self._n_real_watchers = real_watchers
        self.watchers: list[_RealWatcher] = []

    # -- RPC (failover across live servers, like _SpotFleet) -----------

    def _rpc(self, method: str, args):
        last: Optional[Exception] = None
        for nid in sorted(self.cluster.servers):
            cs = self.cluster.servers.get(nid)
            if cs is None:  # raced a kill
                continue
            try:
                return cs.rpc_self(method, args)
            except Exception as e:
                last = e
                # a throttle verdict is an ANSWER from the door, not a
                # dead server — don't shop it to the next peer
                if _retry_after(e) is not None:
                    raise
        if last is not None:
            raise last
        raise RuntimeError("no live servers")

    # -- lifecycle ------------------------------------------------------

    def populate(self, deadline_s: float = 120.0) -> bool:
        """Create every node and fire the whole registration storm at
        once — the node door paces admission; throttled nodes honor the
        Retry-After hint like real clients. True once ALL registered."""
        now = time.monotonic()
        with self._cv:
            for _ in range(self.size):
                sim = _SimNode(mock.node())
                self._sims[sim.node.id] = sim
                self._push_locked(now, sim.node.id, ACT_REGISTER)
            self._cv.notify_all()
        for t in self._drivers:
            if not t.is_alive():
                t.start()
        ok = self._wait(
            lambda: len(self.registered) >= self.size, deadline_s
        )
        if ok and self._n_real_watchers:
            ids = sorted(self._sims)[: self._n_real_watchers]
            self.watchers = [_RealWatcher(self, nid) for nid in ids]
            for w in self.watchers:
                w.start()
        return ok

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._drivers:
            if t.is_alive():
                t.join(timeout=10)
        for w in self.watchers:
            w.join(timeout=10)

    # -- mass operations ------------------------------------------------

    def kill(self, fraction: float) -> list[str]:
        """Silent mass death (partition / reclaim): heartbeats from the
        victims just STOP — only the leader's wheel can notice."""
        with self._lock:
            candidates = sorted(self.registered)
            n = max(1, math.ceil(len(candidates) * fraction))
            victims = self._rng.sample(candidates, min(n, len(candidates)))
            died = time.monotonic()
            for nid in victims:
                self._sims[nid].alive = False
                self.registered.discard(nid)
                self.dead_at[nid] = died
        return victims

    def reconnect(self, node_ids: list[str], spread_s: float = 0.0) -> None:
        """The partition heals: every victim re-registers at once (or
        within ``spread_s``). This is the storm the register batcher and
        the node door exist for."""
        now = time.monotonic()
        with self._cv:
            for nid in node_ids:
                sim = self._sims.get(nid)
                if sim is None:
                    continue
                sim.alive = True
                self.dead_at.pop(nid, None)
                self._push_locked(
                    now + self._rng.uniform(0, spread_s), nid, ACT_REGISTER
                )
            self._cv.notify_all()

    # -- cooperative driver ---------------------------------------------

    def _push_locked(self, due: float, node_id: str, action: int) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (due, self._seq, node_id, action))

    def _push(self, due: float, node_id: str, action: int) -> None:
        with self._cv:
            self._push_locked(due, node_id, action)
            self._cv.notify()

    def _drive(self) -> None:
        while not self._stop.is_set():
            entry = None
            with self._cv:
                while not self._stop.is_set():
                    now = time.monotonic()
                    if self._heap and self._heap[0][0] <= now:
                        entry = heapq.heappop(self._heap)
                        break
                    wait = 0.2
                    if self._heap:
                        wait = min(wait, max(0.0, self._heap[0][0] - now))
                    self._cv.wait(wait)
            if entry is None:
                return
            _due, _seq, node_id, action = entry
            try:
                self._step(node_id, action)
            except Exception:
                logger.exception("fleet action failed")

    def _step(self, node_id: str, action: int) -> None:
        sim = self._sims.get(node_id)
        if sim is None or not sim.alive:
            return
        now = time.monotonic()
        if action == ACT_REGISTER:
            try:
                sim.ttl = float(
                    self._rpc("Node.register", {"node": sim.node})
                )
            except Exception as e:
                hint = _retry_after(e)
                with self._lock:
                    if hint is not None:
                        self.throttled += 1
                    else:
                        self.register_errors += 1
                delay = (
                    hint + self._rng.uniform(0, hint / 2)
                    if hint
                    else 0.2 + self._rng.uniform(0, 0.2)
                )
                self._push(now + delay, node_id, ACT_REGISTER)
                return
            with self._lock:
                self.registered.add(node_id)
            # like the real client: promote to ready immediately with a
            # first heartbeat instead of idling `initializing`/down
            self._push(now + self._rng.uniform(0, 0.05), node_id,
                       ACT_HEARTBEAT)
            self._push(
                now + self._rng.uniform(0, self.watch_period_s),
                node_id, ACT_WATCH,
            )
        elif action == ACT_HEARTBEAT:
            t0 = time.perf_counter()
            try:
                sim.ttl = float(
                    self._rpc("Node.heartbeat", {"node_id": node_id})
                )
            except Exception:
                with self._lock:
                    self.hb_errors += 1
                self._push(
                    now + min(1.0, max(0.1, sim.ttl / 4)),
                    node_id, ACT_HEARTBEAT,
                )
                return
            self._record_latency(time.perf_counter() - t0)
            period = sim.ttl * self.hb_frac
            self._push(
                now + period * self._rng.uniform(0.9, 1.0),
                node_id, ACT_HEARTBEAT,
            )
        elif action == ACT_WATCH:
            # in-process O(1) probe of the hub's per-node cursor: "did
            # my alloc set change?" without parking a thread per node
            lead = self.cluster.leader()
            if lead is not None:
                idx = lead.server.watch_hub.index_of(node_id)
                if idx > sim.watch_index:
                    sim.watch_index = idx
                    with self._lock:
                        self.watch_advances += 1
            self._push(now + self.watch_period_s, node_id, ACT_WATCH)

    # -- measurement -----------------------------------------------------

    def _record_latency(self, lat: float) -> None:
        with self._lock:
            self._hb_count += 1
            if len(self._lats) < self._lat_cap:
                self._lats.append(lat)
            else:
                j = self._rng.randrange(self._hb_count)
                if j < self._lat_cap:
                    self._lats[j] = lat

    def _wait(self, pred, timeout_s: float, poll_s: float = 0.1) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            if self._stop.wait(poll_s):
                return pred()
        return pred()

    def hb_percentiles(self) -> dict[str, float]:
        with self._lock:
            lats = sorted(self._lats)
            count = self._hb_count
        if not lats:
            return {"count": 0, "p50": 0.0, "p99": 0.0, "max": 0.0}
        def q(p: float) -> float:
            return lats[min(len(lats) - 1, int(p * len(lats)))]
        return {
            "count": count,
            "p50": round(q(0.50), 6),
            "p99": round(q(0.99), 6),
            "max": round(lats[-1], 6),
        }

    def report(self) -> dict:
        with self._lock:
            out = {
                "size": self.size,
                "registered": len(self.registered),
                "throttled": self.throttled,
                "register_errors": self.register_errors,
                "hb_errors": self.hb_errors,
                "watch_advances": self.watch_advances,
            }
        out["hb_rpc_seconds"] = self.hb_percentiles()
        out["real_watchers"] = {
            "count": len(self.watchers),
            "rounds": sum(w.rounds for w in self.watchers),
            "alloc_rounds": sum(w.alloc_rounds for w in self.watchers),
            "errors": sum(w.errors for w in self.watchers),
        }
        return out


def _retry_after(e: BaseException) -> Optional[float]:
    from ..ratelimit import retry_after_from_text

    return retry_after_from_text(str(e))


def _counters() -> dict:
    return dict(metrics.registry().snapshot()["counters"])


def _delta(after: dict, before: dict, name: str) -> float:
    return after.get(name, 0) - before.get(name, 0)


def run_fleet_scale(
    data_root: str,
    *,
    seed: int = 0,
    n_servers: int = 1,
    n_nodes: int = 500,
    steady_s: float = 10.0,
    heartbeat_ttl_s: float = 2.0,
    hb_rate_hz: float = 0.0,
    driver_threads: int = 4,
    real_watchers: int = 4,
    partition_fraction: float = 0.2,
    node_register_rate: float = 0.0,
    register_deadline_s: float = 60.0,
    expiry_grace_factor: float = 6.0,
    min_avg_batch: float = 2.0,
    rate: float = 10.0,
    p99_bound_s: float = 0.5,
    cpu_per_node_bound: float = 0.005,
    use_tpu_worker: bool = False,
) -> dict:
    """Fleet-scale survival: registration storm → steady state → mass
    expiry → mass reconnect, against a live cluster.

    Gates returned in the report:
      * ``registered_all`` — the whole fleet got through the node door;
      * ``expiry_detected`` / ``expiry_batched`` — every silent victim
        is down-marked within ``ttl × expiry_grace_factor``, via
        coalesced wheel sweeps (avg expiry batch ≥ ``min_avg_batch``,
        or raft entries bounded by the wheel ticks the victims'
        deadlines span — per-node down-marks fail either way);
      * ``reconnect_recovered`` / ``reconnect_batched`` — the reconnect
        storm re-admits everyone, with node raft entries bounded by the
        register batcher (avg batch ≥ ``min_avg_batch``);
      * ``p99_bounded`` — heartbeat RPC p99 under ``p99_bound_s``
        THROUGH both storms;
      * ``cpu_bounded`` — server process CPU per node per wall-second
        under ``cpu_per_node_bound`` (cores/node);
      * ``invariants_ok`` / ``converged`` — the standard chaos-cluster
        invariants hold after the dust settles.
    """
    from .chaos import ChaosCluster
    from .loadgen import LoadGen, LoadGenConfig
    from .scenarios import _join_loadgen, _loadgen_thread

    if hb_rate_hz <= 0:
        # hold the granted TTL at ~heartbeat_ttl_s regardless of fleet
        # size (the production 50/s cap would stretch a 5k-node TTL to
        # 100s — correct for production, useless in a 10-minute soak)
        hb_rate_hz = max(50.0, n_nodes / heartbeat_ttl_s)
    if node_register_rate <= 0:
        # admit the whole fleet within about half the register deadline
        node_register_rate = max(
            50.0, n_nodes / max(register_deadline_s / 2, 1.0)
        )

    cluster = ChaosCluster(
        n_servers, data_root, seed=seed, num_workers=1,
        use_tpu_batch_worker=use_tpu_worker,
    )
    fleet: Optional[SimFleet] = None
    victims: list[str] = []
    try:
        cluster.start()
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError("fleet cluster never elected a leader")
        from ..retry import RetryPolicy

        for cs in cluster.servers.values():
            cs.forward_retry = RetryPolicy(
                base_s=0.05, max_s=0.5, deadline_s=5.0
            )
            cs.server.heartbeaters.min_ttl_s = heartbeat_ttl_s
            cs.server.heartbeaters.rate_hz = hb_rate_hz
            # burst sized to the expected heal-storm: a partition's worth
            # of reconnects rushes the door at full speed (the register
            # batcher coalesces it into shared raft entries) while the
            # sustained rate still paces an unbounded flood — pacing
            # every reconnect to rate would feed the batcher one
            # registration at a time and defeat the coalescing it gates
            cs.set_node_register_limit(
                node_register_rate,
                max(node_register_rate / 2,
                    n_nodes * partition_fraction),
            )

        fleet = SimFleet(
            cluster, n_nodes, seed,
            driver_threads=driver_threads,
            real_watchers=real_watchers,
        )
        c_boot = _counters()
        t_pop = time.monotonic()
        registered_all = fleet.populate(deadline_s=register_deadline_s)
        populate_s = round(time.monotonic() - t_pop, 2)
        c_pop = _counters()

        # job traffic so the fleet's allocs (and the watch path) carry
        # real placements through the storms
        cfg = LoadGenConfig(
            rate_eval_per_s=rate,
            duration_s=3600.0,  # stopped explicitly below
            seed=seed,
            node_count=0,  # jobs land on the sim fleet's nodes
            node_churn_period_s=0.0,
            heartbeat_period_s=3600.0,
            submitters=2,
        )
        gen = LoadGen(cluster, cfg)
        t, box = _loadgen_thread(gen)
        if not gen.setup_done.wait(timeout=60):
            raise RuntimeError("loadgen setup never finished")

        cpu_t0 = time.process_time()
        wall_t0 = time.monotonic()

        # steady state: heartbeats + watches + placements
        hub_peak = 0
        deadline = time.monotonic() + steady_s
        while time.monotonic() < deadline:
            time.sleep(0.25)
            lead = cluster.leader()
            if lead is not None:
                hub_peak = max(
                    hub_peak,
                    int(lead.server.watch_hub.stats()["nodes_tracked"]),
                )

        # mass expiry: a fraction of the fleet goes silent at once
        c0 = _counters()
        victims = fleet.kill(partition_fraction)
        expiry_bound_s = heartbeat_ttl_s * 1.5 + \
            heartbeat_ttl_s * expiry_grace_factor

        def all_down() -> bool:
            lead = cluster.leader()
            if lead is None:
                return False
            state = lead.server.state
            for nid in victims:
                node = state.node_by_id(nid)
                if node is None or node.status != NODE_STATUS_DOWN:
                    return False
            return True

        t_exp = time.monotonic()
        expiry_detected = fleet._wait(all_down, expiry_bound_s + 30.0)
        expiry_detect_s = round(time.monotonic() - t_exp, 2)
        c1 = _counters()

        # mass reconnect: the partition heals, everyone re-registers
        fleet.reconnect(victims)

        def all_ready() -> bool:
            with fleet._lock:
                if len(fleet.registered) < n_nodes:
                    return False
            lead = cluster.leader()
            if lead is None:
                return False
            state = lead.server.state
            return all(
                (node := state.node_by_id(nid)) is not None
                and node.status == NODE_STATUS_READY
                for nid in victims
            )

        t_rec = time.monotonic()
        reconnect_recovered = fleet._wait(
            all_ready, register_deadline_s + heartbeat_ttl_s + 30.0
        )
        reconnect_s = round(time.monotonic() - t_rec, 2)
        c2 = _counters()

        cpu_delta = time.process_time() - cpu_t0
        wall = max(time.monotonic() - wall_t0, 1e-9)

        gen.stop()
        lg_report = _join_loadgen(t, box, timeout_s=120)
        fleet.stop()

        converged = cluster.converged(timeout_s=60)
        cluster.acked_jobs = set(gen.acked_jobs)
        invariants_ok, invariant_error = True, ""
        try:
            cluster.check_invariants()
        except AssertionError as e:
            invariants_ok, invariant_error = False, str(e)

        # raft-entry accounting for the two storms
        expired = _delta(c1, c0, "nomad.heartbeat.expired")
        expire_batches = _delta(c1, c0, "nomad.heartbeat.expire_batches")
        rec_batches = _delta(c2, c1, "nomad.fleet.node_raft_batches")
        rec_coalesced = _delta(c2, c1, "nomad.fleet.node_raft_coalesced")
        avg_expiry_batch = expired / expire_batches if expire_batches else 0.0
        avg_rec_batch = rec_coalesced / rec_batches if rec_batches else 0.0
        # small fleets can't coalesce meaningfully — only gate batching
        # once a storm is big enough to have a shape
        gate_batching = len(victims) >= 20
        # per-sweep coalescing bounds expiry raft entries by the wheel
        # ticks the victims' deadlines span (about one heartbeat period),
        # not by victim count: a small-TTL smoke legitimately spreads its
        # victims across many ticks at ~2 per entry — that's the wheel
        # working, so accept EITHER dense batches or a tick-bounded entry
        # count (per-node down-marks still fail: victims >> span ticks)
        from ..server.heartbeat import DEFAULT_WHEEL_TICK_S

        expiry_entry_bound = int(
            heartbeat_ttl_s * fleet.hb_frac / DEFAULT_WHEEL_TICK_S
        ) + 2
        hb = fleet.hb_percentiles()
        per_node_cpu_fraction = cpu_delta / wall / max(n_nodes, 1)

        return {
            "seed": seed,
            "n_nodes": n_nodes,
            "n_servers": n_servers,
            "heartbeat_ttl_s": heartbeat_ttl_s,
            "node_register_rate": node_register_rate,
            "populate_s": populate_s,
            "registered_all": registered_all,
            "register_throttled": _delta(
                c_pop, c_boot, "nomad.rpc.node_throttled"
            ),
            "admission_engaged": _delta(
                c2, c_boot, "nomad.rpc.node_throttled"
            ) > 0,
            "fleet": fleet.report(),
            "watch_hub_nodes_tracked_peak": hub_peak,
            "victims": len(victims),
            "expiry_detected": expiry_detected,
            "expiry_detect_s": expiry_detect_s,
            "expiry_bound_s": round(expiry_bound_s + 30.0, 2),
            "expired": expired,
            "expire_batches": expire_batches,
            "avg_expiry_batch": round(avg_expiry_batch, 2),
            "expiry_batched": (
                not gate_batching
                or (expire_batches > 0
                    and (avg_expiry_batch >= min_avg_batch
                         or expire_batches <= expiry_entry_bound))
            ),
            "reconnect_recovered": reconnect_recovered,
            "reconnect_s": reconnect_s,
            "reconnect_batches": rec_batches,
            "reconnect_coalesced": rec_coalesced,
            "avg_reconnect_batch": round(avg_rec_batch, 2),
            "reconnect_batched": (
                not gate_batching
                or (rec_batches > 0 and avg_rec_batch >= min_avg_batch)
            ),
            "hb_p99_s": hb["p99"],
            "p99_bound_s": p99_bound_s,
            "p99_bounded": hb["count"] > 0 and hb["p99"] <= p99_bound_s,
            "server_cpu": {
                "cpu_seconds": round(cpu_delta, 3),
                "wall_seconds": round(wall, 2),
                "per_node_cpu_fraction": round(per_node_cpu_fraction, 7),
            },
            "cpu_per_node_bound": cpu_per_node_bound,
            "cpu_bounded": per_node_cpu_fraction <= cpu_per_node_bound,
            "loadgen": lg_report,
            "converged": converged,
            "invariants_ok": invariants_ok,
            "invariant_error": invariant_error,
        }
    finally:
        if fleet is not None:
            fleet.stop()
        cluster.shutdown()
