from . import chaos  # noqa: F401  (scenario harness + fault-plane re-export)
from .harness import Harness, RejectPlanHarness
from .waits import wait_for_state
