from .harness import Harness, RejectPlanHarness
