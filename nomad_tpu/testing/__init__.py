from .harness import Harness, RejectPlanHarness
from .waits import wait_for_state
