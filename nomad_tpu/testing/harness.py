"""Scheduler test harness.

Reference: scheduler/testing.go — Harness :43 wraps a real state store with a
fake Planner whose SubmitPlan applies the plan directly (:83), bypassing the
plan queue/applier; RejectPlan :18 forces the refresh path. This is the
primary TDD loop for both the host oracle and the TPU solver (differential
testing runs both against identical states).
"""

from __future__ import annotations

import itertools
import logging
from typing import Optional

from ..state import StateStore
from ..structs import Evaluation, Plan, PlanResult
from ..scheduler import new_scheduler

logger = logging.getLogger("nomad_tpu.harness")


class Harness:
    def __init__(self, state: Optional[StateStore] = None) -> None:
        self.state = state or StateStore()
        self._index = itertools.count(1000)
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []  # evals created by the scheduler
        self.updates: list[Evaluation] = []  # eval status updates
        self.optimize_plan = False

    # -- Planner interface --------------------------------------------

    def next_index(self) -> int:
        return next(self._index)

    def submit_plan(self, plan: Plan):
        self.plans.append(plan)
        index = self.next_index()
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=index,
            alloc_batches=plan.alloc_batches,
        )
        self.state.upsert_plan_results(index, result)
        return result, None

    def update_eval(self, eval_obj: Evaluation) -> None:
        self.updates.append(eval_obj)

    def create_eval(self, eval_obj: Evaluation) -> None:
        self.evals.append(eval_obj)
        self.state.upsert_evals(self.next_index(), [eval_obj])

    def refresh_state(self, min_index: int):
        return self.state.snapshot()

    # -- driving ------------------------------------------------------

    def snapshot(self):
        return self.state.snapshot()

    def process(self, scheduler_name: str, eval_obj: Evaluation, config=None):
        """Run one scheduler pass for the eval against current state."""
        sched = new_scheduler(
            scheduler_name, logger, self.state.snapshot(), self, config
        )
        sched.process(eval_obj)
        return sched


class RejectPlanHarness(Harness):
    """Planner that rejects every plan, forcing state refresh + retry
    (reference: scheduler/testing.go RejectPlan :18)."""

    def submit_plan(self, plan: Plan):
        self.plans.append(plan)
        result = PlanResult(refresh_index=self.state.latest_index())
        return result, self.state.snapshot()
