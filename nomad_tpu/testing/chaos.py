"""Chaos scenario harness: scripted kill/partition/heal clusters.

The FaultPlane core lives in the production-side leaf
``nomad_tpu/faultplane.py`` (hook sites import only that); this module
is the TEST surface — it re-exports the whole plane API so tests and
docs say ``from nomad_tpu.testing import chaos`` — plus
:class:`ChaosCluster`, the in-process multi-server cluster that
scenarios kill, restart, partition, and heal, with the standard
invariants every scenario asserts: no acked write lost, no duplicate
alloc minted, convergence within a bound.

See docs/fault-injection.md for the scenario cookbook.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Iterable, Optional

from ..faultplane import (  # noqa: F401  (re-exported plane API)
    DeviceFault,
    DropResponse,
    FaultPlane,
    InjectedDiskError,
    InjectedRPCError,
    active,
    env_knobs_active,
    install,
    uninstall,
)


def __getattr__(name):
    # `chaos.plane` must always reflect the LIVE slot in faultplane
    # (install/uninstall rebind it there); a by-value re-export would
    # go stale after the first install.
    if name == "plane":
        from .. import faultplane

        return faultplane.plane
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# ---------------------------------------------------------------------------
# Scenario harness: a live in-process cluster scripted kills/partitions run
# against, with the standard invariants.
# ---------------------------------------------------------------------------


class ChaosCluster:
    """An in-process raft cluster with durable per-node data dirs that
    scenarios can kill, restart, partition, and heal.

    Every server's ConnPool and raft store carry the node's label so
    the installed FaultPlane can target them; ``install_plane=True``
    (default) installs a fresh seeded plane for the cluster's lifetime
    and uninstalls it on shutdown.
    """

    def __init__(self, n: int, data_root: str, seed: int = 0,
                 install_plane: bool = True, **server_kw) -> None:
        import socket

        self.data_root = data_root
        self.seed = seed
        self.server_kw = dict(server_kw)
        self.plane: Optional[FaultPlane] = None
        self._installed = False
        if install_plane:
            self.plane = install(FaultPlane(seed=seed))
            self._installed = True
        socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        self.ids = [f"s{i}" for i in range(n)]
        self.addrs = {
            nid: ("127.0.0.1", ports[i]) for i, nid in enumerate(self.ids)
        }
        self.servers: dict[str, object] = {}
        # acked-write journal for the no-acked-write-lost invariant:
        # scenarios record ids here only after the RPC returned success
        self.acked_jobs: set[str] = set()
        # Election accounting that survives kills: a killed server's
        # in-memory raft counter dies with it, so harvest it at kill
        # time and add the live counters on read (total_elections).
        self._elections_harvested = 0

    # -- lifecycle -----------------------------------------------------

    def _boot_one(self, nid: str):
        from ..server.cluster import ClusterServer

        kw = dict(self.server_kw)
        cs = ClusterServer(
            nid,
            peers={p: a for p, a in self.addrs.items() if p != nid},
            port=self.addrs[nid][1],
            num_workers=kw.pop("num_workers", 1),
            data_dir=os.path.join(self.data_root, nid),
            **kw,
        )
        # ClusterServer.__init__ already labels its pool/rpc/raft_store
        # with the node id for the plane; the harness only needs to
        # teach the plane which fabric addr belongs to which label.
        if self.plane is not None:
            self.plane.register_addr(nid, cs.rpc.addr)
        cs.start()
        self.servers[nid] = cs
        return cs

    def start(self) -> "ChaosCluster":
        for nid in self.ids:
            self._boot_one(nid)
        return self

    def shutdown(self) -> None:
        for cs in list(self.servers.values()):
            try:
                cs.shutdown()
            except Exception:
                pass
        self.servers.clear()
        if self._installed:
            uninstall()

    # -- scripted faults -----------------------------------------------

    def kill(self, nid: str) -> None:
        """Hard-stop one server (threads die with the sockets; the data
        dir survives for restart)."""
        cs = self.servers.pop(nid, None)
        if cs is not None:
            self._elections_harvested += cs.raft.leadership_transitions
            cs.shutdown()

    def restart(self, nid: str):
        """Boot a fresh incarnation of a killed server from its disk."""
        assert nid not in self.servers, f"{nid} still running"
        return self._boot_one(nid)

    def kill_when(self, nid: str, cond: Callable[[object], bool],
                  timeout_s: float = 30.0) -> bool:
        """Kill `nid` the moment cond(server) first holds — the scripted
        way to land a crash inside a specific window (e.g. mid-replay:
        ``cond=lambda cs: cs.raft.last_applied >= k``). Condition-
        triggered, not timing-triggered, so it reproduces across boxes."""
        cs = self.servers.get(nid)
        if cs is None:
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if cond(cs):
                self.kill(nid)
                return True
            time.sleep(0.002)
        return False

    def partition(self, group_a: Iterable[str], group_b: Iterable[str]) -> None:
        assert self.plane is not None, "cluster booted without a plane"
        self.plane.partition(group_a, group_b)

    def heal(self, kind: Optional[str] = None) -> None:
        """Drop all fault rules, or only one kind (e.g. 'rpc.drop' to
        end a partition while keeping disk/device faults live)."""
        if self.plane is not None:
            self.plane.heal(kind)

    # -- production-ops scenarios --------------------------------------

    def rotate_secret_on(self, nid: str, new_secret: str,
                         window_s=None) -> bool:
        """Rotate ONE server's keyring in place — the per-agent step of
        a staggered rpc_secret rollout (what `Agent.reload` does on
        SIGHUP; rpc/keyring.py dual-accept window)."""
        cs = self.servers[nid]
        return cs.keyring.rotate(new_secret, window_s=window_s)

    def rotate_secret(self, new_secret: str, window_s=None,
                      stagger_s: float = 0.0) -> int:
        """Rotate every live server, optionally pausing between agents
        (a real rollout is never simultaneous — the dual-accept window
        plus the pool's previous-secret dial fallback is what keeps the
        mixed cluster flowing). Future restarts boot with the new
        secret. Returns how many keyrings actually rotated."""
        rotated = 0
        for nid in sorted(self.servers):
            if self.rotate_secret_on(nid, new_secret, window_s=window_s):
                rotated += 1
            if stagger_s > 0:
                time.sleep(stagger_s)
        self.server_kw["rpc_secret"] = new_secret
        return rotated

    def total_elections(self) -> int:
        """Elections won across the cluster's whole history, dead
        incarnations included — the rolling-upgrade churn bound."""
        return self._elections_harvested + sum(
            cs.raft.leadership_transitions for cs in self.servers.values()
        )

    def wait_caught_up(self, nid: str, timeout_s: float = 45.0) -> bool:
        """The restarted-server barrier of a rolling upgrade: wait until
        `nid` has applied everything the CURRENT leader had committed
        when we started waiting — i.e. its replay finished AND it is
        accepting AppendEntries from the live leader again (so it
        counts toward quorum for the next kill)."""
        deadline = time.monotonic() + timeout_s
        target = None
        while time.monotonic() < deadline:
            cs = self.servers.get(nid)
            if cs is None:
                return False
            lead = self.leader()
            if lead is not None and target is None:
                target = lead.raft.commit_index
            if target is not None and cs.raft.last_applied >= target:
                return True
            time.sleep(0.02)
        return False

    def rolling_restart(
        self,
        order=None,
        settle_timeout_s: float = 60.0,
        pause_s: float = 0.0,  # dwell after each step (traffic flows
                               # against the n-1 quorum and then the
                               # freshly-rejoined server)
        pre_kill=None,   # optional hook(nid) before each kill
        post_step=None,  # optional hook(nid) after each re-join settles
    ) -> dict:
        """Restart every server one at a time — the rolling-upgrade
        scenario. Between steps the roll WAITS for a stable quorum and
        for the restarted server's replay barrier, exactly like an
        operator following the upgrade runbook (docs/operations.md).
        Returns evidence: servers rolled, elections across the roll,
        and per-step timings. Raises AssertionError if any step never
        re-converged — a roll must not proceed on a degraded quorum."""
        elections_before = self.total_elections()
        steps = []
        for nid in (list(order) if order else list(self.ids)):
            t0 = time.monotonic()
            if pre_kill is not None:
                pre_kill(nid)
            if pause_s > 0:
                time.sleep(pause_s)
            self.kill(nid)
            # survivors must hold (or re-establish) a working quorum
            # before the node comes back
            lead = self.wait_for_stable_leader(settle_timeout_s)
            assert lead is not None, (
                f"rolling restart: no stable leader after killing {nid}"
            )
            self.restart(nid)
            assert self.wait_caught_up(nid, settle_timeout_s), (
                f"rolling restart: {nid} never caught up after restart"
            )
            if post_step is not None:
                post_step(nid)
            if pause_s > 0:
                time.sleep(pause_s)
            steps.append(
                {"node": nid, "seconds": round(time.monotonic() - t0, 2)}
            )
        return {
            "restarted": len(steps),
            "elections": self.total_elections() - elections_before,
            "steps": steps,
        }

    # -- observation ---------------------------------------------------

    def leader(self):
        for cs in self.servers.values():
            if cs.is_leader():
                return cs
        return None

    def wait_for_stable_leader(self, timeout_s: float = 45.0,
                               stable_for_s: float = 0.0):
        """Wait for exactly one live leader whose replay barrier has
        applied (its FSM is caught up with its own log) and — when
        stable_for_s > 0 — that keeps the lease that long. This is the
        recovery-time 'wait for a stable leader' primitive: callers
        retry through churn instead of failing on the first
        NotLeaderError."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            leaders = [c for c in self.servers.values() if c.is_leader()]
            if len(leaders) == 1:
                lead = leaders[0]
                if lead.raft.wait_for_replay(
                    timeout_s=min(5.0, max(0.1, deadline - time.monotonic()))
                ):
                    if stable_for_s <= 0:
                        return lead
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < stable_for_s:
                        if not lead.is_leader():
                            break
                        time.sleep(0.02)
                    else:
                        return lead
            time.sleep(0.02)
        return None

    def converged(self, timeout_s: float = 45.0) -> bool:
        """Every live server applied the same log prefix (last_applied
        equal across the cluster and no committed entry unapplied)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            lead = self.leader()
            if lead is not None:
                applied = [
                    cs.raft.last_applied for cs in self.servers.values()
                ]
                if (
                    len(set(applied)) == 1
                    and lead.raft.last_applied >= lead.raft.commit_index
                    and lead.raft.commit_index > 0
                ):
                    return True
            time.sleep(0.05)
        return False

    # -- invariants ----------------------------------------------------

    def check_invariants(self) -> None:
        """Assert the scenario-independent safety properties on every
        live server: no acked write lost, no duplicate alloc minted."""
        for nid, cs in self.servers.items():
            st = cs.server.state
            jobs = {
                j.id for j in st.jobs() if not j.stop
            }
            missing = self.acked_jobs - jobs
            assert not missing, (
                f"acked writes lost on {nid}: jobs {sorted(missing)}"
            )
            assert_no_duplicate_allocs(st, label=nid, cluster_server=cs)


def duplicate_alloc_forensics(state, key, id_a, id_b,
                              cluster_server=None) -> dict:
    """Evidence bundle for a duplicate-alloc invariant failure (the
    known ~1/7 bench-soak flake, CHANGES round 15): the two allocs'
    store rows, their minting evals' PLAN-APPLY SNAPSHOT INDEX vs the
    server's raft commit/applied indexes, and the raft log entries that
    carry each alloc id — everything the stale-snapshot-re-placement
    theory needs to be confirmed or killed on evidence. Failure-path
    only; the raft-log scan is a raw substring search over the encoded
    entries (alloc ids are uuid strings, msgpack stores them verbatim).
    """
    out: dict = {"key": list(key)}
    for aid in (id_a, id_b):
        a = state.alloc_by_id(aid)
        row: dict = {"id": aid}
        if a is not None:
            row.update(
                create_index=a.create_index,
                modify_index=a.modify_index,
                desired_status=a.desired_status,
                client_status=a.client_status,
                eval_id=a.eval_id,
            )
            ev = state.eval_by_id(a.eval_id) if a.eval_id else None
            if ev is not None:
                row["eval"] = {
                    "snapshot_index": ev.snapshot_index,
                    "status": ev.status,
                    "triggered_by": ev.triggered_by,
                    "create_index": ev.create_index,
                    "modify_index": ev.modify_index,
                }
        out.setdefault("allocs", []).append(row)
    raft = getattr(cluster_server, "raft", None)
    if raft is not None:
        out["raft"] = {
            "commit_index": getattr(raft, "commit_index", None),
            "last_applied": getattr(raft, "last_applied", None),
            # entries at or below this index are compacted into the
            # snapshot — a mint below it is unscannable (noted, not
            # silently absent)
            "snapshot_last_index": getattr(
                raft, "_snap_last_index", None
            ),
        }
        try:
            log = list(getattr(raft, "_log", ()) or ())
        except Exception:
            log = []
        mints: dict[str, list] = {id_a: [], id_b: []}
        for e in log:
            raw = getattr(e, "payload", b"")
            if not isinstance(raw, (bytes, bytearray)):
                continue
            for aid in (id_a, id_b):
                if aid.encode() in raw:
                    mints[aid].append(
                        {"index": e.index, "type": e.msg_type}
                    )
        out["mint_entries"] = mints
    return out


def assert_no_duplicate_allocs(state, label: str = "",
                               cluster_server=None) -> None:
    """No two live allocations may share (namespace, job, alloc name) —
    a duplicate means one placement request was minted twice (e.g. an
    eval restored from a stale mid-replay snapshot re-placed a job).
    On failure the message carries the store/raft forensics
    (duplicate_alloc_forensics) so a flaky reproduction is evidence,
    not just a flag."""
    import json as _json

    seen: dict[tuple, str] = {}
    for a in state.allocs():
        if a.terminal_status():
            continue
        key = (a.namespace, a.job_id, a.name)
        if key in seen:
            detail = duplicate_alloc_forensics(
                state, key, seen[key], a.id, cluster_server=cluster_server
            )
            raise AssertionError(
                f"duplicate alloc minted{' on ' + label if label else ''}: "
                f"{key} -> {seen[key]} and {a.id}; forensics: "
                + _json.dumps(detail, default=str, sort_keys=True)
            )
        seen[key] = a.id
