"""Production-ops resilience scenarios: the orchestrator must stay
correct *while the operators operate on it*.

Four seeded, invariant-checked scenarios over the ChaosCluster +
LoadGen substrate (testing/chaos.py, testing/loadgen.py):

- :func:`run_secret_rotation` — rotate the fabric ``rpc_secret``
  agent-by-agent under live scheduling traffic (the SIGHUP keyring
  push, rpc/keyring.py): zero dropped RPCs, zero auth failures during
  the dual-accept window, and old-secret dials rejected once it closes.
- :func:`run_rolling_upgrade` — restart every server one at a time
  under traffic, waiting for quorum + the restarted server's replay
  barrier between steps: no acked write lost, no duplicate alloc, and
  leadership churn bounded by restarts + 1.
- :func:`run_spot_churn` — a slice of the client-node fleet dies
  (silently or via a drain notice) and is replaced every cycle while
  jobs keep arriving: the drainer, blocked-evals containment, and the
  scheduler keep converging, the blocked set stays bounded, and no
  allocation is left live on a dead node past the heartbeat TTL.
- :func:`run_pool_member_death` — a solver-pool member is killed
  mid-remote-solve (the leader must fall back local off a retriable
  DeviceFault), then the leader itself is killed with a warm pool: the
  new leader re-points dispatch at the survivors' warm replicas with
  ZERO resident-state cold starts, no acked write lost, no duplicate
  alloc.

Each returns an evidence dict (counters, timings, invariant verdicts);
the tests in tests/test_scenarios.py gate on it. Seeded: the fault
plane, LoadGen op mix, and churn victim choices all draw from seeded
RNGs, so a failing run reproduces by seed.

Runbooks for the human versions of these operations:
docs/operations.md §"Rotating the cluster secret" and §"Rolling a
server upgrade".
"""

from __future__ import annotations

import logging
import math
import random
import threading
import time
from typing import Optional

from .. import metrics
from ..rpc import AuthFailedError, ConnPool, Keyring
from ..structs.structs import DrainStrategy
from .. import mock
from .chaos import ChaosCluster
from .loadgen import LoadGen, LoadGenConfig

logger = logging.getLogger("nomad_tpu.scenarios")

_KEYRING_COUNTERS = (
    "nomad.keyring.rotations",
    "nomad.keyring.accept_previous",
    "nomad.keyring.dial_fallback",
    "nomad.keyring.auth_fail",
)


def _counter_snapshot(names) -> dict:
    counters = metrics.snapshot()["counters"]
    return {n: counters.get(n, 0) for n in names}


def _counter_delta(names, base: dict) -> dict:
    counters = metrics.snapshot()["counters"]
    return {n: counters.get(n, 0) - base[n] for n in names}


def _loadgen_thread(gen: LoadGen) -> tuple[threading.Thread, dict]:
    box: dict = {}

    def run():
        try:
            box["report"] = gen.run()
        except Exception as e:  # surfaced by the caller's join
            logger.exception("scenario loadgen failed")
            box["error"] = e

    t = threading.Thread(target=run, name="scenario-loadgen", daemon=True)
    t.start()
    return t, box


def _join_loadgen(t: threading.Thread, box: dict, timeout_s: float) -> dict:
    t.join(timeout=timeout_s)
    if t.is_alive():
        raise RuntimeError("scenario loadgen never finished")
    if "error" in box:
        raise RuntimeError(f"scenario loadgen failed: {box['error']}")
    return box["report"]


# ---------------------------------------------------------------------------
# 1. Live secret rotation
# ---------------------------------------------------------------------------


class _FabricProber:
    """A client that dials the fabric SOCKET fresh every probe (pooled
    connections outlive a rotation by design — authentication is
    per-connection — so only fresh dials exercise the keyring; this is
    the 'new client agent joins mid-rotation' path). Counts dial
    outcomes; its own keyring is rotated mid-rollout by the scenario,
    so probes cover both mixed-cluster directions: old-secret dial at a
    rotated server (dual-accept) and new-secret dial at a not-yet-
    rotated server (previous-secret fallback)."""

    def __init__(self, cluster: ChaosCluster, secret: str,
                 period_s: float = 0.1) -> None:
        self.cluster = cluster
        self.keyring = Keyring(secret)
        self.period_s = period_s
        self.ok = 0
        self.auth_failures = 0
        self.errors = 0
        self._stop = threading.Event()
        self._t = threading.Thread(
            target=self._run, name="scenario-fabric-probe", daemon=True
        )

    def start(self) -> None:
        self._t.start()

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            lead = self.cluster.leader()
            if lead is None:
                continue
            pool = ConnPool(secret=self.keyring)
            try:
                pool.call(lead.addr, "Status.ping", {}, timeout_s=5)
                self.ok += 1
            except AuthFailedError:
                self.auth_failures += 1
            except Exception:
                self.errors += 1
            finally:
                pool.shutdown()


def run_secret_rotation(
    data_root: str,
    *,
    seed: int = 0,
    n_servers: int = 3,
    rate: float = 30.0,
    duration_s: float = 12.0,
    window_s: float = 6.0,
    stagger_s: float = 0.25,
    old_secret: str = "rotation-secret-v1",
    new_secret: str = "rotation-secret-v2",
    node_count: int = 6,
) -> dict:
    """Rotate the cluster secret under live scheduling traffic and
    return the evidence: keyring counter deltas across the rollout
    (auth_fail must be 0), fabric-probe outcomes, the loadgen report,
    and the post-window probes (old secret rejected, new accepted)."""
    cluster = ChaosCluster(
        n_servers, data_root, seed=seed, num_workers=1,
        rpc_secret=old_secret,
    )
    prober = None
    try:
        cluster.start()
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError("rotation cluster never elected a leader")
        cfg = LoadGenConfig(
            rate_eval_per_s=rate,
            duration_s=duration_s,
            seed=seed,
            node_count=node_count,
            node_churn_period_s=0.0,  # isolate: rotation is the event
            submitters=2,
        )
        gen = LoadGen(cluster, cfg)
        t, box = _loadgen_thread(gen)
        if not gen.setup_done.wait(timeout=60):
            raise RuntimeError("loadgen setup never finished")
        prober = _FabricProber(cluster, old_secret)
        prober.start()
        time.sleep(max(0.5, duration_s * 0.15))  # traffic before the push

        base = _counter_snapshot(_KEYRING_COUNTERS)
        # the staggered rollout: servers one at a time, the fabric
        # client midway — every mixed-cluster direction occurs
        ids = sorted(cluster.servers)
        half = len(ids) // 2
        for i, nid in enumerate(ids):
            if i == half:
                prober.keyring.rotate(new_secret, window_s=window_s)
            cluster.rotate_secret_on(nid, new_secret, window_s=window_s)
            time.sleep(stagger_s)
        cluster.server_kw["rpc_secret"] = new_secret

        # Deterministic dual-accept probes while the window is open:
        # EVERY server must accept a fresh dial presenting the old
        # secret (previous slot) AND the new one (current slot). The
        # background prober's timing depends on load; these do not.
        window_probe_failures = []
        for nid, cs in sorted(cluster.servers.items()):
            for label, sec in (("old", old_secret), ("new", new_secret)):
                pool = ConnPool(secret=sec)
                try:
                    if (
                        pool.call(cs.addr, "Status.ping", {}, timeout_s=10)
                        != "pong"
                    ):
                        window_probe_failures.append((nid, label))
                except Exception as e:
                    window_probe_failures.append((nid, label, str(e)))
                finally:
                    pool.shutdown()

        report = _join_loadgen(t, box, timeout_s=duration_s + 120)
        prober.stop()
        deltas = _counter_delta(_KEYRING_COUNTERS, base)

        converged = cluster.converged(timeout_s=60)
        cluster.acked_jobs = set(gen.acked_jobs)
        invariants_ok, invariant_error = True, ""
        try:
            cluster.check_invariants()
        except AssertionError as e:
            invariants_ok, invariant_error = False, str(e)

        # window close: an old-secret dial must now be REJECTED and a
        # new-secret dial accepted (probed on a fresh pool each)
        remaining = max(
            (cs.keyring.status()["window_remaining_s"]
             for cs in cluster.servers.values()),
            default=0.0,
        )
        time.sleep(remaining + 0.2)
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError(
                "no stable leader after the rotation window closed"
            )
        old_rejected = False
        pool = ConnPool(secret=old_secret)
        try:
            pool.call(lead.addr, "Status.ping", {}, timeout_s=5)
        except AuthFailedError:
            old_rejected = True
        except Exception:
            pass  # counted as not-cleanly-rejected
        finally:
            pool.shutdown()
        pool = ConnPool(secret=new_secret)
        try:
            new_accepted = (
                pool.call(lead.addr, "Status.ping", {}, timeout_s=5)
                == "pong"
            )
        finally:
            pool.shutdown()

        return {
            "seed": seed,
            "loadgen": report,
            "keyring_counters": deltas,
            "rotated_servers": len(ids),
            "probe_ok": prober.ok,
            # CLIENT-VISIBLE auth failures are the gate: a probe call
            # that ultimately failed AuthFailedError, over the probe's
            # whole life (rollout, window, and after). The acceptor-
            # side nomad.keyring.auth_fail counter is evidence, not a
            # gate — it counts first-attempt rejects a staggered
            # rollout EXPECTS (rotated dialer → unrotated server),
            # each recovered by the previous-secret dial fallback
            # (docs/operations.md explains how to read it).
            "probe_auth_failures": prober.auth_failures,
            "probe_errors": prober.errors,
            "dropped_rpcs": report["failed"] + prober.errors,
            "acceptor_rejects": deltas["nomad.keyring.auth_fail"],
            "window_exercised": (
                deltas["nomad.keyring.accept_previous"]
                + deltas["nomad.keyring.dial_fallback"]
            ) > 0,
            "window_probe_failures": window_probe_failures,
            "old_secret_rejected_after_window": old_rejected,
            "new_secret_accepted": new_accepted,
            "converged": converged,
            "invariants_ok": invariants_ok,
            "invariant_error": invariant_error,
        }
    finally:
        if prober is not None and prober._t.is_alive():
            prober.stop()
        cluster.shutdown()


# ---------------------------------------------------------------------------
# 2. Rolling server upgrade
# ---------------------------------------------------------------------------


def run_rolling_upgrade(
    data_root: str,
    *,
    seed: int = 0,
    n_servers: int = 3,
    rate: float = 30.0,
    settle_timeout_s: float = 60.0,
    max_duration_s: float = 180.0,
    step_pause_s: float = 0.75,
    node_count: int = 6,
    rpc_secret: str = "",
) -> dict:
    """Restart every server one at a time under LoadGen traffic (the
    upgrade runbook, docs/operations.md): evidence is the roll report
    (elections across the roll must be ≤ restarts + 1), the loadgen
    report, and the standard invariants (no acked write lost, no
    duplicate alloc, convergence)."""
    from ..retry import RetryPolicy

    cluster = ChaosCluster(
        n_servers, data_root, seed=seed, num_workers=1,
        rpc_secret=rpc_secret,
    )
    try:
        cluster.start()
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError("upgrade cluster never elected a leader")
        for cs in cluster.servers.values():
            # bounded leaderless-retry budget (the soak posture): a
            # submitter must measure the roll, not a 10s retry policy
            cs.forward_retry = RetryPolicy(
                base_s=0.05, max_s=0.5, deadline_s=5.0
            )
        cfg = LoadGenConfig(
            rate_eval_per_s=rate,
            duration_s=max_duration_s,
            seed=seed,
            node_count=node_count,
            node_churn_period_s=0.0,
            submitters=2,
        )
        gen = LoadGen(cluster, cfg)
        t, box = _loadgen_thread(gen)
        if not gen.setup_done.wait(timeout=60):
            raise RuntimeError("loadgen setup never finished")
        time.sleep(1.0)  # traffic in flight before the first kill

        def fix_retry(nid):
            cluster.servers[nid].forward_retry = RetryPolicy(
                base_s=0.05, max_s=0.5, deadline_s=5.0
            )

        roll = cluster.rolling_restart(
            settle_timeout_s=settle_timeout_s,
            pause_s=step_pause_s,
            post_step=fix_retry,
        )
        time.sleep(1.0)  # post-roll traffic against the rolled cluster
        gen.stop()
        report = _join_loadgen(t, box, timeout_s=120)

        converged = cluster.converged(timeout_s=60)
        cluster.acked_jobs = set(gen.acked_jobs)
        invariants_ok, invariant_error = True, ""
        try:
            cluster.check_invariants()
        except AssertionError as e:
            invariants_ok, invariant_error = False, str(e)
        return {
            "seed": seed,
            "roll": roll,
            "loadgen": report,
            "no_failed_writes": report["failed"] == 0,
            "elections_bound": roll["restarted"] + 1,
            "elections_bounded": (
                roll["elections"] <= roll["restarted"] + 1
            ),
            "converged": converged,
            "invariants_ok": invariants_ok,
            "invariant_error": invariant_error,
        }
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# 3. Spot-node churn
# ---------------------------------------------------------------------------


class _SpotFleet:
    """A fleet of mock client nodes with one shared heartbeat thread.
    Churn kills nodes two ways: ``hard`` (silent death — heartbeats
    just stop; the leader's TTL timer must notice) and ``graceful`` (a
    spot-termination notice: drain first, then die). Replacements
    register to keep the fleet at size."""

    def __init__(self, cluster, size: int, seed: int,
                 hb_period_s: float = 0.5) -> None:
        self.cluster = cluster
        self.rng = random.Random(seed ^ 0x5F0F)
        self.hb_period_s = hb_period_s
        self._lock = threading.Lock()
        self.live: dict[str, object] = {}
        # node_id -> monotonic death time (hard kills only: the
        # stranded-alloc clock starts when heartbeats STOP)
        self.dead_at: dict[str, float] = {}
        self.draining: set[str] = set()
        self.hb_errors = 0
        # alternates across ALL victims (not per-cycle) so small fleets
        # with one victim per cycle still exercise both death modes
        self._kill_toggle = 0
        self.reaped = 0
        self._stop = threading.Event()
        self._t = threading.Thread(
            target=self._hb_loop, name="spot-fleet-hb", daemon=True
        )
        self.size = size

    def _rpc(self, method: str, args):
        last: Optional[Exception] = None
        for nid in sorted(self.cluster.servers):
            cs = self.cluster.servers.get(nid)
            if cs is None:  # raced a kill
                continue
            try:
                return cs.rpc_self(method, args)
            except Exception as e:  # leaderless window: try a peer
                last = e
        if last is not None:
            raise last
        raise RuntimeError("no live servers")

    def populate(self) -> None:
        for _ in range(self.size):
            self.add_node()

    def add_node(self):
        node = mock.node()
        self._rpc("Node.register", {"node": node})
        with self._lock:
            self.live[node.id] = node
        return node

    def start(self) -> None:
        self._t.start()

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=10)

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.hb_period_s):
            with self._lock:
                ids = list(self.live)
            for node_id in ids:
                try:
                    self._rpc("Node.heartbeat", {"node_id": node_id})
                except Exception:
                    # leaderless window / raced kill: next beat retries
                    self.hb_errors += 1

    # -- churn ---------------------------------------------------------

    def kill_hard(self, node_id: str) -> None:
        """Silent spot reclaim: the node just stops heartbeating."""
        with self._lock:
            self.live.pop(node_id, None)
            self.dead_at[node_id] = time.monotonic()

    def drain_then_kill(self, node_id: str,
                        deadline_s: float = 30.0) -> None:
        """The 2-minute-notice path: mark the node draining (the
        drainer migrates its allocs) — the churn loop later reaps it
        once the drain completes (or its own next cycles do)."""
        self._rpc(
            "Node.update_drain",
            {
                "node_id": node_id,
                "drain": DrainStrategy(deadline_s=deadline_s),
            },
        )
        with self._lock:
            self.draining.add(node_id)

    def reap_drained(self) -> list:
        """Hard-kill any draining node whose drain finished (drain
        strategy cleared by the drainer's batch_node_drain_update)."""
        lead = self.cluster.leader()
        if lead is None:
            return []
        reaped = []
        with self._lock:
            draining = list(self.draining)
        for node_id in draining:
            node = lead.server.state.node_by_id(node_id)
            if node is not None and not node.drain:
                with self._lock:
                    self.draining.discard(node_id)
                self.kill_hard(node_id)
                reaped.append(node_id)
        self.reaped += len(reaped)
        return reaped

    def churn_once(self, fraction: float = 0.1) -> dict:
        """Kill ~``fraction`` of the live fleet (alternating hard and
        graceful) and register replacements."""
        with self._lock:
            candidates = [
                nid for nid in self.live if nid not in self.draining
            ]
        n = max(1, math.ceil(len(candidates) * fraction))
        victims = self.rng.sample(candidates, min(n, len(candidates)))
        hard, graceful = 0, 0
        for node_id in victims:
            self._kill_toggle += 1
            if self._kill_toggle % 2 == 1:
                self.kill_hard(node_id)
                hard += 1
            else:
                self.drain_then_kill(node_id)
                graceful += 1
        joins = 0
        with self._lock:
            deficit = self.size - len(self.live)
        for _ in range(max(0, deficit)):
            self.add_node()
            joins += 1
        return {"hard": hard, "graceful": graceful, "joins": joins}


def run_spot_churn(
    data_root: str,
    *,
    seed: int = 0,
    n_servers: int = 1,
    fleet_size: int = 10,
    churn_fraction: float = 0.1,
    cycle_s: float = 3.0,
    cycles: int = 4,
    rate: float = 25.0,
    heartbeat_ttl_s: float = 2.0,
    blocked_cap: int = 32,
    use_tpu_worker: bool = False,
    strand_grace_factor: float = 6.0,
) -> dict:
    """Spot-instance churn: every cycle ~``churn_fraction`` of the
    client fleet dies (half silently, half behind a drain notice) and
    replacements join, while LoadGen keeps submitting jobs. Gates:
    every silently-dead node is marked down and cleared of live
    allocations within ``heartbeat_ttl_s * strand_grace_factor`` of
    its death (TTL detection + one scheduling pass), the blocked-evals
    set stays bounded, and the cluster converges with the standard
    invariants once churn stops."""
    cluster = ChaosCluster(
        n_servers, data_root, seed=seed, num_workers=1,
        use_tpu_batch_worker=use_tpu_worker,
    )
    fleet: Optional[_SpotFleet] = None
    try:
        cluster.start()
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError("churn cluster never elected a leader")
        for cs in cluster.servers.values():
            # shrink the TTL floor so death→down-mark→reschedule cycles
            # fit the budget; the mechanism (leader TTL timers, armed at
            # establish-leadership AND per heartbeat) is the production
            # one
            cs.server.heartbeaters.min_ttl_s = heartbeat_ttl_s
            cs.server.blocked_evals.configure(cap=blocked_cap)

        fleet = _SpotFleet(cluster, fleet_size, seed)
        fleet.populate()
        fleet.start()

        duration_s = cycle_s * (cycles + 2)
        cfg = LoadGenConfig(
            rate_eval_per_s=rate,
            duration_s=duration_s + 60,  # stopped explicitly below
            seed=seed,
            node_count=0,  # jobs land on the fleet's nodes
            node_churn_period_s=0.0,  # the fleet IS the churn
            heartbeat_period_s=3600.0,
            submitters=2,
        )
        gen = LoadGen(cluster, cfg)
        t, box = _loadgen_thread(gen)
        if not gen.setup_done.wait(timeout=60):
            raise RuntimeError("loadgen setup never finished")

        # stranded-alloc gate, enforced LIVE: a silently-dead node must
        # be marked down and cleared of live allocations within
        # strand_bound_s of its death (TTL detection + one scheduling
        # pass) — checked every monitor tick, so a violation is a real
        # bound miss, not observation lag.
        strand_bound_s = heartbeat_ttl_s * strand_grace_factor
        stranded: list[str] = []
        detect_latency: dict[str, float] = {}

        def check_dead_nodes() -> None:
            lead = cluster.leader()
            if lead is None:
                return
            state = lead.server.state
            for node_id, died in list(fleet.dead_at.items()):
                if node_id in detect_latency or node_id in stranded:
                    continue
                node = state.node_by_id(node_id)
                cleared = (
                    node is not None
                    and node.status == "down"
                    and not any(
                        not a.terminal_status()
                        for a in state.allocs_by_node(node_id)
                    )
                )
                if cleared:
                    detect_latency[node_id] = round(
                        time.monotonic() - died, 2
                    )
                elif time.monotonic() - died > strand_bound_s:
                    stranded.append(node_id)

        churn_log = []
        max_blocked = 0
        traffic_deadline = time.monotonic() + duration_s
        next_churn = time.monotonic() + cycle_s
        while time.monotonic() < traffic_deadline:
            time.sleep(0.1)
            fleet.reap_drained()
            check_dead_nodes()
            lead = cluster.leader()
            if lead is not None:
                st = lead.server.blocked_evals.stats
                max_blocked = max(
                    max_blocked,
                    st["total_blocked"] + st["total_escaped"],
                )
            if time.monotonic() >= next_churn and cycles > len(churn_log):
                churn_log.append(fleet.churn_once(churn_fraction))
                next_churn += cycle_s
        gen.stop()
        report = _join_loadgen(t, box, timeout_s=120)
        fleet.stop()

        # settle: keep enforcing each remaining dead node's own bound
        # until every one resolves (cleared or definitively stranded);
        # the outer deadline only guards a leaderless wedge — every
        # node resolves by its own bound otherwise
        settle_deadline = time.monotonic() + strand_bound_s + 30.0
        while len(detect_latency) + len(stranded) < len(fleet.dead_at):
            check_dead_nodes()
            if time.monotonic() > settle_deadline:
                stranded.extend(
                    nid for nid in fleet.dead_at
                    if nid not in detect_latency and nid not in stranded
                )
                break
            time.sleep(0.1)

        converged = cluster.converged(timeout_s=60)
        cluster.acked_jobs = set(gen.acked_jobs)
        invariants_ok, invariant_error = True, ""
        try:
            cluster.check_invariants()
        except AssertionError as e:
            invariants_ok, invariant_error = False, str(e)
        return {
            "seed": seed,
            "loadgen": report,
            "churn_cycles": churn_log,
            "hard_kills": len(fleet.dead_at),
            "graceful_drains": sum(c["graceful"] for c in churn_log),
            "drains_completed": fleet.reaped,
            "joins": sum(c["joins"] for c in churn_log),
            "max_blocked": max_blocked,
            "blocked_cap": blocked_cap,
            "blocked_bounded": max_blocked <= blocked_cap,
            "strand_bound_s": strand_bound_s,
            "stranded_nodes": stranded,
            "down_detect_latency_s": detect_latency,
            "fleet_hb_errors": fleet.hb_errors,
            "converged": converged,
            "invariants_ok": invariants_ok,
            "invariant_error": invariant_error,
        }
    finally:
        if fleet is not None:
            fleet.stop()
        cluster.shutdown()


# ---------------------------------------------------------------------------
# 4. Solver-pool member death + leader failover with a warm pool
# ---------------------------------------------------------------------------


_POOL_COUNTERS = (
    "nomad.solver.pool.dispatched",
    "nomad.solver.pool.member_fault",
    "nomad.solver.pool.fallback_local",
    "nomad.solver.pool.aborted",
    "nomad.solver.pool.warmups",
)


def _join_pool_ring(cluster: ChaosCluster) -> None:
    """Gossip-join every live server to every other. ChaosCluster boots
    with STATIC raft peers (no server_join), so the serf ring — which
    pool membership rides — starts empty on every node; production
    agents join via server_join and never need this."""
    for nid, cs in cluster.servers.items():
        seeds = [
            c.rpc.addr for n2, c in cluster.servers.items() if n2 != nid
        ]
        if seeds:
            cs.join(seeds)


def _pool_member_stats(cluster: ChaosCluster) -> dict:
    """Each live server's own SolverPool.Status view (warmups counts
    COLD STARTS of the resident replica — the zero-warmup gate reads
    its delta across the leader kill)."""
    out = {}
    for nid, cs in cluster.servers.items():
        out[nid] = dict(cs.solver_pool.endpoint.status(None))
    return out


def run_pool_member_death(
    data_root: str,
    *,
    seed: int = 0,
    n_servers: int = 3,
    rate: float = 20.0,
    node_count: int = 6,
    max_duration_s: float = 120.0,
    member_solve_delay_s: float = 0.4,
) -> dict:
    """The solver-pool tier's two failure drills (docs/solver-pool.md),
    run back to back under live LoadGen traffic:

    1. **Pool member dies mid-solve.** The victim's ``SolverPool.Solve``
       is slowed so the kill provably lands while a dispatch is in
       flight on it; the leader must convert the dead RPC into a
       retriable DeviceFault and re-solve the SAME evals on the host
       fallback path — no acked write lost, no duplicate alloc.
    2. **Leader dies with a warm pool.** The victim is restarted and
       re-warmed first, then the leader is killed. The new leader's
       dispatch stream re-points at the surviving members' ALREADY-WARM
       replicas: the gate is zero resident-state cold starts (warmups
       delta == 0) on the survivors across the failover, while remote
       dispatches keep completing.

    Evidence dict gates: tests/test_scenarios.py assert_pool_death_ok.
    """
    base = _counter_snapshot(_POOL_COUNTERS)
    cluster = ChaosCluster(
        n_servers, data_root, seed=seed, num_workers=1,
        use_tpu_batch_worker=True, solver_pool_role="solver",
    )
    try:
        cluster.start()
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError("pool cluster never elected a leader")
        _join_pool_ring(cluster)
        cfg = LoadGenConfig(
            rate_eval_per_s=rate,
            duration_s=max_duration_s,
            seed=seed,
            node_count=node_count,
            node_churn_period_s=0.0,
            submitters=2,
        )
        gen = LoadGen(cluster, cfg)
        t, box = _loadgen_thread(gen)
        if not gen.setup_done.wait(timeout=60):
            raise RuntimeError("loadgen setup never finished")

        # traffic must actually be flowing through the pool before any
        # fault: wait for the first completed remote dispatch
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            lead = cluster.leader() or lead
            if getattr(lead, "solver_pool", None) is not None and \
                    lead.solver_pool.completed > 0:
                break
            time.sleep(0.05)
        if lead.solver_pool.completed == 0:
            raise RuntimeError("pool never completed a remote dispatch")

        # -- drill 1: kill a pool member mid-solve ----------------------
        victim_id = next(
            nid for nid, cs in cluster.servers.items() if cs is not lead
        )
        victim = cluster.servers[victim_id]
        # widen the in-flight window so the kill provably lands mid-
        # solve (instance attr shadows the class-body Solve alias)
        orig_solve = victim.solver_pool.endpoint.solve

        def slow_solve(args):
            time.sleep(member_solve_delay_s)
            return orig_solve(args)

        victim.solver_pool.endpoint.Solve = slow_solve
        pool = lead.solver_pool
        killed_mid_solve = cluster.kill_when(
            victim_id,
            lambda cs: pool._member_stats.get(victim_id, {})
            .get("in_flight", 0) > 0,
            timeout_s=30.0,
        )
        # the dead member's dispatch must resolve as a member fault and
        # the batch must re-solve locally (DeviceFault -> host fallback)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and pool.faults == 0:
            time.sleep(0.05)
        member_faults = pool.faults

        # -- drill 2: leader dies, pool stays warm ----------------------
        cluster.restart(victim_id)
        _join_pool_ring(cluster)  # fresh serf ring on the restart
        if not cluster.wait_caught_up(victim_id, timeout_s=45):
            raise RuntimeError("restarted pool member never caught up")
        # wait for the restarted member's warm loop to rebuild its
        # replica (its ONE cold start; later deltas must be zero)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            st = cluster.servers[victim_id].solver_pool.endpoint.status(None)
            if st.get("resident"):
                break
            time.sleep(0.1)
        lead = cluster.leader() or lead
        old_leader_id = lead.node_id
        pre_kill = _pool_member_stats(cluster)
        pre_dispatched = {
            nid: cs.solver_pool.completed
            for nid, cs in cluster.servers.items()
        }
        cluster.kill(old_leader_id)
        new_lead = cluster.wait_for_stable_leader(timeout_s=60)
        if new_lead is None:
            raise RuntimeError("no leader after pool leader kill")
        # the new leader must drive remote dispatches to completion on
        # the surviving warm members
        deadline = time.monotonic() + 30
        post_failover_completed = 0
        while time.monotonic() < deadline:
            post_failover_completed = (
                new_lead.solver_pool.completed
                - pre_dispatched.get(new_lead.node_id, 0)
            )
            if post_failover_completed > 0:
                break
            time.sleep(0.05)
        post_kill = _pool_member_stats(cluster)
        warmup_deltas = {
            nid: post_kill[nid].get("warmups", 0)
            - pre_kill.get(nid, {}).get("warmups", 0)
            for nid in post_kill
        }

        gen.stop()
        report = _join_loadgen(t, box, timeout_s=120)
        converged = cluster.converged(timeout_s=60)
        cluster.acked_jobs = set(gen.acked_jobs)
        invariants_ok, invariant_error = True, ""
        try:
            cluster.check_invariants()
        except AssertionError as e:
            invariants_ok, invariant_error = False, str(e)
        return {
            "seed": seed,
            "loadgen": report,
            "killed_mid_solve": killed_mid_solve,
            "member_faults": member_faults,
            "old_leader": old_leader_id,
            "new_leader": new_lead.node_id,
            "post_failover_completed": post_failover_completed,
            "warmup_deltas": warmup_deltas,
            "zero_warmup_failover": all(
                v == 0 for v in warmup_deltas.values()
            ),
            "pool_counters": _counter_delta(_POOL_COUNTERS, base),
            "converged": converged,
            "invariants_ok": invariants_ok,
            "invariant_error": invariant_error,
        }
    finally:
        cluster.shutdown()
