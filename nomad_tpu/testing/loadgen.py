"""Closed-loop load generator: sustained mixed traffic against a live
in-process cluster.

Every measurement before this was a short burst; the north star is
hours of mixed traffic from many tenants. :class:`LoadGen` drives a
live cluster (a :class:`~nomad_tpu.testing.chaos.ChaosCluster` or a
single ClusterServer) at a target eval arrival rate with a seeded mix
of job registers, scales, stops, dispatches, forced evaluations, and
node up/down churn — through the REAL front doors (``rpc_self`` →
precheck rate limits → leader forwarding → admission control), so
throttles and 429-class rejections are part of the measured loop, not
bypassed around it.

Closed-loop: the generator paces to the target rate, honors
Retry-After hints per namespace (a throttled tenant backs off exactly
as a well-behaved SDK would), records what was offered vs accepted vs
throttled, and finally drains + reads the end-to-end latency
histograms from the production metrics registry.

:func:`run_soak` is the one-call harness bench.py's ``soak`` config and
the tier-1 mini-soak test share: boot a durable ChaosCluster under a
seeded FaultPlane schedule, configure the overload knobs, run the
generator, then assert the ChaosCluster invariants (no acked write
lost, no duplicate alloc, convergence) and report shed/throttle/latency
evidence.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import clusterobs, metrics
from ..ratelimit import RateLimitError, is_throttle_text, retry_after_from_text
from ..rpc.client import RPCError
from ..server.raft_replication import NotLeaderError
from ..structs.structs import Namespace
from .. import mock

logger = logging.getLogger("nomad_tpu.loadgen")

# counters whose deltas the report captures
_COUNTERS = (
    "nomad.broker.shed",
    "nomad.broker.rejected",
    "nomad.http.throttled",
    "nomad.rpc.throttled",
    "nomad.worker.backpressure_throttled",
    "nomad.blocked_evals.deduped",
    "nomad.blocked_evals.evicted",
)


@dataclass
class LoadGenConfig:
    rate_eval_per_s: float = 50.0
    duration_s: float = 10.0
    seed: int = 0
    namespaces: tuple = ("default", "tenant-a", "tenant-b")
    node_count: int = 10
    group_count: int = 2  # allocs per registered job
    max_live_jobs: int = 40  # per namespace; stops recycle beyond this
    node_churn_period_s: float = 4.0  # 0 = no churn
    dispatch: bool = True
    heartbeat_period_s: float = 3.0
    drain_timeout_s: float = 30.0
    # parallel submitter threads: each front-door write blocks on a
    # raft commit (~tens of ms), so a single closed loop tops out far
    # below real arrival rates — N submitters share one paced budget
    submitters: int = 4
    # scheduled one-shot events: (offset_s, fn) — run_soak uses these
    # for partition/heal cycles
    events: list = field(default_factory=list)


@dataclass
class _Counts:
    offered: int = 0
    accepted: int = 0
    throttled: int = 0
    churn_errors: int = 0
    failed: int = 0


class LoadGen:
    def __init__(self, cluster, cfg: LoadGenConfig) -> None:
        """cluster — a ChaosCluster (drives a live member, leader-
        forwarded) or any object with ``rpc_self``/``server``."""
        self.cluster = cluster
        self.cfg = cfg
        self.rng = random.Random(cfg.seed)
        # one lock covers the rng, the counts, the live-job lists, the
        # pacing clock, and the per-namespace backoffs — submitter
        # threads hold it only to plan/commit an op, never across the
        # RPC itself
        self._lock = threading.Lock()
        self.counts = _Counts()
        # jobs this generator registered AND saw acked, minus acked
        # stops — the no-acked-write-lost invariant set
        self.acked_jobs: set[str] = set()
        self._live: dict[str, list] = {ns: [] for ns in cfg.namespaces}
        self._param_jobs: dict[str, str] = {}
        self._nodes: list = []
        self._nodes_down: set[str] = set()
        self._ns_backoff: dict[str, float] = {}
        self._seq = 0
        # scenario hooks: set once run() finished registering its
        # namespaces/nodes (scripted operations start against a warmed
        # cluster), and stop() for drivers whose scripted operation
        # finishes before duration_s elapses
        self.setup_done = threading.Event()

    def stop(self) -> None:
        """End the traffic phase now (drain + report still run): the
        production-ops scenarios call this once their scripted
        operation — a secret rotation, a completed roll — is done."""
        self._traffic_deadline = time.monotonic()

    # -- cluster access -------------------------------------------------

    def _driver(self):
        """A live server to submit through (its endpoints forward to
        the leader and retry leaderless windows internally)."""
        servers = getattr(self.cluster, "servers", None)
        if servers:
            # prefer the lowest id: run_soak keeps it in the majority
            # side of any scripted partition
            for nid in sorted(servers):
                return servers[nid]
            raise RuntimeError("no live servers")
        return self.cluster

    def _rpc(self, method: str, args) -> object:
        return self._driver().rpc_self(method, args)

    # -- setup ----------------------------------------------------------

    def _retrying(self, fn, attempts: int = 20, what: str = "setup"):
        """Setup-time writes ride through churn/throttles with patience
        (the measured loop instead COUNTS those outcomes)."""
        last = None
        for _ in range(attempts):
            try:
                return fn()
            except RateLimitError as e:
                last = e
                time.sleep(min(2.0, e.retry_after_s or 0.25))
            except Exception as e:  # leaderless windows, injected drops
                last = e
                time.sleep(0.25)
        raise RuntimeError(f"loadgen {what} failed: {last}")

    def setup(self) -> None:
        cfg = self.cfg
        for ns in cfg.namespaces:
            if ns == "default":
                continue
            self._retrying(
                lambda ns=ns: self._rpc(
                    "Namespace.upsert", {"namespace": Namespace(name=ns)}
                ),
                what=f"namespace {ns}",
            )
        for i in range(cfg.node_count):
            node = mock.node()
            self._retrying(
                lambda n=node: self._rpc("Node.register", {"node": n}),
                what=f"node {i}",
            )
            self._nodes.append(node)
        if cfg.dispatch:
            from ..structs.structs import ParameterizedJobConfig

            for ns in cfg.namespaces:
                j = self._new_job(ns)
                j.type = "batch"
                j.parameterized = ParameterizedJobConfig(payload="optional")
                self._retrying(
                    lambda j=j: self._rpc("Job.register", {"job": j}),
                    what=f"param job {ns}",
                )
                self._param_jobs[ns] = j.id

    def _new_job(self, ns: str):
        self._seq += 1
        j = mock.job(id=f"load-{ns}-{self._seq}")
        j.namespace = ns
        tg = j.task_groups[0]
        tg.count = self.cfg.group_count
        tg.tasks[0].resources.cpu = 50
        tg.tasks[0].resources.memory_mb = 32
        tg.tasks[0].resources.networks = []
        return j

    # -- the traffic loop ----------------------------------------------

    def _pick_ns(self, now: float) -> Optional[str]:
        with self._lock:
            ready = [
                ns
                for ns in self.cfg.namespaces
                if self._ns_backoff.get(ns, 0.0) <= now
            ]
            return self.rng.choice(ready) if ready else None

    def _one_op(self, ns: str) -> None:
        """One eval-minting write through the front door: plan + reserve
        under the lock, the RPC itself outside it, bookkeeping back
        under it. Raises on throttle (caller counts + backs off the
        namespace)."""
        with self._lock:
            live = self._live[ns]
            r = self.rng.random()
            if (r < 0.40 and len(live) < self.cfg.max_live_jobs) or not live:
                kind, job = "register", self._new_job(ns)
            elif r < 0.40:
                # at the live-jobs cap: recycle by stopping the oldest
                kind, job = "stop", live.pop(0)
            elif r < 0.70:
                kind, job = "scale", self.rng.choice(live)
                count = self.rng.randint(1, max(2, self.cfg.group_count * 2))
            elif r < 0.80:
                kind, job = "stop", live.pop(self.rng.randrange(len(live)))
            elif r < 0.90 and self._param_jobs.get(ns):
                kind, job = "dispatch", None
            else:
                kind, job = "evaluate", self.rng.choice(live)
            if kind == "stop":
                # ambiguous-outcome safety: a stop may APPLY even when
                # its response is lost (injected serve.drop, partition
                # after delivery) — stop asserting this job's liveness
                # BEFORE the RPC, or the no-acked-write-lost invariant
                # would flag a write that in fact landed
                self.acked_jobs.discard(job.id)
        if kind == "register":
            self._rpc("Job.register", {"job": job})
            with self._lock:
                live.append(job)
                self.acked_jobs.add(job.id)
        elif kind == "scale":
            self._rpc(
                "Job.scale",
                {
                    "namespace": ns,
                    "job_id": job.id,
                    "group": job.task_groups[0].name,
                    "count": count,
                    "message": "loadgen",
                },
            )
        elif kind == "stop":
            self._rpc(
                "Job.deregister",
                {"namespace": ns, "job_id": job.id, "purge": False},
            )
        elif kind == "dispatch":
            self._rpc(
                "Job.dispatch",
                {
                    "namespace": ns,
                    "job_id": self._param_jobs[ns],
                    "meta": {},
                    "payload": b"",
                },
            )
        else:
            self._rpc(
                "Job.evaluate", {"namespace": ns, "job_id": job.id}
            )

    def _churn_node(self) -> None:
        """Flip one node down/up through the real status endpoint: the
        FSM side channels mint node-update evals and capacity-change
        unblocks — the storm the blocked-evals containment must absorb."""
        if not self._nodes:
            return
        with self._lock:
            node = self.rng.choice(self._nodes)
        try:
            if node.id in self._nodes_down:
                self._rpc(
                    "Node.update_status",
                    {"node_id": node.id, "status": "ready"},
                )
                self._nodes_down.discard(node.id)
            else:
                self._rpc(
                    "Node.update_status",
                    {"node_id": node.id, "status": "down"},
                )
                self._nodes_down.add(node.id)
        except Exception:
            self.counts.churn_errors += 1

    def _heartbeats(self) -> None:
        for node in self._nodes:
            if node.id in self._nodes_down:
                continue
            try:
                self._rpc("Node.heartbeat", {"node_id": node.id})
            except Exception:
                self.counts.churn_errors += 1

    def _claim_slot(self) -> float:
        """Shared pacing budget across submitters: 0.0 = send now, else
        seconds to wait before re-checking. Catch-up is capped at one
        interval — a stall is never answered with an unbounded burst."""
        now = time.monotonic()
        with self._lock:
            if now < self._next_send:
                return min(0.01, self._next_send - now)
            self._next_send = max(
                self._next_send + self._interval, now - self._interval
            )
            return 0.0

    def _submit_loop(self) -> None:
        while True:
            now = time.monotonic()
            if now >= self._traffic_deadline:
                return
            wait = self._claim_slot()
            if wait > 0:
                time.sleep(wait)
                continue
            ns = self._pick_ns(now)
            if ns is None:
                time.sleep(0.005)
                continue  # every namespace told to back off
            with self._lock:
                self.counts.offered += 1
            try:
                self._one_op(ns)
                with self._lock:
                    self.counts.accepted += 1
            except RateLimitError as e:
                with self._lock:
                    self.counts.throttled += 1
                    self._ns_backoff[ns] = now + min(
                        5.0, e.retry_after_s or 0.5
                    )
            except NotLeaderError:
                # locally-raised churn (LeadershipLostError included):
                # the driver was/lost the leader mid-write — real
                # overload induces elections; count and carry on
                with self._lock:
                    self.counts.churn_errors += 1
            except RPCError as e:
                text = str(e)
                with self._lock:
                    if is_throttle_text(text):
                        self.counts.throttled += 1
                        self._ns_backoff[ns] = now + min(
                            5.0, retry_after_from_text(text) or 0.5
                        )
                    elif (
                        "NotLeaderError" in text
                        or "LeadershipLostError" in text
                        or "no cluster leader" in text
                    ):
                        # LeadershipLostError is outcome-UNKNOWN (the
                        # write may still commit), so it is never acked
                        # here — but it is leadership churn, not a
                        # dropped request: the rolling-upgrade scenario
                        # gates `failed` at zero while kills are
                        # in-flight, and only churn may say otherwise
                        self.counts.churn_errors += 1
                    else:
                        # includes KeyError-not-found: a scale/evaluate
                        # raced a stop/GC of its job
                        self.counts.failed += 1
            except (ConnectionError, TimeoutError, OSError):
                with self._lock:
                    self.counts.churn_errors += 1
            except (KeyError, ValueError, LookupError):
                with self._lock:
                    self.counts.failed += 1

    def run(self) -> dict:
        cfg = self.cfg
        base = {
            name: metrics.snapshot()["counters"].get(name, 0)
            for name in _COUNTERS
        }
        e2e_base = (
            metrics.snapshot()["samples"]
            .get("nomad.eval.e2e_seconds", {})
            .get("count", 0)
        )
        self._interval = 1.0 / max(0.01, cfg.rate_eval_per_s)
        # a scenario's stop() between setup and the loop must stick: only
        # push the deadline out, never overwrite an earlier one
        self._traffic_deadline = float("inf")
        self.setup()
        start = time.monotonic()
        self._next_send = start
        self._traffic_deadline = min(
            self._traffic_deadline, start + cfg.duration_s
        )
        self.setup_done.set()
        threads = [
            threading.Thread(
                target=self._submit_loop,
                name=f"loadgen-{i}",
                daemon=True,
            )
            for i in range(max(1, cfg.submitters))
        ]
        for t in threads:
            t.start()
        # the main thread owns the background traffic: heartbeats, node
        # churn, and the scripted fault-schedule events
        next_hb = start + cfg.heartbeat_period_s
        next_churn = (
            start + cfg.node_churn_period_s
            if cfg.node_churn_period_s > 0
            else float("inf")
        )
        events = sorted(cfg.events, key=lambda e: e[0])
        ei = 0
        while True:
            now = time.monotonic()
            if now >= self._traffic_deadline:
                break
            while ei < len(events) and now - start >= events[ei][0]:
                try:
                    events[ei][1]()
                except Exception:
                    logger.exception("loadgen scheduled event failed")
                ei += 1
            if now >= next_hb:
                self._heartbeats()
                next_hb = now + cfg.heartbeat_period_s
            if now >= next_churn:
                self._churn_node()
                next_churn = now + cfg.node_churn_period_s
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=30)
        wall = time.monotonic() - start
        drained = self._wait_drain()
        return self._report(base, e2e_base, wall, drained)

    def _wait_drain(self) -> bool:
        """Wait for the broker to finish (or shed) everything offered —
        bounded; an overloaded-but-degrading-gracefully cluster drains
        once arrivals stop."""
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        while time.monotonic() < deadline:
            try:
                srv = self._driver().server
                if (
                    srv.eval_broker.pending_count() == 0
                    and srv.eval_broker.inflight_count() == 0
                ):
                    return True
            except Exception:
                pass
            time.sleep(0.1)
        return False

    def _report(self, base: dict, e2e_base: int, wall: float,
                drained: bool) -> dict:
        snap = metrics.snapshot()
        counters = {
            name: snap["counters"].get(name, 0) - base[name]
            for name in _COUNTERS
        }
        e2e = snap["samples"].get("nomad.eval.e2e_seconds") or {}
        report = {
            "duration_s": round(wall, 2),
            "offered": self.counts.offered,
            "accepted": self.counts.accepted,
            "throttled_client_visible": self.counts.throttled,
            "churn_errors": self.counts.churn_errors,
            "failed": self.counts.failed,
            "offered_rate_per_s": round(self.counts.offered / wall, 2)
            if wall > 0
            else 0.0,
            "accepted_rate_per_s": round(self.counts.accepted / wall, 2)
            if wall > 0
            else 0.0,
            "drained": drained,
            "counters": counters,
            "evals_completed": int(e2e.get("count", 0)) - int(e2e_base),
        }
        if e2e.get("count"):
            report["e2e_seconds"] = {
                "p50": round(e2e["p50"], 4),
                "p95": round(e2e["p95"], 4),
                "p99": round(e2e["p99"], 4),
                "max": round(e2e["max"], 4),
            }
        return report


# ---------------------------------------------------------------------------
# The soak harness: ChaosCluster + seeded fault schedule + LoadGen +
# invariants. Shared by bench.py's `soak` config and the tier-1 mini-soak.
# ---------------------------------------------------------------------------


def run_soak(
    data_root: str,
    *,
    duration_s: float = 20.0,
    rate: float = 100.0,
    seed: int = 42,
    n_servers: int = 3,
    admission_depth: int = 64,
    namespace_cap: int = 32,
    blocked_cap: int = 64,
    nack_delay_s: float = 1.0,
    rpc_rate: float = 0.0,
    rpc_burst: float = 0.0,
    use_tpu_worker: bool = False,
    faults: bool = True,
    partition_cycle: bool = False,
    node_count: int = 10,
    p99_bound_s: float = 15.0,
    loadgen_overrides: Optional[dict] = None,
) -> dict:
    """Boot a durable in-process cluster under a seeded fault schedule,
    drive it with closed-loop mixed traffic, and return the evidence
    dict (loadgen report + invariant verdicts + gate inputs)."""
    from .chaos import ChaosCluster

    cluster = ChaosCluster(
        n_servers,
        data_root,
        seed=seed,
        num_workers=1,
        use_tpu_batch_worker=use_tpu_worker,
    )

    def seed_background_faults() -> None:
        if not faults or cluster.plane is None:
            return
        # low-probability background noise for the whole run: dropped
        # calls ride the pool's redial/forwarder retries; lost responses
        # exercise at-most-once; slow fsync exercises backpressure. One
        # seed fixes the whole schedule (faultplane.py draw order).
        cluster.plane.drop_rpc(prob=0.01)
        cluster.plane.drop_response(prob=0.004)
        cluster.plane.slow_disk(0.01, prob=0.02)
        if use_tpu_worker:
            cluster.plane.fail_device(prob=0.02, retriable=True)

    # Server-CPU-per-node measurement (ROADMAP "bounded server-CPU-per-
    # node" gate): a PRIVATE host profiler instance samples every thread
    # for the traffic window and the per-role busy split separates
    # server-side roles (rpc/raft/worker/applier/...) from the
    # generator's own loadgen/main threads. Fresh instance — never the
    # process-global one a co-resident Agent may be running.
    from .. import hostobs

    # NOMAD_TPU_SOAK_PROFILE=0 turns the measurement apparatus off
    # (role attribution degrades to empty; the gated CPU stat is
    # process_time and survives) — also the A/B knob for isolating
    # profiler-load effects on race-timing-sensitive soaks.
    profile_on = os.environ.get("NOMAD_TPU_SOAK_PROFILE", "1") != "0"
    prof = hostobs.HostProfiler(interval_s=0.01, idle_interval_s=0.02)
    try:
        # both starts INSIDE the try: a boot failure (port bind, raft
        # store) must still tear the sampler thread + its gc hooks and
        # provider down in the finally (prof.stop is a safe no-op when
        # start never ran)
        if profile_on:
            prof.start()
        cluster.start()
        lead = cluster.wait_for_stable_leader(timeout_s=60)
        if lead is None:
            raise RuntimeError("soak cluster never elected a leader")
        from ..retry import RetryPolicy

        for cs in cluster.servers.values():
            # tighter leaderless-retry budget than production: a soak
            # submitter stuck 10s in a forwarder retry measures the
            # retry policy, not the control plane — 3s bounds the tail
            # while still riding out a normal election
            cs.forward_retry = RetryPolicy(
                base_s=0.05, max_s=0.5, deadline_s=3.0
            )
            cs.server.eval_broker.configure(
                nack_delay_s=nack_delay_s,
                admission_depth=admission_depth,
                namespace_cap=namespace_cap,
            )
            cs.server.blocked_evals.configure(cap=blocked_cap)
            if rpc_rate > 0:
                cs.set_rate_limits(rpc_rate, rpc_burst)
        seed_background_faults()

        events = []
        if partition_cycle and n_servers >= 3 and faults:
            ids = sorted(cluster.addrs)
            minority, majority = [ids[-1]], ids[:-1]

            def cut():
                cluster.plane.partition(minority, majority)

            def heal():
                # heal() drops every rpc.drop rule, the background
                # noise included — re-seed it after the cut ends
                cluster.heal("rpc.drop")
                if faults:
                    cluster.plane.drop_rpc(prob=0.01)

            third = duration_s / 3.0
            events = [(third, cut), (third + min(2.0, third / 2), heal)]

        cfg = LoadGenConfig(
            rate_eval_per_s=rate,
            duration_s=duration_s,
            seed=seed,
            node_count=node_count,
            events=events,
        )
        for k, v in (loadgen_overrides or {}).items():
            setattr(cfg, k, v)
        gen = LoadGen(cluster, cfg)
        prof.reset_stats()  # exclude cluster boot from the CPU window
        cpu_t0 = time.process_time()
        report = gen.run()
        cpu_delta = time.process_time() - cpu_t0
        prof_snap = prof.snapshot(top=1)

        # per-source attribution coverage across every member's ledger
        # (clusterobs.py): how much of the served handler seconds were
        # billed to a KNOWN node/peer/namespace
        src_total_calls = 0
        src_total_s = 0.0
        src_unattr_s = 0.0
        src_evicted = 0
        src_rows: list[dict] = []
        for cs in cluster.servers.values():
            snap = cs.source_ledger.snapshot(top=10)
            src_total_calls += snap["total_calls"]
            src_total_s += snap["total_seconds"]
            src_unattr_s += snap["unattributed_seconds"]
            src_evicted += snap["evicted"]
            src_rows.extend(snap["top"])

        # quiesce: stop injecting, let the cluster converge, then hold
        # it to the standard invariants
        cluster.heal()
        converged = cluster.converged(timeout_s=60)
        cluster.acked_jobs = set(gen.acked_jobs)
        invariants_ok = True
        invariant_error = ""
        try:
            cluster.check_invariants()
        except AssertionError as e:
            invariants_ok = False
            invariant_error = str(e)

        # Server CPU x node attribution. The GATED stat is real process
        # CPU time over the traffic window (time.process_time sums every
        # thread's actual CPU): one process hosts the whole control
        # plane here, so this is the fleet's server cost — an UPPER
        # bound, since the in-process generator's own threads count too.
        # The profiler role table rides along as the attribution view;
        # its numbers are busy WALL (a thread parked in a C call —
        # time.sleep, a device wait — samples busy at the calling
        # frame, the documented hostobs conflation), so they apportion
        # cost by role but must never be summed as CPU.
        roles = prof_snap.get("threads") or {}
        client_roles = {"loadgen", "main"}
        server_busy_s = sum(
            r["busy_seconds"]
            for name, r in roles.items()
            if name not in client_roles
        )
        client_busy_s = sum(
            r["busy_seconds"]
            for name, r in roles.items()
            if name in client_roles
        )
        wall = max(report.get("duration_s") or 0.0, 1e-9)
        nodes = max(int(cfg.node_count), 1)
        report["server_cpu"] = {
            "cpu_seconds": round(cpu_delta, 3),
            "per_node_cpu_seconds": round(cpu_delta / nodes, 4),
            # cores-per-node over the traffic window: the number the
            # fleet-scale gate bounds (ROADMAP item 4)
            "per_node_cpu_fraction": round(
                cpu_delta / wall / nodes, 5
            ),
            "node_count": nodes,
            "server_busy_wall_seconds": round(server_busy_s, 3),
            "client_busy_wall_seconds": round(client_busy_s, 3),
            "busy_wall_by_role": {
                name: round(r["busy_seconds"], 3)
                for name, r in sorted(roles.items())
            },
        }
        report["server_cpu_per_node"] = report["server_cpu"][
            "per_node_cpu_seconds"
        ]
        report["source_attribution"] = {
            "total_calls": src_total_calls,
            "total_seconds": round(src_total_s, 4),
            "unattributed_seconds": round(src_unattr_s, 4),
            "evicted": src_evicted,
            "coverage": round(
                1.0 - src_unattr_s / max(src_total_s, 1e-12), 4
            )
            if src_total_calls
            else 1.0,
            "top": clusterobs.merge_top_sources(src_rows, top=5),
        }
        counters = report["counters"]
        admission_engaged = (
            counters["nomad.broker.shed"]
            + counters["nomad.broker.rejected"]
            + counters["nomad.http.throttled"]
            + counters["nomad.rpc.throttled"]
        ) > 0
        p99 = (report.get("e2e_seconds") or {}).get("p99")
        report.update(
            {
                "seed": seed,
                "fault_schedule": bool(faults),
                "fired_faults": dict(cluster.plane.fired)
                if cluster.plane is not None
                else {},
                "converged": converged,
                "invariants_ok": invariants_ok,
                "invariant_error": invariant_error,
                "admission_engaged": admission_engaged,
                "p99_bound_s": p99_bound_s,
                "p99_bounded": p99 is not None and p99 <= p99_bound_s,
            }
        )
        return report
    finally:
        cluster.shutdown()
        if profile_on:
            prof.stop()
        # the private profiler's provider must not outlive the run (it
        # would shadow a co-resident Agent's global profiler under the
        # same "nomad.host" name — provider stacks are newest-wins)
        if prof._provider_handle is not None:
            metrics.unregister_provider(
                "nomad.host", prof._provider_handle
            )
