"""Lock-order race detection.

Reference intent: SURVEY §5.2 — the reference's CI runs `go test -race`;
CPython has no data-race sanitizer, but the failure mode that actually
bites a lock-disciplined Python codebase is LOCK-ORDER INVERSION
(thread A holds L1 wanting L2 while thread B holds L2 wanting L1 —
a deadlock waiting for load). This module is the repo's -race analog:

  * ``install()`` replaces ``threading.Lock``/``threading.RLock`` with
    tracked factories. Every lock is keyed by its ALLOCATION SITE
    (file:line), so instances group into lock classes the way lock-order
    checkers conventionally do.
  * Each acquisition records held-before edges class→class. A cycle in
    that graph is a potential deadlock; the offending edge is recorded
    with both stacks.
  * ``violations()`` returns what was found; ``uninstall()`` restores
    the real primitives.

Enabled in CI via ``NOMAD_RACECHECK=1`` (tests/test_racecheck.py runs a
full server+client exercise under it in a subprocess); production code
never imports this module.
"""

from __future__ import annotations

import os
import threading
import traceback

_real_lock = threading.Lock
_real_rlock = threading.RLock

_state_lock = _real_lock()
_edges: dict[tuple[str, str], str] = {}  # (from_class, to_class) -> stack
_violations: list[dict] = []
_holding = threading.local()
_installed = False


def _alloc_site() -> str:
    # first frame outside THIS module (exact path — a substring match
    # would skip a caller merely named *racecheck*) and threading.py
    for frame in reversed(traceback.extract_stack()[:-2]):
        fn = frame.filename
        if fn == __file__ or fn.endswith("threading.py"):
            continue
        return f"{fn}:{frame.lineno}"
    return "unknown"


def _held() -> list[str]:
    if not hasattr(_holding, "stack"):
        _holding.stack = []
    return _holding.stack


def _reachable(graph: dict, start: str, goal: str) -> bool:
    seen = set()
    work = [start]
    while work:
        cur = work.pop()
        if cur == goal:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        work.extend(b for (a, b) in graph if a == cur)
    return False


def _record_acquire(cls: str) -> None:
    held = _held()
    for prior in held:
        if prior == cls:
            continue  # same class (e.g. two store instances) — skip,
            # intra-class ordering needs instance identity to be sound
        edge = (prior, cls)
        with _state_lock:
            if edge in _edges:
                continue
            # would cls→...→prior + prior→cls close a cycle?
            if _reachable(_edges, cls, prior):
                _violations.append({
                    "classes": (prior, cls),
                    "stack": "".join(traceback.format_stack(limit=12)),
                    "first_seen": _edges.get((cls, prior), ""),
                })
            _edges[edge] = "".join(traceback.format_stack(limit=12))
    held.append(cls)


def _record_release(cls: str) -> None:
    held = _held()
    # remove the most recent matching entry (locks are not always
    # released LIFO; Python allows it)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == cls:
            del held[i]
            return


class _TrackedLock:
    """Wraps a real lock; tracks acquisition order by allocation-site
    class. Condition's wait-path hooks (_release_save /
    _acquire_restore / _is_owned) are implemented EXPLICITLY: the old
    __getattr__ delegation handed Condition the raw RLock's hooks, so
    a cv.wait() released the lock without the held-stack noticing —
    every lock acquired while parked recorded a phantom held-before
    edge from a lock nobody held, and the post-wait reacquire was
    invisible. Now wait release/reacquire update the stack like any
    other release/acquire (recursion count included for RLocks)."""

    def __init__(self, underlying) -> None:
        self._lock = underlying
        self._cls = _alloc_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _record_acquire(self._cls)
        return ok

    def release(self) -> None:
        self._lock.release()
        _record_release(self._cls)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # -- Condition integration (threading.Condition probes these on
    #    construction; RLock state is (count, owner)) ---------------

    def _release_save(self):
        inner = getattr(self._lock, "_release_save", None)
        if inner is not None:  # RLock: full release, any recursion depth
            state = inner()
            n = state[0] if isinstance(state, tuple) and state and \
                isinstance(state[0], int) else 1
        else:  # plain Lock
            self._lock.release()
            state, n = None, 1
        for _ in range(n):
            _record_release(self._cls)
        return state

    def _acquire_restore(self, state):
        inner = getattr(self._lock, "_acquire_restore", None)
        if inner is not None:
            inner(state)
            n = state[0] if isinstance(state, tuple) and state and \
                isinstance(state[0], int) else 1
        else:
            self._lock.acquire()
            n = 1
        for _ in range(n):
            _record_acquire(self._cls)

    def _is_owned(self):
        inner = getattr(self._lock, "_is_owned", None)
        if inner is not None:
            return inner()
        # plain Lock: the Condition default's probe, minus tracking
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __getattr__(self, name):
        return getattr(self._lock, name)


def install() -> None:
    global _installed
    if _installed:
        return
    threading.Lock = lambda: _TrackedLock(_real_lock())  # type: ignore
    threading.RLock = lambda: _TrackedLock(_real_rlock())  # type: ignore
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _real_lock  # type: ignore
    threading.RLock = _real_rlock  # type: ignore
    _installed = False


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _violations.clear()


def violations() -> list[dict]:
    with _state_lock:
        return list(_violations)


_REPO_ROOT = __file__
for _ in range(3):  # nomad_tpu/testing/racecheck.py -> repo root
    _REPO_ROOT = os.path.dirname(_REPO_ROOT)


def _rel(cls: str) -> str:
    """Normalize an allocation-site class to the repo-relative form the
    static analyzer (nomad_tpu/analysis) keys its locks by, so dynamic
    and static edge sets cross-check with plain equality."""
    path, _, line = cls.rpartition(":")
    if path.startswith(_REPO_ROOT + os.sep) or path.startswith(
        _REPO_ROOT + "/"
    ):
        path = path[len(_REPO_ROOT):].lstrip("/\\").replace("\\", "/")
    return f"{path}:{line}"


def edges() -> list[dict]:
    """The observed held-before edge set in a stable JSON form:
    [{"from": "<relpath:line>", "to": "<relpath:line>"}, ...], sorted,
    repo-relative — the ground truth nomad-vet's NV-lock-order
    cross-check consumes (`operator vet -dynamic-edges`)."""
    with _state_lock:
        pairs = sorted(_edges)
    return [{"from": _rel(a), "to": _rel(b)} for a, b in pairs]


def export_json() -> dict:
    """{"edges": [...], "violations": [...]} with repo-relative class
    keys and both stacks per violation — json.dump-able as-is."""
    return {
        "edges": edges(),
        "violations": [
            {
                "from": _rel(v["classes"][0]),
                "to": _rel(v["classes"][1]),
                "stack": v["stack"],
                "first_seen": v["first_seen"],
            }
            for v in violations()
        ],
    }


def report() -> str:
    out = []
    for v in violations():
        a, b = v["classes"]
        out.append(
            f"LOCK-ORDER INVERSION: {a} -> {b} conflicts with an "
            f"existing {b} -> {a} ordering\n--- second acquisition "
            f"stack ---\n{v['stack']}\n--- first ordering stack ---\n"
            f"{v['first_seen']}"
        )
    return "\n\n".join(out)
