"""Lock-order race detection.

Reference intent: SURVEY §5.2 — the reference's CI runs `go test -race`;
CPython has no data-race sanitizer, but the failure mode that actually
bites a lock-disciplined Python codebase is LOCK-ORDER INVERSION
(thread A holds L1 wanting L2 while thread B holds L2 wanting L1 —
a deadlock waiting for load). This module is the repo's -race analog:

  * ``install()`` replaces ``threading.Lock``/``threading.RLock`` with
    tracked factories. Every lock is keyed by its ALLOCATION SITE
    (file:line), so instances group into lock classes the way lock-order
    checkers conventionally do.
  * Each acquisition records held-before edges class→class. A cycle in
    that graph is a potential deadlock; the offending edge is recorded
    with both stacks.
  * ``violations()`` returns what was found; ``uninstall()`` restores
    the real primitives.

Enabled in CI via ``NOMAD_RACECHECK=1`` (tests/test_racecheck.py runs a
full server+client exercise under it in a subprocess); production code
never imports this module.
"""

from __future__ import annotations

import threading
import traceback

_real_lock = threading.Lock
_real_rlock = threading.RLock

_state_lock = _real_lock()
_edges: dict[tuple[str, str], str] = {}  # (from_class, to_class) -> stack
_violations: list[dict] = []
_holding = threading.local()
_installed = False


def _alloc_site() -> str:
    # first frame outside THIS module (exact path — a substring match
    # would skip a caller merely named *racecheck*) and threading.py
    for frame in reversed(traceback.extract_stack()[:-2]):
        fn = frame.filename
        if fn == __file__ or fn.endswith("threading.py"):
            continue
        return f"{fn}:{frame.lineno}"
    return "unknown"


def _held() -> list[str]:
    if not hasattr(_holding, "stack"):
        _holding.stack = []
    return _holding.stack


def _reachable(graph: dict, start: str, goal: str) -> bool:
    seen = set()
    work = [start]
    while work:
        cur = work.pop()
        if cur == goal:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        work.extend(b for (a, b) in graph if a == cur)
    return False


def _record_acquire(cls: str) -> None:
    held = _held()
    for prior in held:
        if prior == cls:
            continue  # same class (e.g. two store instances) — skip,
            # intra-class ordering needs instance identity to be sound
        edge = (prior, cls)
        with _state_lock:
            if edge in _edges:
                continue
            # would cls→...→prior + prior→cls close a cycle?
            if _reachable(_edges, cls, prior):
                _violations.append({
                    "classes": (prior, cls),
                    "stack": "".join(traceback.format_stack(limit=12)),
                    "first_seen": _edges.get((cls, prior), ""),
                })
            _edges[edge] = "".join(traceback.format_stack(limit=12))
    held.append(cls)


def _record_release(cls: str) -> None:
    held = _held()
    # remove the most recent matching entry (locks are not always
    # released LIFO; Python allows it)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == cls:
            del held[i]
            return


class _TrackedLock:
    """Wraps a real lock; tracks acquisition order by allocation-site
    class. Unknown attributes delegate to the underlying primitive so
    Condition's _release_save/_is_owned paths keep working (those
    bypass tracking, which only costs coverage, not correctness)."""

    def __init__(self, underlying) -> None:
        self._lock = underlying
        self._cls = _alloc_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _record_acquire(self._cls)
        return ok

    def release(self) -> None:
        self._lock.release()
        _record_release(self._cls)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __getattr__(self, name):
        return getattr(self._lock, name)


def install() -> None:
    global _installed
    if _installed:
        return
    threading.Lock = lambda: _TrackedLock(_real_lock())  # type: ignore
    threading.RLock = lambda: _TrackedLock(_real_rlock())  # type: ignore
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _real_lock  # type: ignore
    threading.RLock = _real_rlock  # type: ignore
    _installed = False


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _violations.clear()


def violations() -> list[dict]:
    with _state_lock:
        return list(_violations)


def report() -> str:
    out = []
    for v in violations():
        a, b = v["classes"]
        out.append(
            f"LOCK-ORDER INVERSION: {a} -> {b} conflicts with an "
            f"existing {b} -> {a} ordering\n--- second acquisition "
            f"stack ---\n{v['stack']}\n--- first ordering stack ---\n"
            f"{v['first_seen']}"
        )
    return "\n\n".join(out)
