"""Token-bucket rate limiting for the HTTP and RPC front doors.

Production-side stdlib leaf (like metrics/trace/faultplane): the HTTP
layer (agent/http.py) and the RPC precheck (server/cluster.py) each own
a :class:`KeyedRateLimiter` bucketed per namespace, so one namespace's
burst cannot starve the others — the reference's rate-limiting posture
(nomad limits stanza + go rate.Limiter per endpoint) in per-namespace
form. Throttled callers get :class:`RateLimitError` carrying a
``retry_after_s`` hint; the HTTP layer turns it into 429 + Retry-After,
and the shared RetryPolicy (retry.py) honors the hint as a backoff
floor when the caller opts into retrying.

:class:`BrokerSaturatedError` is the queue-full sibling: raised by the
leader's eval-minting write endpoints when the eval broker's admission
depth is exhausted (server.py check_eval_admission). Subclassing
RateLimitError means every 429 mapping and retry classification handles
both with one clause.

All limiter state is monotonic-clock based and reconfigurable in place
(SIGHUP reload swaps rates without dropping bucket state).
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class RateLimitError(Exception):
    """Request rejected by a front-door rate limit. ``retry_after_s``
    is the caller's backoff hint (HTTP Retry-After; retry.py floor).
    The hint is embedded in the message too, so the error survives the
    RPC fabric's string serialization and the far side can re-parse it
    (see :func:`retry_after_from_text`)."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        self.retry_after_s = max(0.0, float(retry_after_s))
        super().__init__(f"{message} (retry_after={self.retry_after_s:.3f}s)")


class BrokerSaturatedError(RateLimitError):
    """The eval broker's admission depth (or a namespace's fairness cap)
    is exhausted: the write was rejected BEFORE minting an eval, so the
    caller can safely retry after the hint."""


def retry_after_from_text(text: str) -> Optional[float]:
    """Recover the retry_after hint from a stringified RateLimitError
    (an ``RPCError`` travelling back from the leader). None when the
    text carries no hint."""
    marker = "retry_after="
    i = text.find(marker)
    if i < 0:
        return None
    j = i + len(marker)
    end = j
    while end < len(text) and (text[end].isdigit() or text[end] == "."):
        end += 1
    try:
        return float(text[j:end])
    except ValueError:
        return None


def is_throttle_text(text: str) -> bool:
    """Does a fabric error string denote a rate-limit/queue-full
    rejection? (The RPC server serializes handler errors as
    ``"{type}: {message}"`` — match on the exception class names.)"""
    return "RateLimitError" in text or "BrokerSaturatedError" in text


class TokenBucket:
    """Classic token bucket on the monotonic clock. NOT thread-safe on
    its own — the owning limiter serializes access."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.stamp = time.monotonic() if now is None else now

    def try_take(self, now: Optional[float] = None) -> float:
        """Take one token. Returns 0.0 on success, else the seconds
        until a token will be available (the Retry-After hint)."""
        if now is None:
            now = time.monotonic()
        if now > self.stamp:
            self.tokens = min(
                self.burst, self.tokens + (now - self.stamp) * self.rate
            )
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        if self.rate <= 0:
            return 1.0
        return (1.0 - self.tokens) / self.rate


class KeyedRateLimiter:
    """Per-key (namespace) token buckets sharing one (rate, burst)
    config. rate <= 0 disables the limiter entirely (the default).

    The key set is bounded: least-recently-used buckets are evicted
    past ``max_keys`` so an attacker minting namespaces can't grow
    memory (an evicted key restarts with a full burst — the pessimistic
    direction for the attacker costs them nothing extra)."""

    def __init__(self, rate: float = 0.0, burst: float = 0.0,
                 max_keys: int = 1024) -> None:
        self._lock = threading.Lock()
        self.rate = float(rate)
        self.burst = float(burst) if burst else float(rate)
        self.max_keys = max_keys
        self._buckets: dict[str, TokenBucket] = {}

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    def configure(self, rate: float, burst: float = 0.0) -> None:
        """Live reconfig (SIGHUP): new rate/burst apply to existing
        buckets in place; disabling clears them."""
        with self._lock:
            self.rate = float(rate)
            self.burst = float(burst) if burst else float(rate)
            if self.rate <= 0:
                self._buckets.clear()
                return
            for b in self._buckets.values():
                b.rate = self.rate
                b.burst = max(1.0, self.burst)
                b.tokens = min(b.tokens, b.burst)

    def check(self, key: str, now: Optional[float] = None) -> float:
        """Charge one request against the key's bucket. Returns 0.0 when
        admitted; else the retry-after hint in seconds (caller decides
        whether to raise)."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            bucket = self._buckets.pop(key, None)
            if bucket is None:
                if len(self._buckets) >= self.max_keys:
                    # evict least-recently-used (dict order = recency
                    # because hits re-insert)
                    self._buckets.pop(next(iter(self._buckets)))
                bucket = TokenBucket(self.rate, self.burst, now=now)
            self._buckets[key] = bucket
            return bucket.try_take(now)

    def enforce(self, key: str, what: str = "request") -> None:
        """check() and raise RateLimitError when over the limit."""
        wait = self.check(key)
        if wait > 0:
            raise RateLimitError(
                f"{what} rate limit exceeded for namespace {key!r}",
                retry_after_s=wait,
            )
