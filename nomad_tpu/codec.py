"""Wire codec for the shared struct vocabulary.

Reference: the Go tree serializes every RPC payload with msgpack struct
codecs (helper/pool/pool.go:22-30 msgpackHandle; nomad/structs/structs.go
codec tags) and the HTTP API with encoding/json. The TPU-native build keeps
one reflective codec for both paths:

  * ``to_wire`` lowers any registered dataclass (Job, Node, Allocation, …)
    to plain JSON-able data tagged with its type name;
  * ``from_wire`` reconstructs typed structs recursively;
  * ``pack``/``unpack`` frame that through msgpack for the RPC fabric —
    never pickle, so a malicious peer can at worst produce garbage structs,
    not code execution.

Tuple dict-keys (the state store's (namespace, job_id) keys) and tuples as
values are encoded explicitly since neither JSON nor msgpack has them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import msgpack

_TYPE_KEY = "$t"
_TUPLE_KEY = "$tuple"
_MAP_KEY = "$map"  # dict with non-str keys: list of [k, v] pairs
_BYTES_KEY = "$b64"  # JSON transports bytes base64-tagged (msgpack: native)

_REGISTRY: dict[str, type] = {}


def register_type(cls: type) -> type:
    """Register a dataclass for wire round-trips (idempotent)."""
    _REGISTRY[cls.__name__] = cls
    return cls


def _register_builtin_structs() -> None:
    from . import structs as structs_pkg
    from .structs import structs as structs_mod

    for mod in (
        structs_mod,
        __import__("nomad_tpu.structs.network", fromlist=["x"]),
        __import__("nomad_tpu.structs.devices", fromlist=["x"]),
    ):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                register_type(obj)
    # Non-dataclass state-store types that ride in FSM snapshots.
    from .state.store import JobSummary

    register_type(JobSummary)
    from .acl.structs import ACLPolicy, ACLToken

    register_type(ACLPolicy)
    register_type(ACLToken)
    # Driver plugin boundary payloads (nomad_tpu/drivers/plugin.py).
    from .drivers import base as driver_base

    for name in ("Fingerprint", "TaskConfig", "ExitResult", "TaskStatus"):
        register_type(getattr(driver_base, name))


def to_wire(obj: Any) -> Any:
    """Lower to JSON/msgpack-able data. Unknown object types are an error —
    payloads must be built from registered structs and primitives."""
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, tuple):
        return {_TUPLE_KEY: [to_wire(v) for v in obj]}
    if isinstance(obj, (list, set, frozenset)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, dict):
        # A "$"-prefixed key in user data could collide with our tags
        # ($t/$tuple/$map/$b64) — escape such dicts into the pair-list
        # form, which decodes any keys verbatim.
        if all(isinstance(k, str) for k in obj) and not any(
            k.startswith("$") for k in obj
        ):
            return {k: to_wire(v) for k, v in obj.items()}
        return {_MAP_KEY: [[to_wire(k), to_wire(v)] for k, v in obj.items()]}
    cls = type(obj)
    if dataclasses.is_dataclass(obj):
        if cls.__name__ not in _REGISTRY:
            register_type(cls)
        out: dict[str, Any] = {_TYPE_KEY: cls.__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = to_wire(getattr(obj, f.name))
        return out
    # Non-dataclass registered types (e.g. JobSummary) round-trip via
    # __dict__.
    if cls.__name__ in _REGISTRY:
        out = {_TYPE_KEY: cls.__name__}
        for k, v in vars(obj).items():
            out[k] = to_wire(v)
        return out
    raise TypeError(f"cannot encode {cls.__name__!r} for the wire")


def from_wire(data: Any) -> Any:
    if data is None or isinstance(data, (bool, int, float, str, bytes)):
        return data
    if isinstance(data, list):
        return [from_wire(v) for v in data]
    if isinstance(data, dict):
        if _TUPLE_KEY in data and len(data) == 1:
            return tuple(from_wire(v) for v in data[_TUPLE_KEY])
        if _MAP_KEY in data and len(data) == 1:
            return {from_wire(k): from_wire(v) for k, v in data[_MAP_KEY]}
        if _BYTES_KEY in data and len(data) == 1:
            import base64

            return base64.b64decode(data[_BYTES_KEY])
        tname = data.get(_TYPE_KEY)
        if tname is None:
            return {k: from_wire(v) for k, v in data.items()}
        cls = _REGISTRY.get(tname)
        if cls is None:
            raise TypeError(f"unknown wire type {tname!r}")
        obj = cls.__new__(cls)
        seen = set()
        for k, v in data.items():
            if k == _TYPE_KEY:
                continue
            setattr(obj, k, from_wire(v))
            seen.add(k)
        # Fields the sender didn't know about (version skew) get their
        # declared defaults so the struct is always fully formed.
        if dataclasses.is_dataclass(cls):
            for f in dataclasses.fields(cls):
                if f.name in seen:
                    continue
                if f.default is not dataclasses.MISSING:
                    setattr(obj, f.name, f.default)
                elif f.default_factory is not dataclasses.MISSING:
                    setattr(obj, f.name, f.default_factory())
        return obj
    raise TypeError(f"cannot decode wire value of type {type(data).__name__}")


def json_default(o):
    """json.dumps default for wire payloads: bytes ride base64-tagged and
    registered structs lower through to_wire — handlers may return structs
    nested anywhere in a plain dict (e.g. Job.Plan's FailedTGAllocs), and
    on forwarded RPCs the fabric rehydrates them before the HTTP encode."""
    if isinstance(o, bytes):
        import base64

        return {_BYTES_KEY: base64.b64encode(o).decode()}
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return to_wire(o)
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


def pack(obj: Any) -> bytes:
    return msgpack.packb(to_wire(obj), use_bin_type=True)


def unpack(raw: bytes) -> Any:
    return from_wire(msgpack.unpackb(raw, raw=False, strict_map_key=False))


_register_builtin_structs()
