"""Wire codec for the shared struct vocabulary.

Reference: the Go tree serializes every RPC payload with msgpack struct
codecs (helper/pool/pool.go:22-30 msgpackHandle; nomad/structs/structs.go
codec tags) and the HTTP API with encoding/json. The TPU-native build keeps
one reflective codec for both paths:

  * ``to_wire`` lowers any registered dataclass (Job, Node, Allocation, …)
    to plain JSON-able data tagged with its type name;
  * ``from_wire`` reconstructs typed structs recursively;
  * ``pack``/``unpack`` frame that through msgpack for the RPC fabric —
    never pickle, so a malicious peer can at worst produce garbage structs,
    not code execution.

Tuple dict-keys (the state store's (namespace, job_id) keys) and tuples as
values are encoded explicitly since neither JSON nor msgpack has them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import msgpack

_TYPE_KEY = "$t"
_TUPLE_KEY = "$tuple"
_MAP_KEY = "$map"  # dict with non-str keys: list of [k, v] pairs
_BYTES_KEY = "$b64"  # JSON transports bytes base64-tagged (msgpack: native)

_REGISTRY: dict[str, type] = {}


def register_type(cls: type) -> type:
    """Register a dataclass for wire round-trips (idempotent)."""
    _REGISTRY[cls.__name__] = cls
    return cls


def _register_builtin_structs() -> None:
    from . import structs as structs_pkg
    from .structs import structs as structs_mod

    for mod in (
        structs_mod,
        __import__("nomad_tpu.structs.network", fromlist=["x"]),
        __import__("nomad_tpu.structs.devices", fromlist=["x"]),
    ):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                register_type(obj)
    # Non-dataclass state-store types that ride in FSM snapshots.
    from .state.store import JobSummary

    register_type(JobSummary)
    from .acl.structs import ACLPolicy, ACLToken

    register_type(ACLPolicy)
    register_type(ACLToken)
    # Driver plugin boundary payloads (nomad_tpu/drivers/plugin.py).
    from .drivers import base as driver_base

    for name in ("Fingerprint", "TaskConfig", "ExitResult", "TaskStatus"):
        register_type(getattr(driver_base, name))
    # The SoA placement container (structs/placement_batch.py) — a plain
    # dataclass on the wire (columns as lists + one bytes blob), so Plans
    # carrying batches round-trip with full fidelity.
    from .structs.placement_batch import PlacementBatch

    register_type(PlacementBatch)
    _install_plan_result_encoder()


# Per-class encode/decode plans. A raft apply of a c2m-scale plan packs
# and unpacks ~10⁵ Allocations; per-object dataclasses.fields() reflection
# was the single largest cost of applying a plan. Each entry:
#   cls -> list[(name, compare_default, factory_or_None, has_default)]
# compare_default is what an encoder elides against; factory (when set)
# is what a decoder calls to mint a FRESH default for a missing field —
# mutable defaults must never be shared across decoded objects.
_FIELD_PLANS: dict[type, list] = {}
# cls -> frozenset(field names) for dataclasses, None for other
# registered types (JobSummary et al round-trip via __dict__).
_DATACLASSES: dict[type, Optional[frozenset]] = {}

_SCALARS = frozenset((bool, int, float, str, bytes, type(None)))
_MISSING = object()


def _field_plan(cls: type) -> list:
    plan = _FIELD_PLANS.get(cls)
    if plan is None:
        plan = []
        for f in dataclasses.fields(cls):
            if f.default is not dataclasses.MISSING:
                plan.append((f.name, f.default, None, True))
            elif f.default_factory is not dataclasses.MISSING:
                plan.append((f.name, f.default_factory(), f.default_factory, True))
            else:
                plan.append((f.name, None, None, False))
        _FIELD_PLANS[cls] = plan
    return plan


def _install_plan_result_encoder() -> None:
    """Custom elide-encoder for PlanResult: alloc_batches (the SoA
    placement columns) FOLD into node_allocation as per-row wire maps
    minted from one shared template, so a raft entry carrying batches is
    BYTE-IDENTICAL to the entry the eager-object path would have
    produced (the differential identity battery pins this). The fold
    happens encoder-side precisely so the bulk of a c2m plan never
    exists as 10^5 Python Allocation objects on the leader.

    PlanResult is deliberately NOT registered with the native encoder
    (_fastpack_module skips it): the C path would emit alloc_batches as
    a structural field and break the identity. The surrounding payload
    still C-encodes until it reaches the PlanResult, then falls back —
    and the fold's per-row work is one dict fan-out per row
    (fastpack.wire_rows in C when present)."""
    from .structs import PlanResult

    def _enc(r):
        out: dict[str, Any] = {_TYPE_KEY: "PlanResult"}
        # mirror _gen_encoder's elision: factory defaults elide on
        # (exact-class and ==), None defaults on is-not-None, int
        # defaults on != 0
        v = r.node_update
        if not (v.__class__ is dict and not v):
            out["node_update"] = to_wire(v, True)
        na = r.node_allocation
        batches = r.alloc_batches
        if batches:
            m: dict[str, Any] = {
                nid: [to_wire(a, True) for a in allocs]
                for nid, allocs in na.items()
            }
            for b in batches:
                b.extend_wire_rows(m)
            out["node_allocation"] = m
        elif not (na.__class__ is dict and not na):
            out["node_allocation"] = to_wire(na, True)
        v = r.node_preemptions
        if not (v.__class__ is dict and not v):
            out["node_preemptions"] = to_wire(v, True)
        if r.job is not None:
            out["job"] = to_wire(r.job, True)
        if r.deployment is not None:
            out["deployment"] = to_wire(r.deployment, True)
        v = r.deployment_updates
        if not (v.__class__ is list and not v):
            out["deployment_updates"] = to_wire(v, True)
        v = r.preemption_evals
        if not (v.__class__ is list and not v):
            out["preemption_evals"] = to_wire(v, True)
        v = r.refresh_index
        if not (v.__class__ is int and v == 0):
            out["refresh_index"] = v if v.__class__ is int else to_wire(v, True)
        v = r.alloc_index
        if not (v.__class__ is int and v == 0):
            out["alloc_index"] = v if v.__class__ is int else to_wire(v, True)
        # alloc_batches itself is never emitted — it is the fold above
        return out

    _ENCODERS[PlanResult] = _enc


def to_wire(obj: Any, _elide: bool = False) -> Any:
    """Lower to JSON/msgpack-able data. Unknown object types are an error —
    payloads must be built from registered structs and primitives.

    With _elide (the pack()/RPC path), dataclass fields still equal to
    their declared default are OMITTED: decoders restore defaults for
    missing fields (the version-skew path), so elision is lossless for
    struct consumers — and most fields of bulk payloads (plan allocs) are
    defaults, which is the difference between encoding ~40 and ~8 fields
    per Allocation. The HTTP/JSON path keeps full field sets: the UI and
    third-party API clients read raw JSON, not rehydrated structs."""
    cls = obj.__class__
    if obj is None or cls in _SCALARS:
        return obj
    if _elide:
        enc = _ENCODERS.get(cls)
        if enc is not None:
            return enc(obj)
    if cls is list:
        return [to_wire(v, _elide) for v in obj]
    if cls is dict:
        # A "$"-prefixed key in user data could collide with our tags
        # ($t/$tuple/$map/$b64) — escape such dicts into the pair-list
        # form, which decodes any keys verbatim.
        if all(type(k) is str and not k.startswith("$") for k in obj):
            return {k: to_wire(v, _elide) for k, v in obj.items()}
        return {
            _MAP_KEY: [
                [to_wire(k, _elide), to_wire(v, _elide)] for k, v in obj.items()
            ]
        }
    if isinstance(obj, tuple):
        return {_TUPLE_KEY: [to_wire(v, _elide) for v in obj]}
    if isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, (list, set, frozenset)):
        return [to_wire(v, _elide) for v in obj]
    if isinstance(obj, dict):
        if all(isinstance(k, str) and not k.startswith("$") for k in obj):
            return {k: to_wire(v, _elide) for k, v in obj.items()}
        return {
            _MAP_KEY: [
                [to_wire(k, _elide), to_wire(v, _elide)] for k, v in obj.items()
            ]
        }
    if dataclasses.is_dataclass(obj):
        if cls.__name__ not in _REGISTRY:
            register_type(cls)
        if _elide:
            enc = _ENCODERS.get(cls)
            if enc is None:
                enc = _gen_encoder(cls)
            return enc(obj)
        out: dict[str, Any] = {_TYPE_KEY: cls.__name__}
        for name, _default, _factory, _has_default in _field_plan(cls):
            out[name] = to_wire(getattr(obj, name))
        return out
    # Non-dataclass registered types (e.g. JobSummary) round-trip via
    # __dict__.
    if cls.__name__ in _REGISTRY:
        out = {_TYPE_KEY: cls.__name__}
        for k, v in vars(obj).items():
            out[k] = to_wire(v, _elide)
        return out
    if cls.__name__ == "AllocRow":
        # a lazy store-table handle (structs/placement_batch.py) that
        # escaped to a wire boundary: materialize — the cached row is
        # the value the eager path would have stored
        return to_wire(obj.get(), _elide)
    raise TypeError(f"cannot encode {cls.__name__!r} for the wire")


def _dataclass_fields(cls: type) -> Optional[frozenset]:
    """frozenset of field names for dataclasses (cached), None for other
    registered types (JobSummary et al round-trip via __dict__)."""
    names = _DATACLASSES.get(cls, _MISSING)
    if names is _MISSING:
        names = (
            frozenset(f.name for f in dataclasses.fields(cls))
            if dataclasses.is_dataclass(cls)
            else None
        )
        _DATACLASSES[cls] = names
    return names


def _restore_defaults(obj, data: dict, cls: type) -> None:
    """Fields the sender elided or didn't know about (defaults / version
    skew) get their declared defaults so the struct is always fully
    formed — mutable ones freshly minted, never shared across objects."""
    for name, default, factory, has_default in _field_plan(cls):
        if name in data:
            continue
        if factory is not None:
            setattr(obj, name, factory())
        elif has_default:
            setattr(obj, name, default)


def from_wire(data: Any) -> Any:
    cls = data.__class__
    if data is None or cls in _SCALARS:
        return data
    if cls is list:
        return [from_wire(v) for v in data]
    if cls is dict:
        tname = data.get(_TYPE_KEY)
        if tname is not None:
            tcls = _REGISTRY.get(tname)
            if tcls is None:
                raise TypeError(f"unknown wire type {tname!r}")
            obj = tcls.__new__(tcls)
            field_names = _dataclass_fields(tcls)
            for k, v in data.items():
                # Unknown sender fields (version skew) are dropped — the
                # same rule the msgpack hook applies, and slots classes
                # could not hold them anyway.
                if k != _TYPE_KEY and (field_names is None or k in field_names):
                    setattr(obj, k, from_wire(v))
            if field_names is not None:
                _restore_defaults(obj, data, tcls)
            return obj
        if len(data) == 1:
            if _TUPLE_KEY in data:
                return tuple(from_wire(v) for v in data[_TUPLE_KEY])
            if _MAP_KEY in data:
                return {
                    from_wire(k): from_wire(v) for k, v in data[_MAP_KEY]
                }
            if _BYTES_KEY in data:
                import base64

                return base64.b64decode(data[_BYTES_KEY])
        return {k: from_wire(v) for k, v in data.items()}
    if isinstance(data, (bool, int, float, str, bytes)):
        return data
    if isinstance(data, (list, dict)):  # subclasses
        return (
            [from_wire(v) for v in data]
            if isinstance(data, list)
            else {k: from_wire(v) for k, v in data.items()}
        )
    raise TypeError(f"cannot decode wire value of type {type(data).__name__}")


def json_default(o):
    """json.dumps default for wire payloads: bytes ride base64-tagged and
    registered structs lower through to_wire — handlers may return structs
    nested anywhere in a plain dict (e.g. Job.Plan's FailedTGAllocs), and
    on forwarded RPCs the fabric rehydrates them before the HTTP encode."""
    if isinstance(o, bytes):
        import base64

        return {_BYTES_KEY: base64.b64encode(o).decode()}
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return to_wire(o)
    if type(o).__name__ == "AllocRow":
        # lazy alloc handle at the HTTP/event boundary: materialize the
        # cached row view (docs/pipeline.md § lazy materialization)
        return to_wire(o.get())
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


# cls -> generated elide-encoder. Like the dataclasses module itself,
# the codec compiles a specialized function per class: scalar fields are
# compared and emitted inline (no recursive to_wire frame per int/str),
# which matters when a raft apply packs 10⁵ allocs.
_ENCODERS: dict[type, Any] = {}


def _gen_encoder(cls: type):
    lines = [
        "def _enc(obj):",
        f"    out = {{{_TYPE_KEY!r}: {cls.__name__!r}}}",
    ]
    ns: dict[str, Any] = {"_w": to_wire}
    for i, (name, default, _factory, has_default) in enumerate(
        _field_plan(cls)
    ):
        v, d, t = f"v{i}", f"d{i}", f"t{i}"
        lines.append(f"    {v} = obj.{name}")
        if not has_default:
            lines.append(f"    out[{name!r}] = _w({v}, True)")
        elif default is None:
            lines.append(f"    if {v} is not None:")
            lines.append(f"        out[{name!r}] = _w({v}, True)")
        elif default.__class__ in (bool, int, float, str, bytes):
            ns[d] = default
            ns[t] = default.__class__
            lines.append(f"    if {v}.__class__ is {t}:")
            lines.append(f"        if {v} != {d}:")
            lines.append(f"            out[{name!r}] = {v}")
            lines.append(f"    else:")
            lines.append(f"        out[{name!r}] = _w({v}, True)")
        else:
            ns[d] = default
            ns[t] = default.__class__
            lines.append(
                f"    if not ({v}.__class__ is {t} and {v} == {d}):"
            )
            lines.append(f"        out[{name!r}] = _w({v}, True)")
    lines.append("    return out")
    exec("\n".join(lines), ns)
    enc = ns["_enc"]
    _ENCODERS[cls] = enc
    return enc


def _object_hook(data: dict) -> Any:
    """Per-map decode hook for msgpack: children are already decoded by
    the C unpacker (scalars/lists never surface to Python), so this runs
    once per MAP — the struct-count, not the value-count, bounds the
    Python work of unpacking a bulk payload."""
    tname = data.pop(_TYPE_KEY, None)
    if tname is not None:
        cls = _REGISTRY.get(tname)
        if cls is None:
            raise TypeError(f"unknown wire type {tname!r}")
        field_names = _dataclass_fields(cls)
        if field_names is not None:
            try:
                # The generated __init__ fills every elided/missing field
                # with its declared default (fresh factory instances) in
                # one call — the decode hot path.
                return cls(**data)
            except TypeError:
                # sender knows fields we don't (version skew): keep the
                # intersection and default the rest
                obj = cls.__new__(cls)
                for k, v in data.items():
                    if k in field_names:
                        setattr(obj, k, v)
                _restore_defaults(obj, data, cls)
                return obj
        obj = cls.__new__(cls)
        for k, v in data.items():
            setattr(obj, k, v)
        return obj
    if len(data) == 1:
        if _TUPLE_KEY in data:
            return tuple(data[_TUPLE_KEY])
        if _MAP_KEY in data:
            return {k: v for k, v in data[_MAP_KEY]}
        if _BYTES_KEY in data:
            import base64

            return base64.b64decode(data[_BYTES_KEY])
    return data


_fastpack = None
_fastpack_synced = 0
_native_reported = False


def _fastpack_module():
    """The native encoder/decoder, with the class registry synced
    lazily — register_type after a sync triggers a re-sync on the next
    miss."""
    global _fastpack, _fastpack_synced
    if _fastpack is None:
        from .native import load_fastpack

        _fastpack = load_fastpack() or False
    if _fastpack and _fastpack_synced != len(_REGISTRY):
        for cls in _REGISTRY.values():
            if cls.__name__ == "PlanResult":
                # never C-registered: PlanResult's Python encoder FOLDS
                # alloc_batches into node_allocation for raft-entry byte
                # identity; the C field-plan encoder would emit the
                # batches structurally (see _install_plan_result_encoder)
                continue
            if dataclasses.is_dataclass(cls):
                enc_plan = tuple(
                    (fname, default, has)
                    for fname, default, _factory, has in _field_plan(cls)
                )
                _fastpack.register_class(cls, enc_plan)
            else:
                _fastpack.register_class(cls, None)
        _fastpack_synced = len(_REGISTRY)
    return _fastpack or None


def native_module():
    """The fastpack extension if it is already resolved, else None —
    never triggers the C build (warm_native is the sanctioned build
    point, outside any lock; NV-lock-blocking pins that rule)."""
    return _fastpack or None


def warm_native() -> bool:
    """Resolve (and if necessary compile) the fastpack extension NOW.

    pack() loads it lazily, and nomad-vet's NV-lock-blocking walk
    showed the first call can land under a hot lock — the raft lock
    during a leader transition (_become_leader_locked packs the
    barrier entry), the state-store lock (serialize), the RPC write
    lock — turning a one-time C build (up to ~120s cold) into a
    lock-held stall. Components that pack under locks call this once
    at startup, outside any lock; afterwards _fastpack_module() is a
    cached module lookup. Returns True when the native path is live.

    Also the observability point for the build: logs availability once
    and publishes nomad.native.{available,build_seconds} so an
    operator can tell from a capture whether the C path was live and
    whether this process paid a cold compile.
    """
    global _native_reported
    live = _fastpack_module() is not None
    if not _native_reported:
        _native_reported = True
        import logging

        from . import metrics, native

        build_s = max(native.last_build_seconds, 0.0)
        metrics.set_gauge("nomad.native.available", 1.0 if live else 0.0)
        metrics.set_gauge("nomad.native.build_seconds", build_s)
        logging.getLogger("nomad_tpu.native").info(
            "fastpack %s (resolved in %.3fs; entry points: %s)",
            "live" if live else "unavailable - pure-Python fallbacks",
            build_s,
            ", ".join(native.FASTPACK_ENTRY_POINTS) if live else "none",
        )
    return live


def pack(obj: Any) -> bytes:
    fp = _fastpack_module()
    if fp is not None:
        try:
            return fp.pack(obj)
        except fp.Fallback:
            pass  # unregistered/unusual object: the Python path handles it
    return msgpack.packb(to_wire(obj, _elide=True), use_bin_type=True)


def unpack(raw: bytes) -> Any:
    # decode stays in Python: measured head-to-head, the generated
    # dataclass __init__ through _object_hook beats a C-side
    # __new__+setattr loop on CPython 3.12 (the specializing
    # interpreter makes the 40-field init cheaper than 40 C SetAttrs).
    return msgpack.unpackb(
        raw, raw=False, strict_map_key=False, object_hook=_object_hook
    )


_register_builtin_structs()
