"""Candidate limiting and final selection.

Reference: scheduler/select.go — LimitIterator :5 (visit `limit` nodes,
skipping up to 3 with negative scores), MaxScoreIterator :79.
"""

from __future__ import annotations

from typing import Iterator, Optional

from .rank import RankedNode

MAX_SKIP = 3


def limit_select(options: Iterator[RankedNode], limit: int) -> list[RankedNode]:
    """Take `limit` candidates, passing over up to MAX_SKIP negative-scored
    ones (they are kept as fallback if nothing better shows up)."""
    out: list[RankedNode] = []
    skipped: list[RankedNode] = []
    for option in options:
        if option.final_score < 0 and len(skipped) < MAX_SKIP:
            skipped.append(option)
            continue
        out.append(option)
        if len(out) >= limit:
            return out
    out.extend(skipped[: limit - len(out)])
    return out


def max_score_select(options: list[RankedNode]) -> Optional[RankedNode]:
    best: Optional[RankedNode] = None
    for option in options:
        if best is None or option.final_score > best.final_score:
            best = option
    return best
