"""System / sysbatch scheduler: place on every feasible node.

Reference: scheduler/scheduler_system.go — Process :71, computeJobAllocs,
computePlacements; uses diffSystemAllocs (util.go:230).
"""

from __future__ import annotations

from typing import Optional

from ..structs import (
    AllocMetric,
    Allocation,
    Evaluation,
    generate_uuid,
    now_ns,
)
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_LOST,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
)
from .context import EvalContext, SchedulerConfig
from .stack import SystemStack
from .util import (
    SchedulerRetryError,
    ready_nodes_in_dcs,
    retry_max,
    tainted_nodes,
    diff_system_allocs,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5


class SystemScheduler:
    scheduler_type = "system"

    def __init__(self, logger, state, planner, config: Optional[SchedulerConfig] = None):
        self.logger = logger
        self.state = state
        self.planner = planner
        self.config = config or SchedulerConfig()
        self.sysbatch = self.scheduler_type == "sysbatch"
        self.eval = None
        self.plan = None
        self.plan_result = None
        self.failed_tg_allocs: dict[str, AllocMetric] = {}
        self.queued_allocs: dict[str, int] = {}

    def process(self, eval_obj: Evaluation) -> None:
        self.eval = eval_obj
        try:
            retry_max(MAX_SYSTEM_SCHEDULE_ATTEMPTS, self._attempt, self._progress)
        except SchedulerRetryError as e:
            self._set_status(EVAL_STATUS_FAILED, str(e))
            return
        self._set_status(EVAL_STATUS_COMPLETE, "")

    def _progress(self) -> bool:
        result = self.plan_result
        made = result is not None and not result.is_no_op()
        if result is not None and result.refresh_index > 0:
            self.state = self.planner.refresh_state(result.refresh_index)
        return made

    def _attempt(self) -> tuple[bool, object]:
        eval_obj = self.eval
        job = self.state.job_by_id(eval_obj.namespace, eval_obj.job_id)
        self.plan = eval_obj.make_plan(job)
        self.failed_tg_allocs = {}
        self.plan_result = None
        ctx = EvalContext(self.state, self.plan, self.logger, self.config)
        stack = SystemStack(ctx)

        allocs = self.state.allocs_by_job(eval_obj.namespace, eval_obj.job_id)
        tainted = tainted_nodes(self.state, allocs)

        if job is None or job.stopped():
            for a in allocs:
                if not a.terminal_status():
                    self.plan.append_stopped_alloc(a, "alloc not needed", "")
            return self._finish()

        nodes, dc_counts = ready_nodes_in_dcs(self.state, job.datacenters)
        self._dc_counts = dc_counts
        stack.set_nodes(nodes)
        stack.set_job(job)

        # terminal allocs per node/tg (sysbatch: completed stays completed)
        terminal_by_node: dict[str, dict[str, Allocation]] = {}
        for a in allocs:
            if a.terminal_status():
                terminal_by_node.setdefault(a.node_id, {})[a.task_group] = a

        diff = diff_system_allocs(job, nodes, tainted, allocs, terminal_by_node)

        if eval_obj.annotate_plan:
            # Plan dry-run annotations (reference scheduler/annotate.go) —
            # computed from the raw diff BEFORE destructive updates are
            # folded into diff.place below, so they count once.
            import dataclasses as _dc

            from .reconcile import GroupSummary

            summaries: dict[str, GroupSummary] = {}

            def _s(name: str) -> GroupSummary:
                return summaries.setdefault(name, GroupSummary())

            for tg, node, _terminal in diff.place:
                if node is not None:
                    _s(tg.name).place += 1
            for alloc, _reason in diff.stop:
                _s(alloc.task_group).stop += 1
            for alloc in diff.lost:
                _s(alloc.task_group).stop += 1
            for alloc, tg in diff.update:
                _s(tg.name).destructive += 1
            for alloc in diff.ignore:
                _s(alloc.task_group).ignore += 1
            self.plan.annotations = {
                "DesiredTGUpdates": {
                    k: _dc.asdict(v) for k, v in summaries.items()
                }
            }

        for alloc, reason in diff.stop:
            self.plan.append_stopped_alloc(alloc, reason, "")
        for alloc in diff.lost:
            self.plan.append_stopped_alloc(
                alloc, "alloc is lost since its node is down", ALLOC_CLIENT_STATUS_LOST
            )
        for alloc, tg in diff.update:
            self.plan.append_stopped_alloc(alloc, "alloc not needed due to job update", "")
            diff.place.append((tg, self.state.node_by_id(alloc.node_id), None))

        queued: dict[str, int] = {tg.name: 0 for tg in job.task_groups}
        # group the per-node placements by task group: the TPU subclass
        # vectorizes each group across its nodes in one pass
        by_tg: dict[str, tuple] = {}
        for tg, node, terminal in diff.place:
            if node is None:
                continue
            if (
                self.sysbatch
                and terminal is not None
                and terminal.client_status == ALLOC_CLIENT_STATUS_COMPLETE
                and terminal.job is not None
                and terminal.job.version == job.version
            ):
                continue  # already ran to completion on this node
            entry = by_tg.setdefault(tg.name, (tg, []))
            entry[1].append(node)
        for tg, nodes in by_tg.values():
            self._place_group(job, eval_obj, stack, tg, nodes, queued)
        self.queued_allocs = queued
        eval_obj.queued_allocations = queued
        return self._finish()

    def _place_group(self, job, eval_obj, stack, tg, nodes, queued) -> None:
        """Place one instance of tg on each node (per-node iterator walk;
        the TPU backend overrides this with a vectorized pass)."""
        for node in nodes:
            self._place_one(job, eval_obj, stack, tg, node, queued)

    def _place_one(self, job, eval_obj, stack, tg, node, queued) -> None:
        metric = AllocMetric(nodes_available=dict(self._dc_counts))
        start = now_ns()
        option = stack.select(tg, node, metrics=metric)
        if option is None and self.config.preemption_enabled(job.type):
            option = stack.select(tg, node, metrics=metric, evict=True)
        metric.allocation_time_ns = now_ns() - start
        if option is None:
            if metric.nodes_filtered > 0:
                # the node was constraint-filtered: the system alloc was
                # never meant to run here — neither queued nor reported
                # as a failure (reference scheduler_system.go:308-322)
                return
            self._record_failure(tg, metric, queued)
            return
        alloc = Allocation(
            id=generate_uuid(),
            namespace=eval_obj.namespace,
            eval_id=eval_obj.id,
            name=f"{job.id}.{tg.name}[0]",
            node_id=node.id,
            node_name=node.name,
            job_id=job.id,
            job=job,
            task_group=tg.name,
            resources=option.alloc_resources,
            metrics=metric,
        )
        if option.preempted_allocs:
            alloc.preempted_allocations = [
                p.id for p in option.preempted_allocs
            ]
            for p in option.preempted_allocs:
                self.plan.append_preempted_alloc(p, alloc.id)
        self.plan.append_alloc(alloc, job)

    def _record_failure(self, tg, metric, queued) -> None:
        existing = self.failed_tg_allocs.get(tg.name)
        if existing is not None:
            existing.coalesced_failures += 1
        else:
            self.failed_tg_allocs[tg.name] = metric
        queued[tg.name] = queued.get(tg.name, 0) + 1

    def _finish(self) -> tuple[bool, object]:
        if self.plan.is_no_op():
            return True, None
        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result
        if new_state is not None:
            self.state = new_state
        full, _, _ = result.full_commit(self.plan)
        if not full:
            return False, None
        return True, None

    def _set_status(self, status: str, desc: str) -> None:
        updated = self.eval.copy()
        updated.status = status
        updated.status_description = desc
        updated.failed_tg_allocs = self.failed_tg_allocs
        updated.queued_allocations = self.queued_allocs
        self.planner.update_eval(updated)


class SysBatchScheduler(SystemScheduler):
    scheduler_type = "sysbatch"
