"""Per-evaluation scheduling context and caches.

Reference: scheduler/context.go — EvalContext :76, ProposedAllocs :120,
EvalEligibility :190. The context carries the state snapshot, the plan being
built, per-eval regex/version caches, and the computed-class eligibility
memoization that lets feasibility run once per node class instead of once per
node. The TPU solver reuses EvalEligibility results when building the
feasibility-mask tensor.
"""

from __future__ import annotations

import re
from typing import Optional

from ..structs import Allocation, Plan
from ..structs.funcs import filter_terminal_allocs
from ..structs.node_class import escaped_constraint_target

# Eligibility states for (job/tg, class) pairs.
ELIGIBILITY_UNKNOWN = 0
ELIGIBILITY_ELIGIBLE = 1
ELIGIBILITY_INELIGIBLE = 2
ELIGIBILITY_ESCAPED = 3  # constraints reference unique attrs; no memoization


class SchedulerConfig:
    """Cluster-operator scheduler knobs (reference: structs/operator.go
    SchedulerConfiguration, applied at rank.go:164-170)."""

    def __init__(
        self,
        algorithm: str = "binpack",  # binpack | spread
        preemption_service: bool = True,
        preemption_batch: bool = False,
        preemption_system: bool = True,
        preemption_sysbatch: bool = False,
        memory_oversubscription: bool = False,
        backend: str = "host",  # host | tpu — which placement backend to use
        small_batch_threshold: int = 48,
        inject_device_latency_s: Optional[float] = None,
        soa_placements: Optional[bool] = None,
        mesh_devices: Optional[int] = None,
        micro_solve_threshold: Optional[int] = None,
    ) -> None:
        import os

        # Host microsolve bound (the interactive fast path): a small
        # batch whose node-count x group-count product is at or below
        # this solves with the numpy compact kernel (scheduler/tpu/
        # microsolve.py) — dense-path semantics, zero device round-trip.
        # 0 disables (every small batch keeps the host iterator stack);
        # NOMAD_TPU_MICRO_NG overrides.
        if micro_solve_threshold is None:
            micro_solve_threshold = int(
                os.environ.get("NOMAD_TPU_MICRO_NG", "8192") or 0
            )
        self.micro_solve_threshold = micro_solve_threshold

        # Multi-chip: shard the solve's node axis over this many devices
        # (scheduler/tpu/sharding.py). 0 = single chip. The sharded
        # kernels are bit-identical to the single-chip solver, so every
        # other knob composes unchanged.
        if mesh_devices is None:
            mesh_devices = int(
                os.environ.get("NOMAD_TPU_MESH_DEVICES", "0") or 0
            )
        self.mesh_devices = mesh_devices

        # Struct-of-arrays placements (structs/placement_batch.py): the
        # solver's fast-mint path emits PlacementBatch columns instead of
        # per-row Allocation objects, materialized lazily at API/client
        # boundaries. Default ON; NOMAD_TPU_SOA=0 (or soa_placements=
        # False) keeps the eager-object path — the differential identity
        # battery's comparator.
        if soa_placements is None:
            soa_placements = os.environ.get("NOMAD_TPU_SOA", "1") != "0"
        self.soa_placements = soa_placements

        self.algorithm = algorithm
        self.preemption_service = preemption_service
        self.preemption_batch = preemption_batch
        self.preemption_system = preemption_system
        self.preemption_sysbatch = preemption_sysbatch
        self.memory_oversubscription = memory_oversubscription
        self.backend = backend
        # Batches asking for fewer total placements than this skip the
        # tensor solve: the device round-trip dominates tiny solves, so
        # they run the host iterator stack instead (VERDICT r3 #3 —
        # reference per-eval latency: scheduler/generic_sched.go:125).
        self.small_batch_threshold = small_batch_threshold
        # Simulated device round-trip added to every dense kernel solve
        # (docs/pipeline.md): on CPU fallback this reproduces the ~0.15s
        # tunnel RTT the real chip pays, so the worker's solve/commit
        # overlap is measurable without the hardware. Settable per-config
        # or via NOMAD_TPU_INJECT_DEVICE_LATENCY_S.
        if inject_device_latency_s is None:
            inject_device_latency_s = float(
                os.environ.get("NOMAD_TPU_INJECT_DEVICE_LATENCY_S", "0") or 0
            )
        self.inject_device_latency_s = inject_device_latency_s

    def preemption_enabled(self, scheduler_type: str) -> bool:
        return {
            "service": self.preemption_service,
            "batch": self.preemption_batch,
            "system": self.preemption_system,
            "sysbatch": self.preemption_sysbatch,
        }.get(scheduler_type, False)


class EvalEligibility:
    """Computed-class feasibility memo (reference: context.go:190)."""

    def __init__(self) -> None:
        self.job: dict[str, int] = {}  # class -> eligibility
        self.job_escaped = False
        self.tg: dict[str, dict[str, int]] = {}  # tg -> class -> eligibility
        self.tg_escaped: dict[str, bool] = {}
        self.quota_reached: str = ""

    def set_job(self, job) -> None:
        self.job_escaped = any(
            escaped_constraint_target(c.ltarget) for c in job.constraints
        )
        for tg in job.task_groups:
            escaped = any(escaped_constraint_target(c.ltarget) for c in tg.constraints)
            if not escaped:
                for task in tg.tasks:
                    if any(
                        escaped_constraint_target(c.ltarget) for c in task.constraints
                    ):
                        escaped = True
                        break
            self.tg_escaped[tg.name] = escaped

    def job_status(self, klass: str) -> int:
        if self.job_escaped or not klass:
            return ELIGIBILITY_ESCAPED
        return self.job.get(klass, ELIGIBILITY_UNKNOWN)

    def set_job_eligibility(self, eligible: bool, klass: str) -> None:
        self.job[klass] = ELIGIBILITY_ELIGIBLE if eligible else ELIGIBILITY_INELIGIBLE

    def task_group_status(self, tg: str, klass: str) -> int:
        if self.tg_escaped.get(tg, False) or not klass:
            return ELIGIBILITY_ESCAPED
        return self.tg.get(tg, {}).get(klass, ELIGIBILITY_UNKNOWN)

    def set_task_group_eligibility(self, eligible: bool, tg: str, klass: str) -> None:
        self.tg.setdefault(tg, {})[klass] = (
            ELIGIBILITY_ELIGIBLE if eligible else ELIGIBILITY_INELIGIBLE
        )

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def get_classes(self) -> dict[str, bool]:
        """class -> eligible, for blocked-eval unblocking. Task-group
        verdicts outrank the job-level ones: a class that passed job
        constraints but failed every group's is NOT eligible
        (reference: context.go GetClasses)."""
        out: dict[str, bool] = {}
        for tg_classes in self.tg.values():
            for klass, status in tg_classes.items():
                if status == ELIGIBILITY_ELIGIBLE:
                    out[klass] = True
        for tg_classes in self.tg.values():
            for klass, status in tg_classes.items():
                if status == ELIGIBILITY_INELIGIBLE:
                    out.setdefault(klass, False)
        for klass, status in self.job.items():
            out.setdefault(klass, status == ELIGIBILITY_ELIGIBLE)
        return out


class EvalContext:
    """Everything one evaluation's scheduling pass needs."""

    def __init__(self, state, plan: Optional[Plan] = None, logger=None,
                 scheduler_config: Optional[SchedulerConfig] = None,
                 extra_plans: Optional[list] = None) -> None:
        self.state = state  # StateSnapshot
        self.plan = plan
        # Other in-flight plans of the SAME batch solve (the small-batch
        # host path): proposed-alloc accounting must see their placements
        # or two evals in one batch double-book a node — the dense path
        # coordinates through its shared caches instead.
        self.extra_plans = extra_plans or []
        self.logger = logger
        self.scheduler_config = scheduler_config or SchedulerConfig()
        self._regex_cache: dict[str, re.Pattern] = {}
        self._version_cache: dict[str, object] = {}
        self.eligibility = EvalEligibility()
        self.metrics_nodes_evaluated = 0

    def set_plan(self, plan: Plan) -> None:
        self.plan = plan

    def regex(self, pattern: str) -> Optional[re.Pattern]:
        pat = self._regex_cache.get(pattern)
        if pat is None:
            try:
                pat = re.compile(pattern)
            except re.error:
                return None
            self._regex_cache[pattern] = pat
        return pat

    def proposed_allocs(self, node_id: str) -> list[Allocation]:
        """The node's allocs if the current plan were applied.

        state allocs − plan.node_update − (updated ids) + plan.node_allocation,
        terminal filtered (reference: context.go:120).
        """
        existing = self.state.allocs_by_node_terminal(node_id, False)
        plans = [self.plan] if self.plan is not None else []
        plans.extend(self.extra_plans)
        for plan in plans:
            update_ids = {a.id for a in plan.node_update.get(node_id, [])}
            preempt_ids = {a.id for a in plan.node_preemptions.get(node_id, [])}
            drop = update_ids | preempt_ids
            proposed_new = plan.node_allocation.get(node_id, [])
            new_ids = {a.id for a in proposed_new}
            existing = [a for a in existing if a.id not in drop and a.id not in new_ids]
            existing = existing + list(proposed_new)
        live, _ = filter_terminal_allocs(existing)
        return live
