"""Device instance assignment with affinity scoring.

Reference: scheduler/device.go — deviceAllocator :13, AssignDevice :32.
"""

from __future__ import annotations

from typing import Any, Optional

from ..structs import Node
from ..structs.structs import RequestedDevice
from .context import EvalContext


class DeviceAllocator:
    """Tracks free device instances on one node during ranking."""

    def __init__(self, ctx: EvalContext, node: Node) -> None:
        self.ctx = ctx
        self.node = node
        # device-group id -> set of free healthy instance ids
        self.free: dict[str, set[str]] = {
            d.id_string(): {i.id for i in d.instances if i.healthy}
            for d in node.resources.devices
        }
        self.groups = {d.id_string(): d for d in node.resources.devices}

    def add_allocs(self, allocs) -> None:
        for alloc in allocs:
            if alloc.terminal_status() or alloc.resources is None:
                continue
            for tr in alloc.resources.tasks.values():
                for dev in tr.devices:
                    free = self.free.get(dev.get("id", ""))
                    if free is not None:
                        free.difference_update(dev.get("device_ids", []))

    def assign(self, ask: RequestedDevice) -> Optional[dict[str, Any]]:
        """Pick instances for the ask; prefer groups scoring best on
        affinities. Returns {'id', 'device_ids'} or None."""
        from .feasible import _resolve_device_target, check_constraint

        best: Optional[tuple[float, str, list[str]]] = None
        for gid, group in self.groups.items():
            if not group.matches(ask):
                continue
            free = self.free.get(gid, set())
            if len(free) < ask.count:
                continue
            if ask.constraints:
                ok = True
                for c in ask.constraints:
                    lval, lf = _resolve_device_target(group, c.ltarget)
                    rval, rf = _resolve_device_target(group, c.rtarget)
                    if not check_constraint(self.ctx, c.operand, lval, rval, lf, rf):
                        ok = False
                        break
                if not ok:
                    continue
            score = 0.0
            if ask.affinities:
                total_weight = sum(abs(a.weight) for a in ask.affinities) or 1
                for a in ask.affinities:
                    lval, lf = _resolve_device_target(group, a.ltarget)
                    rval, rf = _resolve_device_target(group, a.rtarget)
                    if check_constraint(self.ctx, a.operand, lval, rval, lf, rf):
                        score += a.weight / total_weight
            if best is None or score > best[0]:
                best = (score, gid, sorted(free)[: ask.count])
        if best is None:
            return None
        _, gid, ids = best
        self.free[gid].difference_update(ids)
        return {"id": gid, "device_ids": ids}
