"""Preemption: evict lower-priority allocations to make room.

Reference: scheduler/preemption.go — Preemptor :96, PreemptForTaskGroup
:198, basicResourceDistance :608, scoreForTaskGroup :640,
filterAndGroupPreemptibleAllocs :663, filterSuperset :702.

Candidate rules (same contract as the reference):
  * only allocs whose job priority is AT LEAST 10 below the placing
    job's priority are preemptible (the "delta 10" rule — reference
    preemption.go:672 skips `jobPriority - allocPriority < 10`);
  * candidates are consumed lowest-priority-tier first;
  * within a tier, pick the alloc whose resources are CLOSEST to the
    remaining need (normalized cpu/memory/disk euclidean distance), with
    a penalty for preempting many allocs of one job past its migrate
    max_parallel;
  * a final superset pass drops preemptions made redundant by later,
    larger picks.

The TPU backend reaches the same decisions tensor-wise: allocs are
lowered into per-priority-tier usage tensors and the solver frees tiers
cheapest-first (scheduler/tpu/lower.py, solver.py).
"""

from __future__ import annotations

import math
from typing import Optional

from ..structs import Allocation, Node, Resources

# Applied per already-preempted alloc of the same job/group beyond its
# migrate max_parallel (reference preemption.go:13 maxParallelPenalty).
MAX_PARALLEL_PENALTY = 50.0

# Minimum priority gap between the placing job and a preemptible alloc.
PRIORITY_DELTA = 10


def basic_resource_distance(ask: Resources, used: Resources) -> float:
    """Normalized euclidean distance between an ask and an alloc's usage
    (reference :608). Lower = closer match = better preemption pick."""
    cpu_coord = mem_coord = disk_coord = 0.0
    if ask.cpu > 0:
        cpu_coord = (ask.cpu - used.cpu) / ask.cpu
    if ask.memory_mb > 0:
        mem_coord = (ask.memory_mb - used.memory_mb) / ask.memory_mb
    if ask.disk_mb > 0:
        disk_coord = (ask.disk_mb - used.disk_mb) / ask.disk_mb
    return math.sqrt(cpu_coord**2 + mem_coord**2 + disk_coord**2)


def score_for_task_group(
    ask: Resources, used: Resources, max_parallel: int, num_preempted: int
) -> float:
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = (num_preempted + 1 - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def _superset(avail: Resources, need: Resources) -> bool:
    return (
        avail.cpu >= need.cpu
        and avail.memory_mb >= need.memory_mb
        and avail.disk_mb >= need.disk_mb
    )


def _add(into: Resources, r: Resources) -> None:
    into.cpu += r.cpu
    into.memory_mb += r.memory_mb
    into.disk_mb += r.disk_mb


def _sub(into: Resources, r: Resources) -> None:
    into.cpu -= r.cpu
    into.memory_mb -= r.memory_mb
    into.disk_mb -= r.disk_mb


class Preemptor:
    """Finds allocations on one node to preempt for a placement."""

    def __init__(
        self,
        job_priority: int,
        namespace: str,
        job_id: str,
        plan=None,
    ) -> None:
        self.job_priority = job_priority
        self.namespace = namespace
        self.job_id = job_id
        # (ns, job_id, tg) -> count of allocs already being preempted in
        # this plan, feeding the max_parallel penalty.
        self._current_preemptions: dict[tuple[str, str, str], int] = {}
        if plan is not None:
            for allocs in plan.node_preemptions.values():
                for a in allocs:
                    key = (a.namespace, a.job_id, a.task_group)
                    self._current_preemptions[key] = (
                        self._current_preemptions.get(key, 0) + 1
                    )
        self._node_remaining: Optional[Resources] = None
        self._candidates: list[Allocation] = []
        self._details: dict[str, tuple[int, Resources]] = {}
        self._total_usage = Resources(cpu=0, memory_mb=0, disk_mb=0)

    def set_node(self, node: Node) -> None:
        avail = node.available_resources()
        self._node_remaining = Resources(
            cpu=avail.cpu, memory_mb=avail.memory_mb, disk_mb=avail.disk_mb
        )

    def set_candidates(self, allocs: list[Allocation]) -> None:
        self._candidates = []
        self._details = {}
        # usage of ALL allocs on the node — non-candidates (e.g. the
        # placing job's own allocs) still consume capacity and must be
        # subtracted from node-remaining, or the picker stops early
        self._total_usage = Resources(cpu=0, memory_mb=0, disk_mb=0)
        for alloc in allocs:
            _add(self._total_usage, alloc.comparable_resources())
            # never preempt the job being placed (its own old versions
            # are handled by the reconciler as stops, not preemptions)
            if alloc.job_id == self.job_id and alloc.namespace == self.namespace:
                continue
            max_parallel = 0
            job = alloc.job
            tg = job.lookup_task_group(alloc.task_group) if job else None
            if tg is not None and tg.migrate is not None:
                max_parallel = tg.migrate.max_parallel
            self._details[alloc.id] = (max_parallel, alloc.comparable_resources())
            self._candidates.append(alloc)

    def _num_preempted(self, alloc: Allocation) -> int:
        return self._current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0
        )

    def preempt_for_task_group(
        self, ask: Resources
    ) -> Optional[list[Allocation]]:
        """Pick allocations to evict so `ask` fits; None if impossible
        (reference PreemptForTaskGroup :198)."""
        if self._node_remaining is None:
            return None
        remaining = Resources(
            cpu=self._node_remaining.cpu,
            memory_mb=self._node_remaining.memory_mb,
            disk_mb=self._node_remaining.disk_mb,
        )
        _sub(remaining, self._total_usage)

        # Group preemptible candidates by priority tier, lowest first.
        tiers: dict[int, list[Allocation]] = {}
        for alloc in self._candidates:
            prio = alloc.job.priority if alloc.job else 50
            if self.job_priority - prio < PRIORITY_DELTA:
                continue
            tiers.setdefault(prio, []).append(alloc)
        if not tiers:
            return None

        need = Resources(cpu=ask.cpu, memory_mb=ask.memory_mb, disk_mb=ask.disk_mb)
        available = Resources(
            cpu=remaining.cpu,
            memory_mb=remaining.memory_mb,
            disk_mb=remaining.disk_mb,
        )
        best: list[Allocation] = []
        met = False
        for prio in sorted(tiers):
            group = list(tiers[prio])
            while group and not met:
                # pick the candidate closest to the remaining need
                best_idx, best_dist = -1, math.inf
                for i, alloc in enumerate(group):
                    max_parallel, used = self._details[alloc.id]
                    dist = score_for_task_group(
                        need, used, max_parallel, self._num_preempted(alloc)
                    )
                    if dist < best_dist:
                        best_dist, best_idx = dist, i
                chosen = group.pop(best_idx)
                used = self._details[chosen.id][1]
                _add(available, used)
                _sub(need, used)
                best.append(chosen)
                met = _superset(available, ask)
            if met:
                break
        if not met:
            return None
        return self._filter_superset(best, remaining, ask)

    def _filter_superset(
        self,
        chosen: list[Allocation],
        node_remaining: Resources,
        ask: Resources,
    ) -> list[Allocation]:
        """Drop picks made redundant by later, larger ones: keep the
        biggest-first prefix that still covers the ask (reference
        filterSuperset :702 sorts descending by distance-from-need and
        re-walks)."""
        chosen = sorted(
            chosen,
            key=lambda a: basic_resource_distance(
                ask, self._details[a.id][1]
            ),
        )
        kept: list[Allocation] = []
        available = Resources(
            cpu=node_remaining.cpu,
            memory_mb=node_remaining.memory_mb,
            disk_mb=node_remaining.disk_mb,
        )
        for alloc in chosen:
            if _superset(available, ask):
                break
            _add(available, self._details[alloc.id][1])
            kept.append(alloc)
        return kept
