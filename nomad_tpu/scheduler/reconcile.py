"""Declarative reconciliation: desired job state vs existing allocations.

Reference: scheduler/reconcile.go (983 LoC) + reconcile_util.go (598 —
allocSet/allocNameIndex). Computes, per task group: placements, stops,
in-place updates, destructive updates, migrations, delayed reschedules
(follow-up evals), and deployment bookkeeping.

Round-1 scope note: rolling deployments (max_parallel batching, auto-revert
bookkeeping, progress deadlines) are implemented; canary placement is tracked
through DeploymentState but canary-specific placement naming is simplified.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    TaskGroup,
    alloc_name,
    new_deployment,
    now_ns,
)
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    JOB_TYPE_BATCH,
    NODE_STATUS_DOWN,
    DeploymentState,
    DeploymentStatusUpdate,
    RescheduleEvent,
    RescheduleTracker,
)
from .util import tasks_updated

ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"


@dataclass
class PlacementRequest:
    """One alloc to place."""

    name: str
    task_group: TaskGroup
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    canary: bool = False
    # When rescheduling, penalize the previous node in ranking.
    penalty_node: str = ""
    min_job_version: int = 0
    lost: bool = False


@dataclass
class GroupSummary:
    place: int = 0
    stop: int = 0
    migrate: int = 0
    in_place: int = 0
    destructive: int = 0
    canary: int = 0
    ignore: int = 0


@dataclass
class ReconcileResults:
    place: list[PlacementRequest] = field(default_factory=list)
    destructive_update: list[tuple[Allocation, PlacementRequest]] = field(
        default_factory=list
    )
    inplace_update: list[Allocation] = field(default_factory=list)
    stop: list[tuple[Allocation, str, str]] = field(default_factory=list)
    # alloc_id -> followup eval id (delayed reschedule annotation)
    attr_updates: dict[str, str] = field(default_factory=dict)
    followup_evals: list[Evaluation] = field(default_factory=list)
    deployment: Optional[object] = None
    deployment_updates: list[DeploymentStatusUpdate] = field(default_factory=list)
    desired_tg_updates: dict[str, GroupSummary] = field(default_factory=dict)

    def total_changes(self) -> int:
        return (
            len(self.place) + len(self.destructive_update) + len(self.inplace_update)
            + len(self.stop)
        )


class AllocReconciler:
    """Reference: reconcile.go allocReconciler.Compute :184."""

    def __init__(
        self,
        job: Job,
        job_id: str,
        existing_allocs: list[Allocation],
        tainted: dict[str, Optional[Node]],
        eval_obj: Evaluation,
        deployment=None,
        batch: bool = False,
        now_fn=now_ns,
    ) -> None:
        self.job = job
        self.job_id = job_id
        self.allocs = existing_allocs
        self.tainted = tainted
        self.eval = eval_obj
        self.deployment = deployment.copy() if deployment is not None else None
        self.batch = batch
        self.now_ns = now_fn()
        self.results = ReconcileResults()

    # ------------------------------------------------------------------

    def compute(self) -> ReconcileResults:
        stopped = self.job.stopped()

        # Cancel deployments for stopped jobs or version mismatch.
        self._cancel_stale_deployments(stopped)

        groups = {tg.name: tg for tg in self.job.task_groups} if not stopped else {}
        by_group: dict[str, list[Allocation]] = {}
        for a in self.allocs:
            by_group.setdefault(a.task_group, []).append(a)

        deployment_complete = True
        for name in set(by_group) | set(groups):
            tg = groups.get(name)
            complete = self._compute_group(name, tg, by_group.get(name, []))
            deployment_complete = deployment_complete and complete

        # Mark a running deployment successful when every group is done.
        if (
            self.deployment is not None
            and deployment_complete
            and self.deployment.status == DEPLOYMENT_STATUS_RUNNING
            and not self.deployment.requires_promotion()
        ):
            self.results.deployment_updates.append(
                DeploymentStatusUpdate(
                    deployment_id=self.deployment.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description="Deployment completed successfully",
                )
            )
        return self.results

    def _cancel_stale_deployments(self, stopped: bool) -> None:
        d = self.deployment
        if d is None:
            return
        if stopped:
            self.results.deployment_updates.append(
                DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description="Cancelled because job is stopped",
                )
            )
            self.deployment = None
            return
        if d.job_version != self.job.version:
            self.results.deployment_updates.append(
                DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description="Cancelled due to newer version of job",
                )
            )
            self.deployment = None
            return
        if not d.active():
            self.deployment = None

    # ------------------------------------------------------------------

    def _compute_group(
        self, name: str, tg: Optional[TaskGroup], allocs: list[Allocation]
    ) -> bool:
        summary = self.results.desired_tg_updates.setdefault(name, GroupSummary())

        # Group removed or job stopped/dead: stop everything live.
        if tg is None:
            for a in allocs:
                if not a.terminal_status():
                    self.results.stop.append((a, ALLOC_NOT_NEEDED, ""))
                    summary.stop += 1
            return True

        # Partition by node taint and client status (reference:
        # reconcile_util.go filterByTainted + filterByRescheduleable).
        migrate: list[Allocation] = []
        lost: list[Allocation] = []
        resched_now: list[Allocation] = []
        resched_later: list[tuple[Allocation, int]] = []
        stable: list[Allocation] = []
        completed: list[Allocation] = []  # batch-only: ran to completion
        for a in allocs:
            if a.server_terminal_status():
                continue  # already stopping
            node = self.tainted.get(a.node_id, "ok")
            if node != "ok" and not a.client_terminal_status():
                if node is None or node.status == NODE_STATUS_DOWN:
                    lost.append(a)
                elif a.desired_transition.should_migrate():
                    # The drainer subsystem marks allocs for migration with
                    # rate limiting (reference reconcile_util.go
                    # filterByTainted: drain-node allocs migrate only once
                    # DesiredTransition.ShouldMigrate is set).
                    migrate.append(a)
                else:
                    stable.append(a)  # awaiting its drainer slot
                continue
            if (
                a.desired_transition.should_migrate()
                and not a.client_terminal_status()
            ):
                # `alloc stop` on a healthy node (reference
                # reconcile_util.go filterByTainted: an untainted alloc
                # with ShouldMigrate still migrates)
                migrate.append(a)
                continue
            if a.client_status == ALLOC_CLIENT_STATUS_FAILED:
                if a.desired_transition.should_force_reschedule():
                    resched_now.append(a)
                    continue
                when, eligible = a.next_reschedule_time()
                if eligible:
                    if when <= self.now_ns:
                        resched_now.append(a)
                    else:
                        resched_later.append((a, when))
                        stable.append(a)  # keeps its name until replaced
                else:
                    stable.append(a)  # attempts exhausted: leave it failed
            elif a.client_status == ALLOC_CLIENT_STATUS_COMPLETE:
                if self.batch:
                    completed.append(a)  # done; keeps name, never replaced
                # service: name is released and the count refilled below
            elif a.client_status == ALLOC_CLIENT_STATUS_LOST:
                pass  # replaced via missing-count placement
            else:
                stable.append(a)

        desired = tg.count

        # Name index over allocs that keep their names.
        used_names = (
            {a.name for a in stable}
            | {a.name for a in migrate}
            | {a.name for a in completed}
        )
        name_index = _NameIndex(self.job_id, name, desired, used_names)

        # --- stops: scale down ---
        keep = [a for a in stable]
        n_live = len(keep) + len(migrate)
        if n_live > desired:
            excess = n_live - desired
            # prefer stopping migrating allocs? reference stops highest indexes
            removable = sorted(
                keep, key=lambda a: (a.index() < desired, -a.index())
            )
            for a in removable[:excess]:
                self.results.stop.append((a, ALLOC_NOT_NEEDED, ""))
                summary.stop += 1
                keep.remove(a)
                name_index.release(a.name)
            n_live = len(keep) + len(migrate)

        # --- deployment handling ---
        dstate: Optional[DeploymentState] = None
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(name)

        # Updates among the kept allocs (job version drift).
        inplace: list[Allocation] = []
        destructive: list[Allocation] = []
        for a in keep:
            if a.job is None or a.job.version == self.job.version:
                summary.ignore += 1
                continue
            if tasks_updated(self.job, a.job, name):
                destructive.append(a)
            else:
                inplace.append(a)

        # Should we create a deployment? Service jobs with an update strategy
        # and pending destructive/new placements get one.
        requires_deploy = (
            tg.update is not None
            and not self.batch
            and self.job.type == "service"
            and not self.job.stopped()
            and (destructive or len(keep) + len(migrate) < desired or inplace)
        )
        if requires_deploy and self.deployment is None:
            self.deployment = new_deployment(self.job)
            self.results.deployment = self.deployment
        if self.deployment is not None and tg.update is not None:
            if name not in self.deployment.task_groups:
                dstate = DeploymentState(
                    auto_revert=tg.update.auto_revert,
                    auto_promote=tg.update.auto_promote,
                    desired_total=desired,
                    desired_canaries=tg.update.canary,
                    progress_deadline_s=tg.update.progress_deadline_s,
                )
                self.deployment.task_groups[name] = dstate
            else:
                dstate = self.deployment.task_groups[name]

        # In-place updates pass straight through.
        for a in inplace:
            updated = a.copy()
            updated.job = self.job
            self.results.inplace_update.append(updated)
            summary.in_place += 1

        # Destructive updates are limited by max_parallel of healthy slack.
        limit = self._update_limit(tg, dstate, len(destructive))
        for a in destructive[:limit]:
            req = PlacementRequest(
                name=a.name,
                task_group=tg,
                previous_alloc=a,
                min_job_version=self.job.version,
            )
            self.results.destructive_update.append((a, req))
            summary.destructive += 1
        for a in destructive[limit:]:
            summary.ignore += 1

        # Migrations: stop + replacement carrying the same name.
        for a in migrate:
            self.results.stop.append((a, ALLOC_MIGRATING, ""))
            summary.migrate += 1
            summary.place += 1  # queued accounting counts every placement
            self.results.place.append(
                PlacementRequest(
                    name=a.name,
                    task_group=tg,
                    previous_alloc=a,
                )
            )

        # Lost: mark lost (client status) + replacement.
        for a in lost:
            self.results.stop.append((a, ALLOC_LOST, ALLOC_CLIENT_STATUS_LOST))
            summary.stop += 1
            if not self.batch or a.client_status != ALLOC_CLIENT_STATUS_COMPLETE:
                self.results.place.append(
                    PlacementRequest(
                        name=a.name,
                        task_group=tg,
                        previous_alloc=a,
                        lost=True,
                    )
                )
                summary.place += 1

        # Reschedule now: replacement with penalty on previous node.
        for a in resched_now:
            self.results.place.append(
                PlacementRequest(
                    name=a.name,
                    task_group=tg,
                    previous_alloc=a,
                    reschedule=True,
                    penalty_node=a.node_id,
                )
            )
            summary.place += 1

        # Reschedule later: follow-up eval at the earliest eligible time.
        if resched_later:
            earliest = min(when for _, when in resched_later)
            followup = self.eval.create_failed_followup_eval(0)
            followup.wait_until_ns = earliest
            followup.triggered_by = "alloc-failure"
            self.results.followup_evals.append(followup)
            for a, _ in resched_later:
                self.results.attr_updates[a.id] = followup.id

        # New placements to reach the desired count.
        have = len(keep) + len(migrate) + len(resched_now) + len(completed)
        have += sum(1 for _ in lost)  # lost replacements already queued
        missing = max(0, desired - have)
        for _ in range(missing):
            idx = name_index.next()
            self.results.place.append(
                PlacementRequest(name=alloc_name(self.job_id, name, idx), task_group=tg)
            )
            summary.place += 1

        if dstate is not None:
            dstate.desired_total = desired

        # Group is deployment-complete if no pending work remains.
        complete = not (
            destructive
            or missing
            or migrate
            or lost
            or resched_now
            or resched_later
        )
        if dstate is not None and complete:
            complete = (
                dstate.desired_total <= dstate.healthy_allocs
            )
        return complete

    def _update_limit(
        self, tg: TaskGroup, dstate: Optional[DeploymentState], want: int
    ) -> int:
        """How many destructive updates may proceed this pass
        (reference: reconcile.go computeLimit :666)."""
        if tg.update is None or tg.update.max_parallel <= 0:
            return want
        limit = tg.update.max_parallel
        if dstate is not None:
            # Only as many as have proven healthy so far plus max_parallel,
            # minus those already placed and unhealthy.
            pending = dstate.placed_allocs - dstate.healthy_allocs
            limit = max(0, tg.update.max_parallel - pending)
        return min(want, limit)


class _NameIndex:
    """Bitmap-style name allocator (reference: reconcile_util.go
    allocNameIndex)."""

    def __init__(self, job_id: str, group: str, count: int, in_use: set[str]) -> None:
        self.job_id = job_id
        self.group = group
        self.count = count
        self.used_idx: set[int] = set()
        for name in in_use:
            idx = _index_of(name)
            if idx >= 0:
                self.used_idx.add(idx)
        self._cursor = 0

    def release(self, name: str) -> None:
        idx = _index_of(name)
        self.used_idx.discard(idx)
        if idx >= 0 and idx < self._cursor:
            self._cursor = idx

    def next(self) -> int:
        # lowest unused index first; cursor never rescans claimed ground
        i = self._cursor
        while i in self.used_idx:
            i += 1
        self.used_idx.add(i)
        self._cursor = i + 1
        return i


def _index_of(name: str) -> int:
    l, r = name.rfind("["), name.rfind("]")
    if l == -1 or r == -1:
        return -1
    try:
        return int(name[l + 1 : r])
    except ValueError:
        return -1
