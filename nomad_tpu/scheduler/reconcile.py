"""Declarative reconciliation: desired job state vs existing allocations.

Reference: scheduler/reconcile.go (983 LoC) + reconcile_util.go (598 —
allocSet/allocNameIndex). Computes, per task group: placements, stops,
in-place updates, destructive updates, migrations, delayed reschedules
(follow-up evals), and deployment bookkeeping.

Canary semantics follow the reference in full (reconcile.go:341
computeGroup): handleGroupCanaries stops stale canaries, canary state
gates destructive updates and placements (computeLimit :666), canaries
take names from NextCanaries (reconcile_util.go:519 — destructive
indexes first, then free, then overflow past count), computeStop prefers
stopping non-canary duplicates after promotion (:772), and non-canary
replacements placed during a canary deployment are downgraded to the old
job version (allocPlaceResult.downgradeNonCanary).

One deliberate departure: the reference identifies an OLDER deployment's
non-promoted canaries via its oldDeployment handle; here they are
recognized by the alloc canary flag plus a foreign deployment_id — our
store clears the flag on promotion (store.promote_deployment), so a
still-flagged canary of another deployment is exactly a non-promoted
stale canary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    TaskGroup,
    alloc_name,
    new_deployment,
    now_ns,
)
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    JOB_TYPE_BATCH,
    NODE_STATUS_DOWN,
    DeploymentState,
    DeploymentStatusUpdate,
    RescheduleEvent,
    RescheduleTracker,
)
from .util import tasks_updated

ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"


@dataclass(slots=True)
class PlacementRequest:
    """One alloc to place (slots: the batch paths mint 10^5 per c2m
    solve; slot storage halves per-object cost and memory)."""

    name: str
    task_group: TaskGroup
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    canary: bool = False
    # When rescheduling, penalize the previous node in ranking.
    penalty_node: str = ""
    min_job_version: int = 0
    lost: bool = False
    # Replacements made while a canary deployment is unpromoted must run
    # the OLD job version (reference allocPlaceResult.downgradeNonCanary):
    # the schedulers place with this job instead of the eval's current one.
    job_override: Optional[Job] = None


class PlacementRun:
    """A contiguous run of identical placement requests sharing ONE
    proto (the reconcile minting fast path): a fresh c2m fill is 10^5
    requests differing only in `name`, and minting 10^5 dataclass
    objects per eval was a named top-10 reconcile cost. The run stores
    the shared proto plus the names column; the TPU path reads exactly
    (count, names) — `_bucket_requests` passes a pure run through whole
    and the lowered group / SoA fast-mint consume the names column
    directly, so per-row request objects never exist on the fast path.
    Row access (`run[i]`, iteration, slicing) mints rows lazily for the
    paths that genuinely need them (the host stack, slow materialize,
    unplaced leftovers) at the same per-row cost as before."""

    __slots__ = ("proto", "names")

    def __init__(self, proto: PlacementRequest, names: list[str]) -> None:
        self.proto = proto
        self.names = names

    def __len__(self) -> int:
        return len(self.names)

    def _row(self, name: str) -> PlacementRequest:
        import dataclasses

        return dataclasses.replace(self.proto, name=name)

    def __getitem__(self, i):
        if isinstance(i, slice):
            # slices stay runs: spread sub-group splits slice the fill
            # and must not materialize rows to do it
            return PlacementRun(self.proto, self.names[i])
        return self._row(self.names[i])

    def __iter__(self):
        for nm in self.names:
            yield self._row(nm)


def iter_place_requests(seq):
    """Flatten a results.place list whose elements may be PlacementRun
    blocks into per-row requests (the host scheduler's shape)."""
    for item in seq:
        if isinstance(item, PlacementRun):
            yield from item
        else:
            yield item


def placement_rows(seq) -> int:
    """Total request rows in a list that may hold PlacementRun blocks."""
    return sum(
        len(item) if isinstance(item, PlacementRun) else 1 for item in seq
    )


@dataclass
class GroupSummary:
    place: int = 0
    stop: int = 0
    migrate: int = 0
    in_place: int = 0
    destructive: int = 0
    canary: int = 0
    ignore: int = 0


@dataclass
class ReconcileResults:
    place: list[PlacementRequest] = field(default_factory=list)
    destructive_update: list[tuple[Allocation, PlacementRequest]] = field(
        default_factory=list
    )
    inplace_update: list[Allocation] = field(default_factory=list)
    stop: list[tuple[Allocation, str, str]] = field(default_factory=list)
    # alloc_id -> followup eval id (delayed reschedule annotation)
    attr_updates: dict[str, str] = field(default_factory=dict)
    followup_evals: list[Evaluation] = field(default_factory=list)
    deployment: Optional[object] = None
    deployment_updates: list[DeploymentStatusUpdate] = field(default_factory=list)
    desired_tg_updates: dict[str, GroupSummary] = field(default_factory=dict)

    def total_changes(self) -> int:
        return (
            len(self.place) + len(self.destructive_update) + len(self.inplace_update)
            + len(self.stop)
        )


class AllocReconciler:
    """Reference: reconcile.go allocReconciler.Compute :184."""

    def __init__(
        self,
        job: Job,
        job_id: str,
        existing_allocs: list[Allocation],
        tainted: dict[str, Optional[Node]],
        eval_obj: Evaluation,
        deployment=None,
        batch: bool = False,
        now_fn=now_ns,
    ) -> None:
        self.job = job
        self.job_id = job_id
        self.allocs = existing_allocs
        self.tainted = tainted
        self.eval = eval_obj
        self.deployment = deployment.copy() if deployment is not None else None
        self.batch = batch
        self.now_ns = now_fn()
        self.results = ReconcileResults()
        self.deployment_paused = False
        self.deployment_failed = False

    # ------------------------------------------------------------------

    def compute(self) -> ReconcileResults:
        stopped = self.job.stopped()

        # Cancel deployments for stopped jobs or version mismatch.
        self._cancel_stale_deployments(stopped)

        if self.deployment is not None:
            self.deployment_paused = (
                self.deployment.status == DEPLOYMENT_STATUS_PAUSED
            )
            self.deployment_failed = (
                self.deployment.status == DEPLOYMENT_STATUS_FAILED
            )

        groups = {tg.name: tg for tg in self.job.task_groups} if not stopped else {}
        by_group: dict[str, list[Allocation]] = {}
        for a in self.allocs:
            by_group.setdefault(a.task_group, []).append(a)

        deployment_complete = True
        for name in set(by_group) | set(groups):
            tg = groups.get(name)
            complete = self._compute_group(name, tg, by_group.get(name, []))
            deployment_complete = deployment_complete and complete

        # Mark a running deployment successful when every group is done.
        if (
            self.deployment is not None
            and deployment_complete
            and self.deployment.status == DEPLOYMENT_STATUS_RUNNING
            and not self.deployment.requires_promotion()
        ):
            self.results.deployment_updates.append(
                DeploymentStatusUpdate(
                    deployment_id=self.deployment.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description="Deployment completed successfully",
                )
            )
        # A created deployment that needs promotion says so (reference
        # Compute :243 sets the running-needs-promotion description).
        d = self.results.deployment
        if d is not None and d.requires_promotion():
            if any(s.auto_promote for s in d.task_groups.values()):
                d.status_description = "Deployment is running pending automatic promotion"
            else:
                d.status_description = "Deployment is running but requires manual promotion"
        return self.results

    def _cancel_stale_deployments(self, stopped: bool) -> None:
        """reference cancelDeployments :258: stopped jobs and version
        mismatches cancel; successful clears; FAILED deployments remain
        attached (they gate placements via deployment_failed)."""
        d = self.deployment
        if d is None:
            return
        if stopped:
            if d.active():
                self.results.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=d.id,
                        status=DEPLOYMENT_STATUS_CANCELLED,
                        status_description="Cancelled because job is stopped",
                    )
                )
            self.deployment = None
            return
        if d.job_version != self.job.version:
            if d.active():
                self.results.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=d.id,
                        status=DEPLOYMENT_STATUS_CANCELLED,
                        status_description="Cancelled due to newer version of job",
                    )
                )
            self.deployment = None
            return
        if d.status not in (
            DEPLOYMENT_STATUS_RUNNING,
            DEPLOYMENT_STATUS_PAUSED,
            DEPLOYMENT_STATUS_FAILED,
        ):
            self.deployment = None

    # ------------------------------------------------------------------

    def _compute_group(
        self, name: str, tg: Optional[TaskGroup], allocs: list[Allocation]
    ) -> bool:
        summary = self.results.desired_tg_updates.setdefault(name, GroupSummary())

        # Group removed or job stopped/dead: stop everything live.
        if tg is None:
            for a in allocs:
                if not a.terminal_status():
                    self.results.stop.append((a, ALLOC_NOT_NEEDED, ""))
                    summary.stop += 1
            return True

        desired = tg.count
        strategy = tg.update

        # Deployment state for the group (reference computeGroup :362):
        # a fresh dstate is prepared even before deciding to create the
        # deployment; it attaches only if needed.
        dstate: Optional[DeploymentState] = None
        existing_deployment = False
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(name)
            existing_deployment = dstate is not None
        if not existing_deployment and strategy is not None:
            dstate = DeploymentState(
                auto_revert=strategy.auto_revert,
                auto_promote=strategy.auto_promote,
                progress_deadline_s=strategy.progress_deadline_s,
            )

        all_ = [a for a in allocs if not a.server_terminal_status()]

        # Batch jobs ignore terminal allocs from OLDER job versions
        # entirely (reference filterOldTerminalAllocs :589): a completed
        # run of v(N-1) must be neither counted nor churned when vN
        # arrives — its name frees up for the new version's instances.
        if self.batch:
            old_terminal = [
                a
                for a in all_
                if a.job is not None
                and a.job.version < self.job.version
                and a.terminal_status()
            ]
            if old_terminal:
                summary.ignore += len(old_terminal)
                dropped = {a.id for a in old_terminal}
                all_ = [a for a in all_ if a.id not in dropped]

        # Canaries: stop stale ones, collect the current deployment's
        # (reference handleGroupCanaries :614).
        canaries, all_ = self._handle_group_canaries(name, all_, summary)
        canary_ids = {a.id for a in canaries}

        # --- partition by node taint (reference filterByTainted) ---
        untainted: list[Allocation] = []
        migrate: list[Allocation] = []
        lost: list[Allocation] = []
        for a in all_:
            node = self.tainted.get(a.node_id, "ok")
            if node != "ok" and not a.client_terminal_status():
                if node is None or node.status == NODE_STATUS_DOWN:
                    lost.append(a)
                elif a.desired_transition.should_migrate():
                    # The drainer marks allocs for migration with rate
                    # limiting; unmarked drain-node allocs wait their turn.
                    migrate.append(a)
                else:
                    untainted.append(a)
                continue
            if (
                a.desired_transition.should_migrate()
                and not a.client_terminal_status()
            ):
                # `alloc stop` / migrate on a healthy node
                migrate.append(a)
                continue
            untainted.append(a)

        # --- rescheduleability (reference filterByRescheduleable) ---
        kept: list[Allocation] = []
        resched_now: list[Allocation] = []
        resched_later: list[tuple[Allocation, int]] = []
        for a in untainted:
            if a.next_allocation and a.terminal_status():
                continue  # already replaced
            if a.client_status == ALLOC_CLIENT_STATUS_FAILED:
                if a.desired_transition.should_force_reschedule():
                    resched_now.append(a)
                    continue
                when, eligible = a.next_reschedule_time()
                if eligible:
                    if when <= self.now_ns:
                        resched_now.append(a)
                    else:
                        resched_later.append((a, when))
                        kept.append(a)  # keeps its name until replaced
                else:
                    kept.append(a)  # attempts exhausted: stays failed
            elif a.client_status == ALLOC_CLIENT_STATUS_COMPLETE:
                if self.batch:
                    kept.append(a)  # ran successfully: holds its name
                # service: name released, count refilled below
            elif a.client_status == ALLOC_CLIENT_STATUS_LOST:
                pass  # replaced via missing-count placement
            else:
                kept.append(a)
        untainted = kept

        # Name index over allocs that keep names (reference :403).
        used_names = (
            {a.name for a in untainted}
            | {a.name for a in migrate}
            | {a.name for a in resched_now}
            | {a.name for a in lost}
        )
        name_index = _NameIndex(self.job_id, name, desired, used_names)

        canary_state = (
            dstate is not None
            and dstate.desired_canaries != 0
            and not dstate.promoted
        )

        # --- stops (reference computeStop :772) ---
        stop_ids = self._compute_stop(
            tg, name_index, untainted, migrate, lost, canaries, canary_state,
            summary,
        )
        untainted = [a for a in untainted if a.id not in stop_ids]
        migrate = [a for a in migrate if a.id not in stop_ids]

        # --- updates (reference computeUpdates :879) ---
        inplace: list[Allocation] = []
        destructive: list[Allocation] = []
        for a in untainted:
            if a.job is None or a.job.version == self.job.version:
                summary.ignore += 1
            elif tasks_updated(self.job, a.job, name):
                destructive.append(a)
            else:
                inplace.append(a)
        for a in inplace:
            updated = a.copy()
            updated.job = self.job
            self.results.inplace_update.append(updated)
            summary.in_place += 1
        if not existing_deployment and dstate is not None:
            dstate.desired_total += len(destructive) + len(inplace)

        # Remove canaries from placement decisions (reference :422).
        if canary_state:
            untainted = [a for a in untainted if a.id not in canary_ids]

        # Destructive updates pending and fewer canaries than asked:
        # create canaries (reference :426-446).
        canaries_promoted = dstate is not None and dstate.promoted
        require_canary = (
            len(destructive) != 0
            and strategy is not None
            and strategy.canary > 0
            and len(canaries) < strategy.canary
            and not canaries_promoted
            # canaries ride deployments, which only service jobs get —
            # a batch job with a stray update stanza must not churn
            and not self.batch
            and self.job.type == "service"
        )
        if require_canary:
            dstate.desired_canaries = strategy.canary
        if require_canary and not self.deployment_paused and not self.deployment_failed:
            n = strategy.canary - len(canaries)
            summary.canary += n
            for cname in name_index.next_canaries(n, canaries, destructive):
                self.results.place.append(
                    PlacementRequest(name=cname, task_group=tg, canary=True)
                )
                summary.place += 1
        canary_state = (
            dstate is not None
            and dstate.desired_canaries != 0
            and not dstate.promoted
        )

        limit = self._compute_limit(tg, untainted, destructive, migrate, canary_state)

        # --- placements (reference computePlacements :712) ---
        downgrade = self._downgrade_job(untainted) if canary_state else None

        def _downgrade_for(a: Optional[Allocation]) -> Optional[Job]:
            if not canary_state:
                return None
            if a is not None:
                if a.deployment_status is not None and a.deployment_status.canary:
                    return None  # canaries replace at the new version
                return a.job if a.job is not None and a.job.version != self.job.version else None
            return downgrade

        def _tg_for(job_override: Optional[Job]) -> TaskGroup:
            if job_override is not None:
                old_tg = job_override.lookup_task_group(name)
                if old_tg is not None:
                    return old_tg
            return tg

        place: list[PlacementRequest] = []
        for a in resched_now:
            ov = _downgrade_for(a)
            place.append(
                PlacementRequest(
                    name=a.name,
                    task_group=_tg_for(ov),
                    previous_alloc=a,
                    reschedule=True,
                    penalty_node=a.node_id,
                    canary=(
                        a.deployment_status is not None
                        and a.deployment_status.canary
                    ),
                    job_override=ov,
                    min_job_version=a.job.version if a.job else 0,
                )
            )
        existing = len(untainted) + len(migrate) + len(resched_now)
        for a in lost:
            if existing >= desired:
                break  # at count: do not replace remaining lost
            existing += 1
            ov = _downgrade_for(a)
            place.append(
                PlacementRequest(
                    name=a.name,
                    task_group=_tg_for(ov),
                    previous_alloc=a,
                    lost=True,
                    canary=(
                        a.deployment_status is not None
                        and a.deployment_status.canary
                    ),
                    job_override=ov,
                )
            )
        if existing < desired:
            # the bulk fill (a fresh c2m job mints its whole count
            # here): ONE shared-proto PlacementRun instead of 10^5
            # per-row request objects — the TPU path reads only
            # (count, names) and the SoA fast-mint consumes the names
            # column directly; rows materialize lazily on the host /
            # leftover paths only
            ov = _downgrade_for(None)
            tg_ov = _tg_for(ov)
            prefix = f"{self.job_id}.{name}["
            place.append(
                PlacementRun(
                    PlacementRequest(
                        name="", task_group=tg_ov, job_override=ov
                    ),
                    [
                        f"{prefix}{idx}]"
                        for idx in name_index.next_n(desired - existing)
                    ],
                )
            )
        if not existing_deployment and dstate is not None:
            dstate.desired_total += placement_rows(place)

        deployment_place_ready = (
            not self.deployment_paused
            and not self.deployment_failed
            and not canary_state
        )
        if deployment_place_ready:
            self.results.place.extend(place)
            n_place = placement_rows(place)
            summary.place += n_place
            for a in resched_now:
                self.results.stop.append((a, ALLOC_RESCHEDULED, ""))
                summary.stop += 1
            limit -= min(n_place, limit)
        else:
            # Paused/failed/canarying deployments still replace lost
            # allocs and reschedule failures (reference :477-505), except
            # failures belonging to the failed deployment itself.
            for req in place:
                if isinstance(req, PlacementRun):
                    # fresh-fill runs are never lost/reschedule rows
                    continue
                if req.lost:
                    self.results.place.append(req)
                    summary.place += 1
                elif req.reschedule:
                    prev = req.previous_alloc
                    if self.deployment_failed and prev is not None and (
                        self.deployment is not None
                        and prev.deployment_id == self.deployment.id
                    ):
                        continue
                    self.results.place.append(req)
                    summary.place += 1
                    self.results.stop.append((prev, ALLOC_RESCHEDULED, ""))
                    summary.stop += 1

        # --- destructive updates (reference :507-522) ---
        if deployment_place_ready:
            n = min(len(destructive), limit)
            for a in sorted(destructive, key=lambda x: x.index())[:n]:
                req = PlacementRequest(
                    name=a.name,
                    task_group=tg,
                    previous_alloc=a,
                    min_job_version=self.job.version,
                )
                self.results.destructive_update.append((a, req))
                summary.destructive += 1
            summary.ignore += len(destructive) - n
        else:
            summary.ignore += len(destructive)

        # --- migrations (reference :524-541) ---
        for a in sorted(migrate, key=lambda x: x.index()):
            self.results.stop.append((a, ALLOC_MIGRATING, ""))
            summary.migrate += 1
            summary.place += 1  # queued accounting counts every placement
            ov = _downgrade_for(a)
            self.results.place.append(
                PlacementRequest(
                    name=a.name,
                    task_group=_tg_for(ov),
                    previous_alloc=a,
                    canary=(
                        a.deployment_status is not None
                        and a.deployment_status.canary
                    ),
                    job_override=ov,
                    min_job_version=a.job.version if a.job else 0,
                )
            )

        # Reschedule later: follow-up eval at the earliest eligible time.
        if resched_later:
            earliest = min(when for _, when in resched_later)
            followup = self.eval.create_failed_followup_eval(0)
            followup.wait_until_ns = earliest
            followup.triggered_by = "alloc-failure"
            self.results.followup_evals.append(followup)
            for a, _ in resched_later:
                self.results.attr_updates[a.id] = followup.id

        # --- create the deployment if warranted (reference :543-570) ---
        updating_spec = bool(destructive) or bool(inplace)
        had_running = any(
            a.job is not None and a.job.version == self.job.version
            for a in all_
        )
        if (
            not existing_deployment
            and strategy is not None
            and not self.batch
            and self.job.type == "service"
            and dstate is not None
            and dstate.desired_total != 0
            and (not had_running or updating_spec)
        ):
            if self.deployment is None:
                self.deployment = new_deployment(self.job)
                self.results.deployment = self.deployment
            self.deployment.task_groups[name] = dstate

        # --- deployment completeness (reference :571-585) ---
        complete = (
            not destructive
            and not inplace
            and not place
            and not migrate
            and not resched_now
            and not resched_later
            and not require_canary
        )
        if complete and self.deployment is not None and dstate is not None:
            if dstate.healthy_allocs < max(
                dstate.desired_total, dstate.desired_canaries
            ) or (dstate.desired_canaries > 0 and not dstate.promoted):
                complete = False
        return complete

    def _handle_group_canaries(
        self, group: str, all_: list[Allocation], summary: GroupSummary
    ) -> tuple[list[Allocation], list[Allocation]]:
        """Stop unneeded canaries, return (current canaries, remaining
        allocs) — reference handleGroupCanaries :614."""
        stop_ids: set[str] = set()
        cur = self.deployment
        cur_id = cur.id if cur is not None else ""
        # Non-promoted canaries from an OLDER deployment: the canary flag
        # survives only while unpromoted (store.promote_deployment clears
        # it), so flagged canaries of a foreign deployment are stale.
        for a in all_:
            if (
                a.deployment_status is not None
                and a.deployment_status.canary
                and a.deployment_id != cur_id
                and not a.terminal_status()
            ):
                stop_ids.add(a.id)
        # Non-promoted canaries of a FAILED current deployment.
        if cur is not None and cur.status == DEPLOYMENT_STATUS_FAILED:
            for ds in cur.task_groups.values():
                if not ds.promoted:
                    stop_ids.update(ds.placed_canaries)
        for a in all_:
            if a.id in stop_ids and not a.terminal_status():
                self.results.stop.append((a, ALLOC_NOT_NEEDED, ""))
                summary.stop += 1
        all_ = [a for a in all_ if a.id not in stop_ids]

        canaries: list[Allocation] = []
        if cur is not None and cur.status != DEPLOYMENT_STATUS_FAILED:
            ds = cur.task_groups.get(group)
            ids = set(ds.placed_canaries) if ds is not None else set()
            gone: set[str] = set()
            for a in all_:
                if a.id not in ids:
                    continue
                node = self.tainted.get(a.node_id, "ok")
                if node != "ok" and not a.client_terminal_status():
                    # Tainted canaries just stop; replacements come from
                    # the canary count, not migration.
                    if node is None or node.status == NODE_STATUS_DOWN:
                        self.results.stop.append(
                            (a, ALLOC_LOST, ALLOC_CLIENT_STATUS_LOST)
                        )
                    else:
                        self.results.stop.append((a, ALLOC_MIGRATING, ""))
                    summary.stop += 1
                    gone.add(a.id)
                    continue
                canaries.append(a)
            all_ = [a for a in all_ if a.id not in gone]
        return canaries, all_

    def _compute_stop(
        self,
        tg: TaskGroup,
        name_index: "_NameIndex",
        untainted: list[Allocation],
        migrate: list[Allocation],
        lost: list[Allocation],
        canaries: list[Allocation],
        canary_state: bool,
        summary: GroupSummary,
    ) -> set[str]:
        """reference computeStop :772. Returns ids marked for stopping."""
        stop_ids: set[str] = set()
        for a in lost:
            stop_ids.add(a.id)
            self.results.stop.append((a, ALLOC_LOST, ALLOC_CLIENT_STATUS_LOST))
            summary.stop += 1

        canary_ids = {a.id for a in canaries}
        pool = (
            [a for a in untainted if a.id not in canary_ids]
            if canary_state
            else list(untainted)
        )
        remove = len(pool) + len(migrate) - tg.count
        if remove <= 0:
            return stop_ids

        pool = [a for a in pool if not a.terminal_status()]

        def _stop(a: Allocation, desc: str = ALLOC_NOT_NEEDED) -> None:
            stop_ids.add(a.id)
            self.results.stop.append((a, desc, ""))
            summary.stop += 1

        # After promotion, prefer stopping old allocs that share a
        # canary's name (the duplicates the canaries were named after).
        if not canary_state and canaries:
            canary_names = {a.name for a in canaries}
            for a in list(pool):
                if a.id in canary_ids or a.name not in canary_names:
                    continue
                _stop(a)
                pool.remove(a)
                remove -= 1
                if remove == 0:
                    return stop_ids

        # Prefer stopping migrating allocs (highest names first).
        if migrate and remove > 0:
            by_idx = sorted(migrate, key=lambda x: -x.index())
            for a in by_idx:
                _stop(a)
                name_index.release(a.name)
                remove -= 1
                if remove == 0:
                    return stop_ids

        # Highest-index names among the rest.
        if remove > 0:
            highest = {
                a.name
                for a in sorted(pool, key=lambda x: -x.index())[:remove]
            }
            for a in list(pool):
                if a.name in highest:
                    _stop(a)
                    pool.remove(a)
                    name_index.release(a.name)
                    remove -= 1
                    if remove == 0:
                        return stop_ids
            # Duplicate names can leave stragglers; stop anything left.
            for a in list(pool):
                _stop(a)
                pool.remove(a)
                remove -= 1
                if remove == 0:
                    return stop_ids
        return stop_ids

    def _compute_limit(
        self,
        tg: TaskGroup,
        untainted: list[Allocation],
        destructive: list[Allocation],
        migrate: list[Allocation],
        canary_state: bool,
    ) -> int:
        """reference computeLimit :666."""
        if (
            tg.update is None
            or tg.update.max_parallel <= 0
            or len(destructive) + len(migrate) == 0
        ):
            return tg.count
        if self.deployment_paused or self.deployment_failed:
            return 0
        if canary_state:
            return 0
        limit = tg.update.max_parallel
        if self.deployment is not None:
            for a in untainted:
                if a.deployment_id != self.deployment.id:
                    continue
                ds = a.deployment_status
                if ds is not None and ds.healthy is False:
                    return 0  # an unhealthy alloc halts the rollout
                if ds is None or ds.healthy is not True:
                    limit -= 1
        return max(0, limit)

    def _downgrade_job(self, untainted: list[Allocation]) -> Optional[Job]:
        """The old job version non-canary replacements should run while
        canaries are unpromoted (reference downgradedJobForPlacement)."""
        for a in untainted:
            if a.deployment_status is not None and a.deployment_status.canary:
                continue
            if a.job is not None and a.job.version != self.job.version:
                return a.job
        return None


class _NameIndex:
    """Bitmap-style name allocator (reference: reconcile_util.go
    allocNameIndex)."""

    def __init__(self, job_id: str, group: str, count: int, in_use: set[str]) -> None:
        self.job_id = job_id
        self.group = group
        self.count = count
        self.used_idx: set[int] = set()
        for name in in_use:
            idx = _index_of(name)
            if idx >= 0:
                self.used_idx.add(idx)
        self._cursor = 0

    def release(self, name: str) -> None:
        idx = _index_of(name)
        self.used_idx.discard(idx)
        if idx >= 0 and idx < self._cursor:
            self._cursor = idx

    def next(self) -> int:
        # lowest unused index first; cursor never rescans claimed ground
        i = self._cursor
        while i in self.used_idx:
            i += 1
        self.used_idx.add(i)
        self._cursor = i + 1
        return i

    def next_n(self, n: int) -> list[int]:
        """n lowest unused indexes in one pass — identical to n
        successive next() calls, without the per-call overhead."""
        used = self.used_idx
        i = self._cursor
        if not used:
            # fresh mint (nothing claimed anywhere): the run is one
            # contiguous block — range() beats 10^5 set probes on the
            # bulk-fill hot path
            out = list(range(i, i + n))
            used.update(out)
            self._cursor = i + n
            return out
        out: list[int] = []
        add = used.add
        ap = out.append
        for _ in range(n):
            while i in used:
                i += 1
            add(i)
            ap(i)
            i += 1
        self._cursor = i
        return out

    def next_canaries(
        self, n: int, existing: list, destructive: list
    ) -> list[str]:
        """Names for n new canaries (reference reconcile_util.go:519
        NextCanaries): prefer the indexes of destructive allocs (their
        names free up on promotion), then unused indexes, then overflow
        past count so promotion shuts the overflow down."""
        out: list[str] = []
        existing_names = {a.name for a in existing}

        def _try(idx: int) -> bool:
            cname = alloc_name(self.job_id, self.group, idx)
            if cname in existing_names:
                return False
            out.append(cname)
            self.used_idx.add(idx)
            return len(out) == n

        didx = sorted(
            {
                i
                for a in destructive
                if 0 <= (i := _index_of(a.name)) < self.count
            }
        )
        for i in didx:
            if _try(i):
                return out
        for i in range(self.count):
            if i in self.used_idx and i not in didx:
                continue
            if i in didx:
                continue  # already tried above
            if _try(i):
                return out
        i = self.count
        while len(out) < n:
            out.append(alloc_name(self.job_id, self.group, i))
            i += 1
        return out


def _index_of(name: str) -> int:
    l, r = name.rfind("["), name.rfind("]")
    if l == -1 or r == -1:
        return -1
    try:
        return int(name[l + 1 : r])
    except ValueError:
        return -1
