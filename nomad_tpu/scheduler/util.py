"""Scheduler helpers.

Reference: scheduler/util.go — diffSystemAllocs :230, readyNodesInDCs :267,
retryMax :305, progressMade :331, taintedNodes :340, shuffleNodes :366,
tasksUpdated (in-place-update check) :993.
"""

from __future__ import annotations

import fnmatch
import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..structs import Allocation, Job, Node, TaskGroup
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    NODE_STATUS_DOWN,
)


def ready_nodes_in_dcs(state, datacenters: list[str]) -> tuple[list[Node], dict[str, int]]:
    """All ready nodes whose datacenter matches any of the job's DC globs.

    Returns (nodes, per-DC available counts). Reference: util.go:267.
    """
    out: list[Node] = []
    dc_counts: dict[str, int] = {}
    # Glob-match once per DISTINCT datacenter, not once per node — a
    # 10k-node cluster has a handful of DCs but this is on the hot path.
    dc_ok: dict[str, bool] = {}
    for node in state.nodes():
        if not node.ready():
            continue
        ok = dc_ok.get(node.datacenter)
        if ok is None:
            ok = any(
                fnmatch.fnmatchcase(node.datacenter, dc) for dc in datacenters
            )
            dc_ok[node.datacenter] = ok
        if not ok:
            continue
        out.append(node)
        dc_counts[node.datacenter] = dc_counts.get(node.datacenter, 0) + 1
    return out, dc_counts


def tainted_nodes(state, allocs: list[Allocation]) -> dict[str, Node]:
    """Nodes referenced by allocs that are down or draining (reference :340).
    A node id mapping to None means the node no longer exists."""
    out: dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.status == NODE_STATUS_DOWN or node.drain:
            out[alloc.node_id] = node
    return out


def shuffle_nodes(nodes: list[Node]) -> None:
    random.shuffle(nodes)


def retry_max(max_attempts: int, fn: Callable[[], tuple[bool, object]],
              reset_fn: Optional[Callable[[], bool]] = None) -> object:
    """Run fn until done, up to max_attempts, resetting the budget whenever
    reset_fn reports progress (reference: util.go retryMax :305)."""
    attempts = 0
    while attempts < max_attempts:
        done, result = fn()
        if done:
            return result
        if reset_fn is not None and reset_fn():
            attempts = 0
            continue
        attempts += 1
    raise SchedulerRetryError(f"maximum attempts reached ({max_attempts})")


class SchedulerRetryError(Exception):
    pass


def update_non_terminal_allocs_to_lost(
    plan, tainted: dict[str, Optional[Node]], allocs: list[Allocation]
) -> None:
    """Mark non-terminal allocs on down nodes as lost (reference:
    generic_sched.go:350 / util.go updateNonTerminalAllocsToLost)."""
    for alloc in allocs:
        node = tainted.get(alloc.node_id, "missing")
        if node == "missing":
            continue
        if node is not None and node.status != NODE_STATUS_DOWN:
            continue
        if alloc.desired_status in ("stop", "evict") and alloc.client_status in (
            "running",
            "pending",
        ):
            plan.append_stopped_alloc(alloc, "alloc is lost since its node is down",
                                      ALLOC_CLIENT_STATUS_LOST)


def annotate_previous_alloc(alloc, req) -> None:
    """previous_allocation + reschedule-tracker wiring, shared by the host
    placement loop (generic.py computePlacements), the dense materializer
    (tpu/solver.py _build_alloc), and the small-batch host path — one
    copy so reschedule-event semantics cannot drift between backends."""
    from ..structs.structs import RescheduleEvent, RescheduleTracker, now_ns

    prev = req.previous_alloc
    if prev is None:
        return
    alloc.previous_allocation = prev.id
    if req.reschedule:
        tracker = (
            prev.reschedule_tracker.copy()
            if prev.reschedule_tracker
            else RescheduleTracker()
        )
        tracker.events.append(
            RescheduleEvent(
                reschedule_time_ns=now_ns(),
                prev_alloc_id=prev.id,
                prev_node_id=prev.node_id,
            )
        )
        # bounded history (reference updateRescheduleTracker:
        # maxPastRescheduleEvents = 5)
        if len(tracker.events) > 5:
            tracker.events = tracker.events[-5:]
        alloc.reschedule_tracker = tracker


def tasks_updated(job_a: Job, job_b: Job, tg_name: str) -> bool:
    """Do two job versions differ such that the group's allocs must be
    destructively replaced? (reference: util.go tasksUpdated :993).
    In-place-safe changes: count, metadata-only, reschedule/restart policy.
    """
    a = job_a.lookup_task_group(tg_name)
    b = job_b.lookup_task_group(tg_name)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if [n.copy() for n in a.networks] != [n.copy() for n in b.networks]:
        return True
    if {k: v.copy() for k, v in a.volumes.items()} != {
        k: v.copy() for k, v in b.volumes.items()
    }:
        return True
    if a.ephemeral_disk.copy() != b.ephemeral_disk.copy():
        return True
    for ta in a.tasks:
        tb = b.lookup_task(ta.name)
        if tb is None:
            return True
        if (
            ta.driver != tb.driver
            or ta.user != tb.user
            or ta.config != tb.config
            or ta.env != tb.env
            or ta.meta != tb.meta
            or [str(c) for c in ta.constraints] != [str(c) for c in tb.constraints]
            or [a_.copy() for a_ in ta.artifacts] != [b_.copy() for b_ in tb.artifacts]
            or [t_.copy() for t_ in ta.templates] != [t_.copy() for t_ in tb.templates]
            or ta.resources.cpu != tb.resources.cpu
            or ta.resources.cores != tb.resources.cores
            or ta.resources.memory_max_mb != tb.resources.memory_max_mb
            or ta.resources.memory_mb != tb.resources.memory_mb
            or [n.copy() for n in ta.resources.networks]
            != [n.copy() for n in tb.resources.networks]
            or [d.copy() for d in ta.resources.devices]
            != [d.copy() for d in tb.resources.devices]
            or [s.copy() for s in ta.services] != [s.copy() for s in tb.services]
            or ta.kill_timeout_s != tb.kill_timeout_s
            or (ta.lifecycle is None) != (tb.lifecycle is None)
        ):
            return True
    # group-level constraints/affinities/spreads
    if [str(c) for c in a.constraints] != [str(c) for c in b.constraints]:
        return True
    return False


# ---------------------------------------------------------------------------
# System-scheduler diff
# ---------------------------------------------------------------------------


@dataclass
class DiffResult:
    place: list = field(default_factory=list)  # (tg, node, existing-terminal alloc|None)
    update: list = field(default_factory=list)  # (alloc, tg) destructive
    ignore: list = field(default_factory=list)
    stop: list = field(default_factory=list)  # (alloc, reason)
    lost: list = field(default_factory=list)


def diff_system_allocs(
    job: Job,
    nodes: list[Node],
    tainted: dict[str, Optional[Node]],
    allocs: list[Allocation],
    terminal_by_node: dict[str, dict[str, Allocation]],
) -> DiffResult:
    """Per-node diff for system jobs: every eligible node should run every
    group exactly once (reference: util.go diffSystemAllocs :230)."""
    result = DiffResult()
    eligible = {n.id: n for n in nodes}
    by_node: dict[str, list[Allocation]] = {}
    for a in allocs:
        by_node.setdefault(a.node_id, []).append(a)

    required = {tg.name: tg for tg in job.task_groups}

    for node_id, node_allocs in by_node.items():
        for alloc in node_allocs:
            if alloc.terminal_status():
                continue
            tg = required.get(alloc.task_group)
            if tg is None or job.stopped():
                result.stop.append((alloc, "alloc not required"))
                continue
            node = tainted.get(alloc.node_id, "ok")
            if node != "ok":
                if node is None or node.status == NODE_STATUS_DOWN:
                    result.lost.append(alloc)
                elif node.drain and (
                    not node.drain_strategy.ignore_system_jobs
                ):
                    # Stop only once the drainer has marked the alloc —
                    # it withholds the mark until every service alloc has
                    # drained (system drains last; drainer.py run_once).
                    if alloc.desired_transition.should_migrate():
                        result.stop.append((alloc, "node is draining"))
                    else:
                        result.ignore.append(alloc)
                else:
                    result.ignore.append(alloc)
                continue
            if node_id not in eligible:
                result.stop.append((alloc, "node is ineligible"))
                continue
            if alloc.job is not None and alloc.job.version != job.version:
                if tasks_updated(job, alloc.job, tg.name):
                    result.update.append((alloc, tg))
                else:
                    result.ignore.append(alloc)
            else:
                result.ignore.append(alloc)

    if not job.stopped():
        for node_id, node in eligible.items():
            live_groups = {
                a.task_group
                for a in by_node.get(node_id, [])
                if not a.terminal_status()
            }
            for tg_name, tg in required.items():
                if tg_name in live_groups:
                    continue
                terminal = terminal_by_node.get(node_id, {}).get(tg_name)
                result.place.append((tg, node, terminal))
    return result
