"""Hard-constraint feasibility checking.

Reference: scheduler/feasible.go — ConstraintChecker :709, checkConstraint
:785, DriverChecker :433, HostVolumeChecker :132, NetworkChecker :341,
DeviceChecker :1173, DistinctHosts/DistinctProperty :505/:604,
FeasibilityWrapper :1029 (computed-class memoization).

Redesign note: the reference chains lazy Go iterators; here each checker is a
predicate object and the stack composes them lazily with generators. The same
predicate set is what the TPU backend compiles into the dense feasibility-mask
tensor (nomad_tpu/scheduler/tpu/lower.py) — comparison/set predicates lower to
vectorized ops over interned attribute codes, regex/version predicates are
evaluated host-side per (class, constraint) and broadcast.
"""

from __future__ import annotations

import re
from typing import Iterable, Iterator, Optional

from ..structs import Constraint, Node
from ..structs.structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_IS_NOT_SET,
    CONSTRAINT_IS_SET,
    CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER,
    CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL,
    CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
    RequestedDevice,
    Task,
    TaskGroup,
    VolumeRequest,
)
from .context import (
    ELIGIBILITY_ELIGIBLE,
    ELIGIBILITY_ESCAPED,
    ELIGIBILITY_INELIGIBLE,
    ELIGIBILITY_UNKNOWN,
    EvalContext,
)

FILTER_CONSTRAINT_HOST_VOLUMES = "missing compatible host volumes"
FILTER_CONSTRAINT_DRIVERS = "missing drivers"
FILTER_CONSTRAINT_DEVICES = "missing devices"
FILTER_CONSTRAINT_NETWORK = "missing network"


# ---------------------------------------------------------------------------
# Attribute resolution
# ---------------------------------------------------------------------------


def resolve_target(node: Node, target: str) -> tuple[str, bool]:
    """Resolve a constraint LTarget against a node.

    Accepts '${node.datacenter}', '${attr.kernel.name}', '${meta.rack}',
    '${node.unique.id}' etc.; a bare string resolves to itself (literal).
    Reference: scheduler/feasible.go resolveTarget :745.
    """
    if not (target.startswith("${") and target.endswith("}")):
        return target, True
    inner = target[2:-1]
    if inner == "node.unique.id":
        return node.id, True
    if inner == "node.unique.name":
        return node.name, True
    if inner == "node.datacenter":
        return node.datacenter, True
    if inner == "node.class":
        return node.node_class, True
    if inner.startswith("attr.unique."):
        val = node.attributes.get(inner[len("attr.") :])
        if val is None:
            val = node.attributes.get(inner[len("attr.unique.") :])
        return (val or "", val is not None)
    if inner.startswith("attr."):
        val = node.attributes.get(inner[len("attr.") :])
        return (val or "", val is not None)
    if inner.startswith("meta.unique."):
        val = node.meta.get(inner[len("meta.") :])
        if val is None:
            val = node.meta.get(inner[len("meta.unique.") :])
        return (val or "", val is not None)
    if inner.startswith("meta."):
        val = node.meta.get(inner[len("meta.") :])
        return (val or "", val is not None)
    if inner.startswith("driver."):
        val = node.attributes.get(inner)
        return (val or "", val is not None)
    return "", False


# ---------------------------------------------------------------------------
# Version comparison (lightweight semver-compatible)
# ---------------------------------------------------------------------------

_VERSION_RE = re.compile(r"^\s*v?(\d+(?:\.\d+)*)(?:[-.]?(.*))?$")


def parse_version(s: str) -> Optional[tuple[tuple[int, ...], str]]:
    m = _VERSION_RE.match(s)
    if not m:
        return None
    nums = tuple(int(p) for p in m.group(1).split("."))
    pre = m.group(2) or ""
    return nums, pre


def _cmp_version(a: tuple[tuple[int, ...], str], b: tuple[tuple[int, ...], str]) -> int:
    an, ap = a
    bn, bp = b
    # pad numeric parts
    ln = max(len(an), len(bn))
    an = an + (0,) * (ln - len(an))
    bn = bn + (0,) * (ln - len(bn))
    if an != bn:
        return -1 if an < bn else 1
    # a pre-release sorts before its release
    if ap == bp:
        return 0
    if ap == "":
        return 1
    if bp == "":
        return -1
    return -1 if ap < bp else 1


def check_version_constraint(
    ver_str: str, constraint_str: str, strict_semver: bool = False
) -> bool:
    """Evaluate a version constraint like '>= 1.2, < 2.0' or '~> 1.2'."""
    ver = parse_version(ver_str)
    if ver is None:
        return False
    if strict_semver and ver[1]:
        # semver operand: a pre-release only satisfies a range when the
        # constraint itself names a pre-release with the same numeric core.
        core_matched = False
        for part in constraint_str.split(","):
            m = re.match(r"^(>=|<=|!=|~>|=|>|<)?\s*(.+)$", part.strip())
            if m:
                target = parse_version(m.group(2))
                if target is not None and target[1] and target[0] == ver[0]:
                    core_matched = True
                    break
        if not core_matched:
            return False
    for part in constraint_str.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^(>=|<=|!=|~>|=|>|<)?\s*(.+)$", part)
        if not m:
            return False
        op = m.group(1) or "="
        target = parse_version(m.group(2))
        if target is None:
            return False
        c = _cmp_version(ver, target)
        if op == "=" and c != 0:
            return False
        if op == "!=" and c == 0:
            return False
        if op == ">" and c <= 0:
            return False
        if op == ">=" and c < 0:
            return False
        if op == "<" and c >= 0:
            return False
        if op == "<=" and c > 0:
            return False
        if op == "~>":
            # pessimistic: >= target and < bump of second-to-last component
            if c < 0:
                return False
            tn = list(target[0])
            if len(tn) > 1:
                upper = tn[:-1]
                upper[-1] += 1
            else:
                upper = [tn[0] + 1]
            if _cmp_version(ver, (tuple(upper), "")) >= 0:
                return False
    return True


# ---------------------------------------------------------------------------
# Scalar constraint evaluation
# ---------------------------------------------------------------------------


def _try_numeric(lval: str, rval: str) -> Optional[tuple[float, float]]:
    try:
        return float(lval), float(rval)
    except (TypeError, ValueError):
        return None


def check_constraint(
    ctx: EvalContext,
    operand: str,
    lval: str,
    rval: str,
    l_found: bool,
    r_found: bool,
) -> bool:
    """Evaluate one constraint (reference: feasible.go checkConstraint :785)."""
    if operand in ("=", "==", "is"):
        return l_found and r_found and lval == rval
    if operand in ("!=", "not"):
        return lval != rval
    if operand in ("<", "<=", ">", ">="):
        if not (l_found and r_found):
            return False
        nums = _try_numeric(lval, rval)
        if nums is not None:
            a, b = nums
        else:
            a, b = lval, rval  # lexical
        return {
            "<": a < b,
            "<=": a <= b,
            ">": a > b,
            ">=": a >= b,
        }[operand]
    if operand == CONSTRAINT_IS_SET:
        return l_found
    if operand == CONSTRAINT_IS_NOT_SET:
        return not l_found
    if not (l_found and r_found):
        return False
    if operand == CONSTRAINT_REGEX:
        pat = ctx.regex(rval)
        return pat is not None and pat.search(lval) is not None
    if operand == CONSTRAINT_VERSION:
        return check_version_constraint(lval, rval)
    if operand == CONSTRAINT_SEMVER:
        return check_version_constraint(lval, rval, strict_semver=True)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        have = {p.strip() for p in lval.split(",")}
        want = [p.strip() for p in rval.split(",")]
        return all(w in have for w in want)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        have = {p.strip() for p in lval.split(",")}
        want = [p.strip() for p in rval.split(",")]
        return any(w in have for w in want)
    return False


def node_matches_constraint(ctx: EvalContext, node: Node, c: Constraint) -> bool:
    lval, l_found = resolve_target(node, c.ltarget)
    rval, r_found = resolve_target(node, c.rtarget)
    return check_constraint(ctx, c.operand, lval, rval, l_found, r_found)


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------


class FeasibilityChecker:
    """A named hard-constraint predicate over nodes."""

    def feasible(self, node: Node) -> tuple[bool, str]:
        raise NotImplementedError


class ConstraintChecker(FeasibilityChecker):
    def __init__(self, ctx: EvalContext, constraints: list[Constraint]) -> None:
        self.ctx = ctx
        self.constraints = constraints

    def feasible(self, node: Node) -> tuple[bool, str]:
        for c in self.constraints:
            if c.operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
                continue  # handled by dedicated iterators
            if not node_matches_constraint(self.ctx, node, c):
                return False, str(c)
        return True, ""


class DriverChecker(FeasibilityChecker):
    """Every task's driver must be detected and healthy on the node
    (reference: feasible.go:433)."""

    def __init__(self, ctx: EvalContext, drivers: set[str]) -> None:
        self.ctx = ctx
        self.drivers = drivers

    def feasible(self, node: Node) -> tuple[bool, str]:
        for driver in self.drivers:
            info = node.drivers.get(driver)
            if info is not None:
                if not (info.detected and info.healthy):
                    return False, FILTER_CONSTRAINT_DRIVERS
                continue
            # Fall back to fingerprint attribute driver.<name> = "1"/"true"
            raw = node.attributes.get(f"driver.{driver}", "")
            if raw not in ("1", "true"):
                return False, FILTER_CONSTRAINT_DRIVERS
        return True, ""


class HostVolumeChecker(FeasibilityChecker):
    """Node must expose every requested host volume (reference :132).

    When a matching volume is REGISTERED in the cluster volume table, its
    access mode also gates placement: a single-node-writer volume with a
    live write claim rejects further writers anywhere (the claim itself
    attaches at plan apply; the volume watcher releases it when the
    claiming alloc terminates)."""

    def __init__(self, ctx: EvalContext, volumes: dict[str, VolumeRequest],
                 namespace: str = "default") -> None:
        self.ctx = ctx
        self.namespace = namespace
        self.asks = [
            v for v in volumes.values() if v.type in ("", "host")
        ]
        # registered volumes per ask (node screening happens per node:
        # a pinned volume only serves allocs on its node)
        self._registered: dict[str, list] = {}
        state = getattr(ctx, "state", None)
        if state is not None and hasattr(state, "volumes_by_name"):
            for ask in self.asks:
                vols = state.volumes_by_name(namespace, ask.source)
                if vols:
                    self._registered[ask.source] = vols

    def feasible(self, node: Node) -> tuple[bool, str]:
        for ask in self.asks:
            vol = node.host_volumes.get(ask.source)
            if vol is None:
                return False, FILTER_CONSTRAINT_HOST_VOLUMES
            if vol.read_only and not ask.read_only:
                return False, FILTER_CONSTRAINT_HOST_VOLUMES
            registered = self._registered.get(ask.source)
            if registered:
                usable = [
                    v for v in registered if v.node_id in ("", node.id)
                ]
                if usable and not any(
                    v.claimable(ask.read_only)[0] for v in usable
                ):
                    return False, FILTER_CONSTRAINT_HOST_VOLUMES
        return True, ""


FILTER_CONSTRAINT_CSI_VOLUMES = "missing CSI volume/plugin"


class CSIVolumeChecker(FeasibilityChecker):
    """CSI-type volume asks (reference: feasible.go CSIVolumeChecker :213):
    the node must run a healthy node-capable instance of the volume's
    plugin, and the registered volume must admit another claim of the
    requested mode."""

    def __init__(self, ctx: EvalContext, volumes: dict[str, VolumeRequest],
                 namespace: str = "default") -> None:
        self.asks = [v for v in volumes.values() if v.type == "csi"]
        self._registered: dict[str, list] = {}
        state = getattr(ctx, "state", None)
        if state is not None and hasattr(state, "volumes_by_name"):
            for ask in self.asks:
                self._registered[ask.source] = [
                    v
                    for v in state.volumes_by_name(namespace, ask.source)
                    if v.type == "csi"
                ]

    def feasible(self, node: Node) -> tuple[bool, str]:
        for ask in self.asks:
            vols = self._registered.get(ask.source) or []
            ok = False
            for vol in vols:
                info = node.csi_plugins.get(vol.plugin_id)
                if not info or not info.get("healthy") \
                        or not info.get("node", True):
                    continue
                if vol.claimable(ask.read_only)[0]:
                    ok = True
                    break
            if not ok:
                return False, FILTER_CONSTRAINT_CSI_VOLUMES
        return True, ""


class NetworkChecker(FeasibilityChecker):
    """Node must be able to satisfy static port + bandwidth asks
    (reference: feasible.go NetworkChecker :341)."""

    def __init__(self, ctx: EvalContext, tg: TaskGroup) -> None:
        self.ctx = ctx
        self.asks = list(tg.networks)
        for t in tg.tasks:
            self.asks.extend(t.resources.networks)

    def feasible(self, node: Node) -> tuple[bool, str]:
        if not self.asks:
            return True, ""
        total_mbits = sum(a.mbits for a in self.asks)
        static_ports = [p.value for a in self.asks for p in a.reserved_ports]
        if not node.resources.networks:
            if total_mbits > 0 or static_ports:
                return False, FILTER_CONSTRAINT_NETWORK
            return True, ""
        cap = max(n.mbits for n in node.resources.networks)
        if total_mbits > cap:
            return False, FILTER_CONSTRAINT_NETWORK
        reserved = set(node.reserved.reserved_ports)
        if any(p in reserved for p in static_ports):
            return False, FILTER_CONSTRAINT_NETWORK
        return True, ""


class DeviceChecker(FeasibilityChecker):
    """Node must have enough healthy matching device instances
    (reference: feasible.go DeviceChecker :1173)."""

    def __init__(self, ctx: EvalContext, tg: TaskGroup) -> None:
        self.ctx = ctx
        self.asks: list[RequestedDevice] = []
        for t in tg.tasks:
            self.asks.extend(t.resources.devices)

    def feasible(self, node: Node) -> tuple[bool, str]:
        if not self.asks:
            return True, ""
        for ask in self.asks:
            satisfied = False
            for dev in node.resources.devices:
                if not dev.matches(ask):
                    continue
                healthy = sum(1 for i in dev.instances if i.healthy)
                if healthy < ask.count:
                    continue
                if ask.constraints:
                    ok = True
                    for c in ask.constraints:
                        lval, lf = _resolve_device_target(dev, c.ltarget)
                        rval, rf = _resolve_device_target(dev, c.rtarget)
                        if not check_constraint(self.ctx, c.operand, lval, rval, lf, rf):
                            ok = False
                            break
                    if not ok:
                        continue
                satisfied = True
                break
            if not satisfied:
                return False, FILTER_CONSTRAINT_DEVICES
        return True, ""


def _resolve_device_target(dev, target: str) -> tuple[str, bool]:
    if not (target.startswith("${") and target.endswith("}")):
        return target, True
    inner = target[2:-1]
    if inner.startswith("device.attr."):
        val = dev.attributes.get(inner[len("device.attr.") :])
        return (str(val) if val is not None else "", val is not None)
    if inner == "device.model":
        return dev.name, True
    if inner == "device.vendor":
        return dev.vendor, True
    if inner == "device.type":
        return dev.type, True
    return "", False


class DistinctHostsChecker(FeasibilityChecker):
    """distinct_hosts (reference :505). Job-level: no two allocs of the job
    on one node. Group-level: no two allocs of that group on one node."""

    def __init__(
        self, ctx: EvalContext, job_id: str, tg_name: str, job_level: bool
    ) -> None:
        self.ctx = ctx
        self.job_id = job_id
        self.tg_name = tg_name
        self.job_level = job_level

    def feasible(self, node: Node) -> tuple[bool, str]:
        for alloc in self.ctx.proposed_allocs(node.id):
            if alloc.job_id != self.job_id:
                continue
            if self.job_level or alloc.task_group == self.tg_name:
                return False, f"{CONSTRAINT_DISTINCT_HOSTS} constraint"
        return True, ""


# ---------------------------------------------------------------------------
# Source iterators + memoizing wrapper
# ---------------------------------------------------------------------------


def feasibility_pipeline(
    ctx: EvalContext,
    nodes: Iterable[Node],
    job_checkers: list[FeasibilityChecker],
    tg_checkers: list[FeasibilityChecker],
    tg_name: str,
    metrics=None,
) -> Iterator[Node]:
    """Lazily yield feasible nodes, memoizing per computed class.

    Reference: feasible.go FeasibilityWrapper :1029 — job-level and
    tg-level checkers are skipped for classes already proven (in)eligible;
    escaped constraints disable the memo.
    """
    elig = ctx.eligibility
    for node in nodes:
        ctx.metrics_nodes_evaluated += 1
        klass = node.computed_class

        j_status = elig.job_status(klass)
        if j_status == ELIGIBILITY_INELIGIBLE:
            if metrics is not None:
                metrics.filter_node(node, "")
            continue
        if j_status in (ELIGIBILITY_UNKNOWN, ELIGIBILITY_ESCAPED):
            ok = True
            for checker in job_checkers:
                feasible, reason = checker.feasible(node)
                if not feasible:
                    ok = False
                    if metrics is not None:
                        metrics.filter_node(node, reason)
                    break
            if j_status == ELIGIBILITY_UNKNOWN:
                elig.set_job_eligibility(ok, klass)
            if not ok:
                continue

        t_status = elig.task_group_status(tg_name, klass)
        if t_status == ELIGIBILITY_INELIGIBLE:
            if metrics is not None:
                metrics.filter_node(node, "")
            continue
        if t_status in (ELIGIBILITY_UNKNOWN, ELIGIBILITY_ESCAPED):
            ok = True
            for checker in tg_checkers:
                feasible, reason = checker.feasible(node)
                if not feasible:
                    ok = False
                    if metrics is not None:
                        metrics.filter_node(node, reason)
                    break
            if t_status == ELIGIBILITY_UNKNOWN:
                elig.set_task_group_eligibility(ok, tg_name, klass)
            if not ok:
                continue

        yield node
