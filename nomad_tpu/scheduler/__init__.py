"""Scheduler registry and interfaces.

Reference: scheduler/scheduler.go — BuiltinSchedulers :23, NewScheduler :32,
and the Scheduler/State/Planner interface trio :55-132.

The Planner contract (implemented by the worker against the plan queue, and
by the test Harness directly):
    submit_plan(plan) -> (PlanResult, new_state | None)
    update_eval(eval) -> None
    create_eval(eval) -> None
    refresh_state(min_index) -> StateSnapshot

The `tpu` entry is the deliberate architectural departure: a batched JAX
backend registered through the same factory seam (see scheduler/tpu/).
"""

from __future__ import annotations

from .context import EvalContext, SchedulerConfig
from .generic import BatchScheduler, GenericScheduler
from .system import SysBatchScheduler, SystemScheduler

BUILTIN_SCHEDULERS = {
    "service": GenericScheduler,
    "batch": BatchScheduler,
    "system": SystemScheduler,
    "sysbatch": SysBatchScheduler,
}


def _tpu_factories():
    # Imported lazily so the control plane never pays the jax import unless
    # the TPU backend is actually selected.
    from .tpu import TPUBatchScheduler, TPUGenericScheduler
    from .tpu.system import TPUSysbatchScheduler, TPUSystemScheduler

    return {
        "service": TPUGenericScheduler,
        "batch": TPUBatchScheduler,
        # system/sysbatch vectorize the per-node walk into one lowered
        # feasibility + capacity pass (falling back per node only for
        # ports/devices/preemption) — drain-churn loads no longer run
        # half host-bound under the TPU backend.
        "system": TPUSystemScheduler,
        "sysbatch": TPUSysbatchScheduler,
    }


def new_scheduler(name: str, logger, state, planner, config=None):
    if config is not None and getattr(config, "backend", "host") == "tpu":
        factory = _tpu_factories().get(name)
    else:
        factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler '{name}'")
    return factory(logger, state, planner, config)
