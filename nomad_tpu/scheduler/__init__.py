"""Scheduler registry and interfaces.

Reference: scheduler/scheduler.go — BuiltinSchedulers :23, NewScheduler :32,
and the Scheduler/State/Planner interface trio :55-132.

The Planner contract (implemented by the worker against the plan queue, and
by the test Harness directly):
    submit_plan(plan) -> (PlanResult, new_state | None)
    update_eval(eval) -> None
    create_eval(eval) -> None
    refresh_state(min_index) -> StateSnapshot

The `tpu` entry is the deliberate architectural departure: a batched JAX
backend registered through the same factory seam (see scheduler/tpu/).
"""

from __future__ import annotations

from .context import EvalContext, SchedulerConfig
from .generic import BatchScheduler, GenericScheduler
from .system import SysBatchScheduler, SystemScheduler

BUILTIN_SCHEDULERS = {
    "service": GenericScheduler,
    "batch": BatchScheduler,
    "system": SystemScheduler,
    "sysbatch": SysBatchScheduler,
}


def new_scheduler(name: str, logger, state, planner, config=None):
    factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler '{name}'")
    return factory(logger, state, planner, config)
