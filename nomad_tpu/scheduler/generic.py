"""Service/batch scheduler.

Reference: scheduler/generic_sched.go — Process :125, process :216,
computeJobAllocs :332, computePlacements :472, selectNextOption :773.
Processes one evaluation: snapshot → reconcile → place each missing alloc via
the stack → submit plan → retry on partial commit → blocked eval on failure.
"""

from __future__ import annotations

from typing import Optional

from ..structs import (
    AllocMetric,
    Allocation,
    Evaluation,
    Plan,
    generate_uuid,
    now_ns,
)
from ..structs.structs import (
    AllocDeploymentStatus,
    DEPLOYMENT_STATUS_FAILED,
    EVAL_TRIGGER_FORCE_EVAL,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_ALLOC_STOP,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    EVAL_TRIGGER_FAILED_FOLLOWUP,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_MAX_PLANS,
    EVAL_TRIGGER_NODE_DRAIN,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_PERIODIC_JOB,
    EVAL_TRIGGER_PREEMPTION,
    EVAL_TRIGGER_QUEUED_ALLOCS,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    EVAL_TRIGGER_ROLLING_UPDATE,
    EVAL_TRIGGER_SCALING,
    EVAL_TRIGGER_SCHEDULED,
    JOB_TYPE_BATCH,
    RescheduleEvent,
    RescheduleTracker,
)
from .context import EvalContext, SchedulerConfig
from .reconcile import AllocReconciler, PlacementRequest
from .stack import GenericStack
from .util import (
    SchedulerRetryError,
    annotate_previous_alloc,
    ready_nodes_in_dcs,
    retry_max,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class GenericScheduler:
    """One instance processes one evaluation (stateless between evals)."""

    scheduler_type = "service"

    def __init__(self, logger, state, planner, config: Optional[SchedulerConfig] = None):
        self.logger = logger
        self.state = state  # snapshot; refreshed on partial commit
        self.planner = planner
        self.config = config or SchedulerConfig()
        self.batch = self.scheduler_type == JOB_TYPE_BATCH
        self.eval: Optional[Evaluation] = None
        self.plan: Optional[Plan] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None
        self.failed_tg_allocs: dict[str, AllocMetric] = {}
        self.queued_allocs: dict[str, int] = {}
        self.followup_evals: list[Evaluation] = []
        self.blocked: Optional[Evaluation] = None
        self.plan_result = None

    # ------------------------------------------------------------------

    def process(self, eval_obj: Evaluation) -> None:
        self.eval = eval_obj
        trigger = eval_obj.triggered_by
        if trigger not in (
            EVAL_TRIGGER_JOB_REGISTER,
            EVAL_TRIGGER_JOB_DEREGISTER,
            EVAL_TRIGGER_NODE_DRAIN,
            EVAL_TRIGGER_NODE_UPDATE,
            EVAL_TRIGGER_ALLOC_STOP,
            EVAL_TRIGGER_ROLLING_UPDATE,
            EVAL_TRIGGER_QUEUED_ALLOCS,
            EVAL_TRIGGER_PERIODIC_JOB,
            EVAL_TRIGGER_MAX_PLANS,
            EVAL_TRIGGER_DEPLOYMENT_WATCHER,
            EVAL_TRIGGER_RETRY_FAILED_ALLOC,
            EVAL_TRIGGER_FORCE_EVAL,
            EVAL_TRIGGER_FAILED_FOLLOWUP,
            EVAL_TRIGGER_PREEMPTION,
            EVAL_TRIGGER_SCALING,
            EVAL_TRIGGER_SCHEDULED,
        ):
            self._set_status(
                EVAL_STATUS_FAILED, f"scheduler cannot handle '{trigger}' evaluation"
            )
            return

        limit = (
            MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else MAX_SERVICE_SCHEDULE_ATTEMPTS
        )
        try:
            retry_max(limit, self._process_attempt, self._progress_made)
        except SchedulerRetryError as e:
            # Exhausted plan attempts: mark failed and roll a new blocked eval
            # so the job eventually retries (reference: generic_sched.go:161).
            if self.eval.status != "blocked":
                follow = self.eval.create_blocked_eval({}, True, "", self.failed_tg_allocs)
                follow.snapshot_index = self.state.index
                follow.triggered_by = EVAL_TRIGGER_MAX_PLANS
                follow.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
                self.planner.create_eval(follow)
            self._set_status(EVAL_STATUS_FAILED, str(e))
            return
        self._set_status(EVAL_STATUS_COMPLETE, "")

    def _progress_made(self) -> bool:
        result = self.plan_result
        made = result is not None and not result.is_no_op()
        if result is not None and result.refresh_index > 0:
            self.state = self.planner.refresh_state(result.refresh_index)
        return made

    # ------------------------------------------------------------------

    def _process_attempt(self) -> tuple[bool, object]:
        eval_obj = self.eval
        job = self.state.job_by_id(eval_obj.namespace, eval_obj.job_id)
        self.plan = eval_obj.make_plan(job)
        self.plan.snapshot_index = self.state.index
        self.failed_tg_allocs = {}
        self.queued_allocs = {}
        self.followup_evals = []
        self.plan_result = None
        self.ctx = EvalContext(self.state, self.plan, self.logger, self.config)
        self.stack = GenericStack(self.batch, self.ctx)

        if job is not None and not job.stopped():
            nodes, dc_counts = ready_nodes_in_dcs(self.state, job.datacenters)
            self.stack.set_nodes(nodes)
            self.stack.set_job(job)
            self._dc_counts = dc_counts
        else:
            self._dc_counts = {}

        if not self._compute_job_allocs(job):
            return False, None

        # No-op plan: done.
        if self.plan.is_no_op() and not self.followup_evals:
            if self.queued_allocs and any(self.queued_allocs.values()):
                self._ensure_blocked_eval()
            return True, None

        # Follow-up evals must exist before allocs reference them.
        for fe in self.followup_evals:
            self.planner.create_eval(fe)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result
        if new_state is not None:
            self.state = new_state

        full, expected, actual = result.full_commit(self.plan)
        if not full:
            # Partial commit: stay in the retry loop (progress_made refreshes
            # the snapshot; the next attempt recomputes queued counts fresh).
            return False, None
        if self.queued_allocs and any(self.queued_allocs.values()):
            self._ensure_blocked_eval()
        return True, None

    # ------------------------------------------------------------------

    def _annotate_plan(self, results) -> None:
        """Plan dry-run annotations (reference scheduler/annotate.go:38):
        per-group create/destroy/in-place/destructive/migrate counts.
        Shared by the host and TPU schedulers so their plan output cannot
        drift."""
        import dataclasses as _dc

        self.plan.annotations = {
            "DesiredTGUpdates": {
                tg: _dc.asdict(s)
                for tg, s in results.desired_tg_updates.items()
            }
        }

    def _compute_job_allocs(self, job) -> bool:
        eval_obj = self.eval
        allocs = self.state.allocs_by_job(eval_obj.namespace, eval_obj.job_id)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        deployment = None
        if job is not None:
            deployment = self.state.latest_deployment_by_job(
                eval_obj.namespace, eval_obj.job_id
            )
            if deployment is not None and not deployment.active() and (
                deployment.status != DEPLOYMENT_STATUS_FAILED
            ):
                # failed deployments stay attached: they gate placements
                # and their canaries need cleanup (reconcile.py)
                deployment = None

        reconciler = AllocReconciler(
            job if job is not None else _tombstone_job(eval_obj),
            eval_obj.job_id,
            allocs,
            tainted,
            eval_obj,
            deployment=deployment,
            batch=self.batch,
        )
        results = reconciler.compute()

        if eval_obj.annotate_plan:
            self._annotate_plan(results)

        self.followup_evals = results.followup_evals
        if results.deployment is not None:
            self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        for alloc, desc, client_status in results.stop:
            self.plan.append_stopped_alloc(alloc, desc, client_status)

        for updated in results.inplace_update:
            self.plan.append_alloc(updated, updated.job)

        # Annotate delayed-reschedule allocs with their follow-up eval.
        for alloc_id, eval_id in results.attr_updates.items():
            existing = self.state.alloc_by_id(alloc_id)
            if existing is not None:
                annotated = existing.copy()
                annotated.followup_eval_id = eval_id
                self.plan.append_alloc(annotated, annotated.job)

        # Destructive updates: stop old, then place replacement.
        place_requests: list[PlacementRequest] = []
        for old, req in results.destructive_update:
            self.plan.append_stopped_alloc(
                old, "alloc not needed due to job update", ""
            )
            place_requests.append(req)
        # results.place may carry PlacementRun blocks (the reconcile
        # minting fast path); the host loop below wants per-row requests
        from .reconcile import iter_place_requests

        place_requests.extend(iter_place_requests(results.place))

        if job is None or job.stopped():
            return True

        queued: dict[str, int] = {
            tg: s.place + s.destructive for tg, s in results.desired_tg_updates.items()
        }
        active_deployment = self.state.latest_deployment_by_job(job.namespace, job.id)
        if active_deployment is not None and (
            not active_deployment.active()
            or active_deployment.job_version != job.version
        ):
            active_deployment = None

        # --- placements (reference: computePlacements :472) ---
        for req in place_requests:
            tg = req.task_group
            metric = AllocMetric(nodes_available=dict(self._dc_counts))
            start = now_ns()
            penalty = {req.penalty_node} if req.penalty_node else None
            option = None
            prev = req.previous_alloc
            if (
                tg.ephemeral_disk.sticky
                and prev is not None
                and prev.node_id
            ):
                # sticky disk: prefer the previous node (reference
                # computePlacements -> SelectOptions.PreferredNodes)
                # a tainted/drained previous node is never preferred
                # (reference selectNextOption's preferred-node filter)
                prev_node = self.state.node_by_id(prev.node_id)
                if prev_node is not None and prev_node.ready():
                    option = self.stack.select(
                        tg, penalty_nodes=penalty, metrics=metric,
                        selected_nodes=[prev_node],
                    )
            if option is None:
                option = self.stack.select(tg, penalty_nodes=penalty, metrics=metric)
            if option is None and self.ctx.scheduler_config.preemption_enabled(
                job.type
            ):
                # Second pass with eviction enabled (reference
                # generic_sched.go:773 selectNextOption → :786 re-run
                # with preemption).
                option = self.stack.select(
                    tg, penalty_nodes=penalty, metrics=metric, evict=True
                )
            metric.allocation_time_ns = now_ns() - start
            metric.nodes_evaluated = self.ctx.metrics_nodes_evaluated

            if option is None:
                # Failed placement: coalesce metrics per task group.
                existing = self.failed_tg_allocs.get(tg.name)
                if existing is not None:
                    existing.coalesced_failures += 1
                else:
                    self.failed_tg_allocs[tg.name] = metric
                continue

            pjob = req.job_override if req.job_override is not None else job
            alloc = Allocation(
                id=generate_uuid(),
                namespace=self.eval.namespace,
                eval_id=self.eval.id,
                name=req.name,
                node_id=option.node.id,
                node_name=option.node.name,
                job_id=pjob.id,
                job=pjob,
                task_group=tg.name,
                resources=option.alloc_resources,
                metrics=metric,
                desired_status="run",
                client_status="pending",
            )
            if req.canary:
                alloc.deployment_status = AllocDeploymentStatus(canary=True)
            if self.plan.deployment is not None and tg.update is not None:
                alloc.deployment_id = self.plan.deployment.id
                dstate = self.plan.deployment.task_groups.get(tg.name)
                if dstate is not None:
                    dstate.placed_allocs += 1
            elif job.type == "service" and active_deployment is not None:
                alloc.deployment_id = active_deployment.id

            if option.preempted_allocs:
                # Reference generic_sched.go:795 handlePreemptions: the
                # evictions ride the plan; the applier re-verifies and
                # the FSM flips them to desired=evict.
                alloc.preempted_allocations = [
                    p.id for p in option.preempted_allocs
                ]
                for p in option.preempted_allocs:
                    self.plan.append_preempted_alloc(p, alloc.id)

            annotate_previous_alloc(alloc, req)
            self.plan.append_alloc(alloc, pjob)
            queued[tg.name] = max(0, queued.get(tg.name, 0) - 1)

        self.queued_allocs = queued
        self.eval.queued_allocations = queued
        return True

    # ------------------------------------------------------------------

    def _ensure_blocked_eval(self) -> None:
        if self.blocked is not None or not self.failed_tg_allocs:
            return
        e = self.eval.create_blocked_eval(
            self.ctx.eligibility.get_classes(),
            self.ctx.eligibility.has_escaped(),
            self.ctx.eligibility.quota_reached,
            self.failed_tg_allocs,
        )
        # The snapshot this placement failed against: blocked_evals uses
        # it to detect capacity that appeared while we were scheduling.
        e.snapshot_index = self.state.index
        e.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(e)
        self.blocked = e

    def _set_status(self, status: str, desc: str) -> None:
        updated = self.eval.copy()
        updated.status = status
        updated.status_description = desc
        updated.failed_tg_allocs = self.failed_tg_allocs
        updated.queued_allocations = self.queued_allocs
        if self.blocked is not None:
            updated.blocked_eval = self.blocked.id
        self.planner.update_eval(updated)


class BatchScheduler(GenericScheduler):
    scheduler_type = "batch"


def _tombstone_job(eval_obj: Evaluation):
    """A stand-in for a deregistered job so the reconciler stops everything."""
    from ..structs import Job

    j = Job(id=eval_obj.job_id, namespace=eval_obj.namespace, stop=True)
    j.task_groups = []
    return j
