"""Host microsolve: the compact placement kernel in plain numpy.

Interactive-scale solves (a single `job register`, a handful of
placements) were paying the full tensor pipeline — lower, pad, upload,
device round-trip, readback — when the problem fits in a few cache
lines. Below the n·g microsolve threshold the solver runs THIS kernel
instead: the same waterfill math as kernels.solve_placement_compact
(same f32 ScoreFit, same stable score sort, same node-index-ordered
compact instance readback), executed synchronously on the host with
zero device round-trip and zero jit involvement. The dense path's
lowering, materialization, spread splits, overflow repair, and failure
accounting are all shared — only the kernel invocation differs — so a
micro solve is the dense solve, minus the tunnel.

Not a third semantics: differential coverage pins this kernel to the
jax compact kernel's outcomes (tests/test_tpu_solver.py), the same way
the sharded kernels are pinned to the single-chip one.
"""

from __future__ import annotations

import numpy as np

# must match kernels.py exactly: scores are computed in f32 and ties
# break toward the lower node index (stable sort on -score)
NEG_INF = np.float32(-1e30)
LN10 = np.float32(2.302585092994046)
_BIG = np.int64(1 << 30)
_F1 = np.float32(1.0)
_F18 = np.float32(18.0)
_F20 = np.float32(20.0)


def solve_placement_compact_micro(
    cap: np.ndarray,
    used: np.ndarray,
    groups: list,
    max_count: int,
):
    """Place all groups on the host; mirror of solve_placement_compact.

    cap/used: [N, 3] integer (unpadded — the micro path never buckets);
    groups: [(ask [3] i64, count, feasible [N] bool, bias [N] f32,
    units_cap [N] i64)] in priority order. Returns
    (inst_node [G, max_count] i32 (-1 past each group's placed total),
    over [N] bool (always False — integer math cannot overflow),
    used' [N, 3] int64).
    """
    n = cap.shape[0]
    used = used.astype(np.int64, copy=True)
    cap = cap.astype(np.int64, copy=False)
    # group-invariant hoists: capacity never changes inside one solve
    safe_cap = np.maximum(cap.astype(np.float32), _F1)
    g = len(groups)
    inst = np.full((g, max_count), -1, dtype=np.int32)
    for gi, (ask, count, feas, bias, ucap) in enumerate(groups):
        count = int(count)
        if count <= 0:
            continue
        free = cap - used
        per_res = np.where(
            ask[None, :] > 0, free // np.maximum(ask[None, :], 1), _BIG
        )
        units = np.minimum(per_res.min(axis=1), ucap)
        units[~feas] = 0
        np.minimum(units, count, out=units)
        np.maximum(units, 0, out=units)
        if not units.any():
            continue
        # f32 ScoreFitBinPack — the kernel's formula term for term
        fr = _F1 - (used + ask[None, :]).astype(np.float32) / safe_cap
        total = np.exp(fr[:, 0] * LN10) + np.exp(fr[:, 1] * LN10)
        score = np.minimum(np.maximum(_F20 - total, 0.0), _F18) / _F18
        score = score + bias.astype(np.float32, copy=False)
        score[units <= 0] = NEG_INF
        order = np.argsort(-score, kind="stable")
        su = units[order]
        prior = np.cumsum(su) - su
        take_sorted = np.minimum(np.maximum(count - prior, 0), su)
        take = np.zeros(n, dtype=np.int64)
        take[order] = take_sorted
        used += take[:, None] * ask[None, :]
        placed_nodes = np.nonzero(take)[0]
        if placed_nodes.size:
            row = np.repeat(
                placed_nodes.astype(np.int32), take[placed_nodes]
            )[:max_count]
            inst[gi, : row.shape[0]] = row
    # the integer waterfill floors units from free capacity, so overflow
    # is impossible by construction — mirror the device kernel's
    # always-False defensive flags
    over = np.zeros(n, dtype=bool)
    return inst, over, used
