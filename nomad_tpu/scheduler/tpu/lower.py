"""Lowering orchestrator state to dense tensors for the TPU placement solver.

This is the bridge between the string-typed, ragged control-plane world
(reference: scheduler/feasible.go's per-node predicate walk) and the dense
[group x node] tensor world the solver kernels operate on
(SURVEY.md §7 hard part 2: attribute vocabulary interning + fixed
constraint-kernel set; regex/version predicates stay host-side as
per-distinct-value mask precomputation).

Key trick: every hard constraint is a predicate over ONE node attribute.
We intern each referenced attribute's values into integer codes (V distinct
values << N nodes), evaluate the predicate once per distinct value with the
exact host-oracle implementation (`check_constraint` — including regex and
version operands), and broadcast to all N nodes with a single vectorized
gather. Feasibility semantics are therefore *identical* to the host oracle
by construction, not by reimplementation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ...structs import Job, Node, TaskGroup
from ...structs.structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
)
from ..context import EvalContext
from ..feasible import check_constraint, resolve_target

NUM_RES = 3  # cpu MHz, memory MB, disk MB — must match structs.Resources.vector
BIG_UNITS = np.int32(1 << 30)


@dataclass
class NodeTable:
    """Interned view of all ready nodes in a snapshot."""

    nodes: list[Node]
    index_of: dict[str, int]
    cap: np.ndarray  # [N, NUM_RES] int64 — available (total - reserved)
    used: np.ndarray  # [N, NUM_RES] int64 — live alloc utilization
    datacenters: np.ndarray  # [N] int32 codes
    dc_values: list[str]
    # preemption tiers: distinct job priorities of live allocs, ascending,
    # and each tier's usage — feeds the preemption kernel's prefix sums
    tier_prios: list[int] = field(default_factory=list)
    tier_used: Optional[np.ndarray] = None  # [T, N, NUM_RES] int64
    # dedicated-core availability: total ids and ids held by live allocs
    # (cores ride OUTSIDE the dense NUM_RES columns — a static screen
    # here, exact id assignment at materialization, allocs_fit backstop)
    cores_free: Optional[np.ndarray] = None  # [N] int64
    # lazily built per-attribute interning: ltarget -> (codes [N] int32, values)
    _attr_cache: dict[str, tuple[np.ndarray, list[str], np.ndarray]] = field(
        default_factory=dict
    )
    # lazily built driver health masks: driver -> bool [N]
    _driver_cache: dict[str, np.ndarray] = field(default_factory=dict)
    # static-port occupancy masks, lazy: port -> bool [N]
    _port_masks: Optional[dict[int, np.ndarray]] = None
    # snapshot accessor for live allocs per node (set by build_node_table)
    _allocs_by_node: Optional[object] = None

    @property
    def n(self) -> int:
        return len(self.nodes)

    def attr_codes(self, target: str) -> tuple[np.ndarray, list[str], np.ndarray]:
        """(codes [N] i32, distinct values, exists-mask [N] bool) for a
        constraint ltarget, interning on first use."""
        cached = self._attr_cache.get(target)
        if cached is not None:
            return cached
        values: list[str] = []
        code_of: dict[str, int] = {}
        codes = np.zeros(self.n, dtype=np.int32)
        exists = np.zeros(self.n, dtype=bool)
        for i, node in enumerate(self.nodes):
            val, found = resolve_target(node, target)
            exists[i] = found
            key = val if found else "\x00missing"
            code = code_of.get(key)
            if code is None:
                code = len(values)
                code_of[key] = code
                values.append(val if found else "")
            codes[i] = code
        out = (codes, values, exists)
        self._attr_cache[target] = out
        return out

    def used_port_mask(self, port: int) -> np.ndarray:
        """bool [N]: does any live alloc (or node reservation) already hold
        this static port on the node?"""
        if self._port_masks is None:
            self._port_masks = {}
        m = self._port_masks.get(port)
        if m is not None:
            return m
        m = np.zeros(self.n, dtype=bool)
        for i, node in enumerate(self.nodes):
            if port in node.reserved.reserved_ports:
                m[i] = True
                continue
            for alloc in self._allocs_by_node(node.id):
                if alloc.resources is None:
                    continue
                nets = list(alloc.resources.shared_networks)
                for tr in alloc.resources.tasks.values():
                    nets.extend(tr.networks)
                if any(
                    p.value == port
                    for net in nets
                    for p in list(net.reserved_ports) + list(net.dynamic_ports)
                ):
                    m[i] = True
                    break
        self._port_masks[port] = m
        return m

    def driver_mask(self, driver: str) -> np.ndarray:
        m = self._driver_cache.get(driver)
        if m is not None:
            return m
        m = np.zeros(self.n, dtype=bool)
        for i, node in enumerate(self.nodes):
            info = node.drivers.get(driver)
            if info is not None:
                m[i] = info.detected and info.healthy
            else:
                m[i] = node.attributes.get(f"driver.{driver}", "") in ("1", "true")
        self._driver_cache[driver] = m
        return m


def build_node_table(
    nodes: list[Node], allocs_by_node, usage_of=None
) -> NodeTable:
    """Lower ready nodes + live utilization to tensors.

    allocs_by_node: callable node_id -> live allocs (snapshot accessor).

    usage_of: optional callable node_id -> (cpu, mem, disk) committed
    usage. When given, per-node utilization comes from the store's
    incremental aggregate in O(nodes) instead of walking every live
    alloc (O(allocs) — the dominant lowering cost on a loaded cluster).
    The fast table carries NO preemption tiers and NO core pools, so the
    solver only takes it for batches that need neither (no preemptible
    job types, no cores asks); everything else about the table is
    identical.
    """
    n = len(nodes)
    cap = np.zeros((n, NUM_RES), dtype=np.int64)
    used = np.zeros((n, NUM_RES), dtype=np.int64)
    dc_values: list[str] = []
    dc_code: dict[str, int] = {}
    dcs = np.zeros(n, dtype=np.int32)
    index_of: dict[str, int] = {}
    # usage bucketed by the owning job's priority → preemption tiers
    by_prio: dict[int, np.ndarray] = {}
    cores_free = np.zeros(n, dtype=np.int64)
    for i, node in enumerate(nodes):
        index_of[node.id] = i
        avail = node.available_resources()
        cap[i] = (avail.cpu, avail.memory_mb, avail.disk_mb)
        cores_free[i] = node.resources.total_cores or 0
        code = dc_code.get(node.datacenter)
        if code is None:
            code = len(dc_values)
            dc_code[node.datacenter] = code
            dc_values.append(node.datacenter)
        dcs[i] = code
        if usage_of is not None:
            u = usage_of(node.id)
            used[i] = (u[0], u[1], u[2])
            continue
        for alloc in allocs_by_node(node.id):
            r = alloc.comparable_resources()
            vec = (r.cpu, r.memory_mb, r.disk_mb)
            used[i] += vec
            if alloc.resources is not None:
                cores_free[i] -= sum(
                    len(tr.reserved_cores)
                    for tr in alloc.resources.tasks.values()
                )
            prio = alloc.job.priority if alloc.job is not None else 50
            tier = by_prio.get(prio)
            if tier is None:
                tier = by_prio[prio] = np.zeros((n, NUM_RES), dtype=np.int64)
            tier[i] += vec
    tier_prios = sorted(by_prio)
    tier_used = (
        np.stack([by_prio[p] for p in tier_prios])
        if tier_prios
        else np.zeros((0, n, NUM_RES), dtype=np.int64)
    )
    table = NodeTable(
        nodes=nodes,
        index_of=index_of,
        cap=cap,
        used=used,
        datacenters=dcs,
        dc_values=dc_values,
        tier_prios=tier_prios,
        tier_used=tier_used,
        cores_free=cores_free,
    )
    table._allocs_by_node = allocs_by_node
    # observability: the lowered table's host-side tensor footprint —
    # the upper bound of what a cold (non-resident) solve ships to the
    # device per batch (solverobs feeds /v1/solver/status)
    from ... import solverobs

    solverobs.note_table(
        n, cap.nbytes + used.nbytes + tier_used.nbytes + dcs.nbytes
    )
    return table


@dataclass
class LoweredGroup:
    """One task group's asks, lowered. All instances of a group are
    interchangeable — the solver places `count` of them at once."""

    key: tuple  # (eval_id, tg_name)
    job: Job
    tg: TaskGroup
    count: int
    ask: np.ndarray  # [NUM_RES] int64
    feasible: np.ndarray  # [N] bool
    bias: np.ndarray  # [N] f32 — affinity/spread score offsets
    units_cap: np.ndarray  # [N] int32 — distinct_hosts/property caps
    priority: int
    names: list[str] = field(default_factory=list)  # instance names to assign
    requests: list = field(default_factory=list)  # original PlacementRequests
    restricted: bool = False  # spread-value-restricted sub-group (retryable)
    # bias WITHOUT the per-solve spread addend — what the lowered-skeleton
    # cache stores (aliases `bias` when the group has no spreads)
    bias_static: Optional[np.ndarray] = None
    # per-dimension feasibility attrition: screen name → nodes that
    # screen newly eliminated. The dense path's answer to the host
    # stack's per-checker counts — AllocMetric.constraint_filtered /
    # dimension_exhausted on the fast-mint path read from here, so
    # `alloc status` explains a dense-path failure the same way it
    # explains a host-path one.
    filtered_dims: dict = field(default_factory=dict)


def lower_group(
    ctx: EvalContext,
    table: NodeTable,
    job: Job,
    tg: TaskGroup,
    requests: list,
    eval_id: str,
) -> LoweredGroup:
    """Build the group's feasibility mask, score bias, and unit caps."""
    n = table.n
    feas = np.ones(n, dtype=bool)
    filtered_dims: dict[str, int] = {}

    def screen(dim: str, mask: np.ndarray) -> None:
        """AND `mask` into the running feasibility and attribute the
        nodes it newly eliminated to `dim` (AllocMetric attrition)."""
        nonlocal feas
        before = int(np.sum(feas))
        feas = feas & mask
        dropped = before - int(np.sum(feas))
        if dropped:
            filtered_dims[dim] = filtered_dims.get(dim, 0) + dropped

    # Datacenter membership (the GenericStack's node source filter).
    import fnmatch

    dc_ok = np.zeros(len(table.dc_values), dtype=bool)
    for vi, dc in enumerate(table.dc_values):
        dc_ok[vi] = any(fnmatch.fnmatchcase(dc, pat) for pat in job.datacenters)
    screen("datacenters", dc_ok[table.datacenters])

    # Drivers.
    for task in tg.tasks:
        screen(f"driver.{task.driver}", table.driver_mask(task.driver))

    # Constraints: job + group + task level, via per-distinct-value masks.
    constraints = list(job.constraints) + list(tg.constraints)
    for task in tg.tasks:
        constraints.extend(task.constraints)
    units_cap = np.full(n, BIG_UNITS, dtype=np.int64)
    for c in constraints:
        if c.operand == CONSTRAINT_DISTINCT_HOSTS:
            units_cap = np.minimum(units_cap, 1)
            # exclude nodes already carrying this job's allocs
            screen(
                CONSTRAINT_DISTINCT_HOSTS,
                _job_free_mask(ctx, table, job.id),
            )
            continue
        if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
            cap_per_value = int(c.rtarget) if c.rtarget else 1
            codes, values, exists = table.attr_codes(c.ltarget)
            counts = _property_counts(ctx, table, job, c.ltarget)
            remaining = np.maximum(
                0, cap_per_value - counts
            )  # per distinct value
            units_cap = np.minimum(units_cap, remaining[codes])
            screen(f"{CONSTRAINT_DISTINCT_PROPERTY}.{c.ltarget}", exists)
            continue
        codes, values, exists = table.attr_codes(c.ltarget)
        rval, r_found = c.rtarget, True  # rtargets are literals for node feas
        value_ok = np.zeros(len(values), dtype=bool)
        for vi, val in enumerate(values):
            value_ok[vi] = check_constraint(
                ctx, c.operand, val, rval, True, r_found
            )
        mask = value_ok[codes]
        # Attributes that didn't resolve fail every operand except is_not_set.
        if c.operand == "is_not_set":
            mask = mask | ~exists
        else:
            mask = mask & exists
        screen(f"constraint.{c.ltarget} {c.operand}".rstrip(), mask)

    # Host volumes (mirrors feasible.py HostVolumeChecker): per-node
    # membership/writability, plus the registered-volume access screen
    # (node-independent: a claimed single-writer volume zeroes the mask).
    vol_asks = [
        v for v in tg.volumes.values() if v.type in ("", "host")
    ]
    if vol_asks:
        state = getattr(ctx, "state", None)
        for ask in vol_asks:
            registered = (
                state.volumes_by_name(job.namespace, ask.source)
                if state is not None and hasattr(state, "volumes_by_name")
                else []
            )
            vol_ok = np.zeros(n, dtype=bool)
            for i, node in enumerate(table.nodes):
                hv = node.host_volumes.get(ask.source)
                if hv is None or (hv.read_only and not ask.read_only):
                    continue
                usable = [
                    v for v in registered if v.node_id in ("", node.id)
                ]
                if usable and not any(
                    v.claimable(ask.read_only)[0] for v in usable
                ):
                    continue  # claimed single-writer: node unusable
                vol_ok[i] = True
            screen(f"host_volume.{ask.source}", vol_ok)

    # CSI volumes (mirrors feasible.py CSIVolumeChecker): node must run a
    # healthy node-capable instance of some registered, claimable volume's
    # plugin for every csi-type ask.
    csi_asks = [v for v in tg.volumes.values() if v.type == "csi"]
    if csi_asks:
        state = getattr(ctx, "state", None)
        for ask in csi_asks:
            vols = [
                v
                for v in (
                    state.volumes_by_name(job.namespace, ask.source)
                    if state is not None
                    and hasattr(state, "volumes_by_name")
                    else []
                )
                if v.type == "csi" and v.claimable(ask.read_only)[0]
            ]
            plugin_ids = {v.plugin_id for v in vols}
            csi_ok = np.array(
                [
                    any(
                        (info := node.csi_plugins.get(pid)) is not None
                        and info.get("healthy")
                        and info.get("node", True)
                        for pid in plugin_ids
                    )
                    for node in table.nodes
                ],
                dtype=bool,
            )
            screen(f"csi_volume.{ask.source}", csi_ok)

    # Network: static-port / bandwidth screens stay host-side but cheap —
    # mbits capacity folds into feasibility; a static-port ask caps the
    # group at one instance per node and excludes nodes already holding
    # the port (dynamic port selection still happens at plan build).
    net_asks = list(tg.networks) + [
        a for t in tg.tasks for a in t.resources.networks
    ]
    total_mbits = sum(a.mbits for a in net_asks)
    if total_mbits > 0:
        net_ok = np.array(
            [
                max((nw.mbits for nw in node.resources.networks), default=0)
                >= total_mbits
                for node in table.nodes
            ],
            dtype=bool,
        )
        screen("network.mbits", net_ok)
    static_ports = [p.value for a in net_asks for p in a.reserved_ports if p.value]
    if static_ports:
        units_cap = np.minimum(units_cap, 1)
        for port in static_ports:
            screen(f"network.port.{port}", ~table.used_port_mask(port))

    # Devices.
    dev_asks = [d for t in tg.tasks for d in t.resources.devices]
    if dev_asks:
        dev_ok = np.ones(n, dtype=bool)
        for i, node in enumerate(table.nodes):
            for ask in dev_asks:
                if not any(
                    d.matches(ask)
                    and sum(1 for inst in d.instances if inst.healthy) >= ask.count
                    for d in node.resources.devices
                ):
                    dev_ok[i] = False
                    break
        screen("devices", dev_ok)

    # Score bias: affinities (normalized like the host oracle) + static
    # spread boosts; the solver adds this to the binpack score for ordering.
    bias = np.zeros(n, dtype=np.float32)
    affinities = list(job.affinities) + list(tg.affinities)
    for task in tg.tasks:
        affinities.extend(task.affinities)
    if affinities:
        total_weight = sum(abs(a.weight) for a in affinities) or 1
        for a in affinities:
            codes, values, exists = table.attr_codes(a.ltarget)
            value_ok = np.zeros(len(values), dtype=bool)
            for vi, val in enumerate(values):
                value_ok[vi] = check_constraint(ctx, a.operand, val, a.rtarget, True, True)
            match = value_ok[codes] & exists
            bias += np.where(match, a.weight / total_weight, 0.0).astype(np.float32)

    bias_static = bias
    sb = spread_bias(ctx, table, job, tg)
    if sb is not None:
        bias = bias + sb

    cores_ask = sum(t.resources.cores for t in tg.tasks)
    if cores_ask > 0 and table.cores_free is not None:
        screen("cores", table.cores_free >= cores_ask)
        # dedicated ids are NOT in the dense resource columns, so cap
        # the per-node unit count here or the solver would stack more
        # instances than a node has cores and the materializer would
        # drop the overflow
        units_cap = np.minimum(
            units_cap, np.maximum(table.cores_free, 0) // cores_ask
        )

    ask = np.array(tg.combined_resources().vector(), dtype=np.int64)
    return LoweredGroup(
        key=(eval_id, tg.name),
        job=job,
        tg=tg,
        count=len(requests),
        ask=ask,
        feasible=feas,
        bias=bias,
        units_cap=np.minimum(units_cap, BIG_UNITS).astype(np.int64),
        priority=job.priority,
        names=request_names(requests),
        requests=requests,
        bias_static=bias_static,
        filtered_dims=filtered_dims,
    )


def spread_bias(
    ctx: EvalContext, table: NodeTable, job: Job, tg: TaskGroup
) -> Optional[np.ndarray]:
    """The spread boost addend [N] f32, or None when the group has no
    spreads. Split out of lower_group because it is the ONLY part of a
    spread-carrying group's lowering that reads live state (per-value
    alloc counts): the solver caches the static tensors across solves
    and re-adds this per solve."""
    spreads = list(tg.spreads) + [
        s for s in job.spreads if s.attribute not in {t.attribute for t in tg.spreads}
    ]
    if not spreads:
        return None
    bias = np.zeros(table.n, dtype=np.float32)
    sum_w = sum(abs(s.weight) for s in spreads) or 1
    for s in spreads:
        codes, values, exists = table.attr_codes(s.attribute)
        counts = _property_counts(ctx, table, job, s.attribute, tg.name)
        desired = _spread_desired(s, values, tg.count)
        # boost = (desired - used)/desired per value (targeted spread);
        # implicit even spread when no explicit targets.
        with np.errstate(divide="ignore", invalid="ignore"):
            boost = np.where(
                desired > 0, (desired - counts) / np.maximum(desired, 1), -1.0
            )
        bias += (boost[codes] * (s.weight / sum_w)).astype(np.float32)
    return bias


def request_names(requests) -> list[str]:
    """The per-row names column without materializing rows: a
    PlacementRun already holds it; plain lists walk their rows."""
    names = getattr(requests, "names", None)
    if names is not None:
        return names
    return [r.name for r in requests]


def group_lower_cacheable(job: Job, tg: TaskGroup) -> bool:
    """May this group's FULL lowered tensors (spread bias included) be
    reused across solves on the (job version, node-universe fingerprint)
    key alone? Only when the static part is cacheable AND there are no
    spreads (existing-alloc counts feed the spread bias per solve)."""
    if tg.spreads or job.spreads:
        return False
    return group_lower_static_cacheable(job, tg)


def group_lower_static_cacheable(job: Job, tg: TaskGroup) -> bool:
    """May this group's STATIC lowered tensors (feasibility, affinity
    bias, unit caps — everything except the spread addend) be cached
    across solves on the (job version, node-universe fingerprint) key
    alone?

    False whenever the static lowering reads state BEYOND the node
    fingerprint: distinct_hosts / distinct_property (proposed-alloc and
    per-value counts), volumes (claim state), static ports (live port
    occupancy), and cores (the free-core column is rebuilt per solve).
    Everything else — dc membership, drivers, attribute constraints,
    affinities, bandwidth, devices — is a pure function of (job spec,
    node objects), which the fingerprint pins. Spreads do NOT disqualify
    the static part: lower.spread_bias recomputes their addend per
    solve on top of the cached tensors."""
    constraints = list(job.constraints) + list(tg.constraints)
    for task in tg.tasks:
        constraints.extend(task.constraints)
    if any(
        c.operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY)
        for c in constraints
    ):
        return False
    if tg.volumes:
        return False
    if any(t.resources.cores > 0 for t in tg.tasks):
        return False
    net_asks = list(tg.networks) + [
        a for t in tg.tasks for a in t.resources.networks
    ]
    if any(p.value for a in net_asks for p in a.reserved_ports):
        return False
    return True


def _job_free_mask(ctx: EvalContext, table: NodeTable, job_id: str) -> np.ndarray:
    mask = np.ones(table.n, dtype=bool)
    for i, node in enumerate(table.nodes):
        for alloc in ctx.proposed_allocs(node.id):
            if alloc.job_id == job_id:
                mask[i] = False
                break
    return mask


def _property_counts(
    ctx: EvalContext, table: NodeTable, job: Job, attribute: str, tg_name: str = ""
) -> np.ndarray:
    """Existing alloc count per distinct attribute value (host-side; the
    solver handles the within-batch delta via units caps)."""
    codes, values, _ = table.attr_codes(attribute)
    counts = np.zeros(len(values), dtype=np.int64)
    stopped: set[str] = set()
    if ctx.plan is not None:
        for allocs_ in ctx.plan.node_update.values():
            stopped.update(a.id for a in allocs_)
    for alloc in ctx.state.allocs_by_job(job.namespace, job.id):
        if alloc.terminal_status() or alloc.id in stopped:
            continue
        if tg_name and alloc.task_group != tg_name:
            continue
        idx = table.index_of.get(alloc.node_id)
        if idx is not None:
            counts[codes[idx]] += 1
    return counts


def _spread_desired(spread, values: list[str], count: int) -> np.ndarray:
    import math

    explicit = {t.value: t.percent for t in spread.targets}
    desired = np.zeros(len(values), dtype=np.float64)
    remaining = 100 - sum(explicit.values())
    implicit = [v for v in values if v not in explicit]
    implicit_pct = remaining / max(1, len(implicit))
    for vi, val in enumerate(values):
        pct = explicit.get(val, implicit_pct)
        desired[vi] = math.ceil(pct / 100.0 * count)
    return desired
