"""JAX placement kernels: vectorized ScoreFit + capacity waterfill.

The deliberate architectural departure from the reference: instead of the
per-alloc iterator chain (reference: scheduler/rank.go BinPackIterator.Next
:193 scoring one node at a time, scheduler/stack.go limiting to log2(n)
candidates), a whole batch of task groups is placed in one compiled program:

  for each group g (lax.scan, priority order):
      units[n]  = how many instances of g fit on node n     (int division)
      score[n]  = normalized bin-pack ScoreFit + bias        (vectorized)
      place `count_g` instances onto the best-scored nodes   (sort + cumsum)
      node_used += placed * ask_g

One scan step places an entire group — the sequential best-fit greedy the
reference runs per alloc collapses into a waterfall over the score-sorted
node axis, because filling the currently-best node until it stops being
best is exactly what per-instance best-fit does.

All shapes are padded to buckets (pad_n/pad_g) so XLA compiles once per
bucket, not once per cluster size. Scores use the reference formula
(structs/funcs.go:237): score = 20 - 10^freeCpu - 10^freeMem, normalized
to [0,1]; bias (affinity/spread) is added on top.

Multi-chip: `make_sharded_solver` shards the node axis over a mesh with
shard_map. Per scan step the per-node score/units vectors are all-gathered
(2 x N x 4B per group — rides ICI), the waterfill decision is computed
replicated, and each device applies its slice of the placement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NUM_RES = 3
# Plain Python float: a module-level jnp scalar would eagerly initialize the
# JAX backend at import time (the lazy-import seam in scheduler/__init__
# promises the control plane never pays that unless the backend is selected).
NEG_INF = -1e30
LN10 = 2.302585092994046


def _pad_to(x: int, bucket: int) -> int:
    return ((x + bucket - 1) // bucket) * bucket


def _shard_map(body, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map: jax >= 0.6 exports `jax.shard_map`
    (replication check kwarg `check_vma`); the 0.4.x line this box runs
    ships it as `jax.experimental.shard_map.shard_map` (kwarg
    `check_rep`). The replication check is disabled either way: the
    waterfill decision is computed replicated from all-gathered vectors,
    which the checker cannot prove."""
    try:
        from jax import shard_map as sm  # jax >= 0.6

        kwargs = {"check_vma": False}
    except ImportError:  # pragma: no cover - exercised on jax 0.4.x boxes
        from jax.experimental.shard_map import shard_map as sm

        kwargs = {"check_rep": False}
    return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kwargs)


def jit_cache_sizes() -> dict[str, int]:
    """The jit-cache entry count of each module-level kernel, straight
    from jax — the ground truth the compile ledger (solverobs.py) is
    cross-checked against in /v1/solver/status. Our signature ledger
    COUNTS events over time; this reports what jax currently CACHES, so
    ledger compiles >= cache size always holds (evictions, restarts).
    Entry-point factories (make_sharded_solver*) build fresh jits per
    mesh and are observed per-instance by their callers instead."""
    out: dict[str, int] = {}
    for name, fn in (
        ("solve_placement", solve_placement),
        ("solve_placement_compact", solve_placement_compact),
        ("solve_placement_preempt", solve_placement_preempt),
    ):
        try:
            out[name] = int(fn._cache_size())
        except Exception:  # private API seam: absent ⇒ report unknown
            out[name] = -1
    return out


def pad_n(n: int) -> int:
    """Node-axis bucket: powers of two up to 2048, then multiples of 2048.

    Power-of-two buckets alone waste up to ~2x work at the top (10k nodes
    padded to 16384 is 64% dead lanes on every scan step); 2048-granular
    buckets cap the waste at <20% while staying multiples of 8 devices x
    128 lanes for the sharded solver and the VPU alike. Recompiles happen
    once per bucket and amortize across the server's lifetime exactly as
    before.
    """
    size = 256
    while size < n and size < 2048:
        size *= 2
    if n <= size:
        return size
    return _pad_to(n, 2048)


def pad_g(g: int) -> int:
    """Group-axis bucket: multiples of 8."""
    return max(8, _pad_to(g, 8))


def _score_nodes(cap_f, used_f, ask_f, bias_g):
    """Vectorized ScoreFitBinPack after hypothetically adding one instance.

    cap_f/used_f: [N, R] f32; ask_f: [R] f32; bias_g: [N] f32 -> [N] f32.
    Mirrors structs/funcs.go:237 on the cpu/mem dimensions.
    """
    util = used_f + ask_f[None, :]
    safe_cap = jnp.maximum(cap_f, 1.0)
    free = 1.0 - util / safe_cap  # [N, R]
    total = jnp.exp(free[:, 0] * LN10) + jnp.exp(free[:, 1] * LN10)
    score = jnp.clip(20.0 - total, 0.0, 18.0) / 18.0
    return score + bias_g


def _units_for(free, ask, ucap, feas_g, count):
    """How many instances fit per node given free capacity + caps."""
    per_res = jnp.where(
        ask[None, :] > 0,
        free // jnp.maximum(ask[None, :], 1),
        jnp.int32(1 << 30),
    )
    units = jnp.min(per_res, axis=1)  # [N]
    units = jnp.clip(units, 0, ucap)
    units = jnp.where(feas_g, units, 0)
    # Clip to the group's count: keeps the cumsum far from int32 overflow
    # and changes nothing (a node can never take more than count instances).
    return jnp.clip(units, 0, count)


def _waterfill(score, units, count):
    """Fill the score-sorted node axis until `count` instances placed."""
    order = jnp.argsort(-score)  # best first
    su = units[order]
    prior = jnp.cumsum(su) - su
    take_sorted = jnp.clip(count - prior, 0, su)
    return jnp.zeros_like(units).at[order].set(take_sorted)


def _waterfill_topk(score, units, count, k: int):
    """_waterfill restricted to the k best-scored nodes — exact when k
    bounds the nodes the full fill could touch.

    Every node the full waterfill takes from receives >= 1 instance, so
    the receiving set is at most min(count, sum(units)) nodes, and those
    are by construction the highest-scored unit-bearing nodes
    (unit-less nodes carry NEG_INF). The caller passes k = the compact
    readback width, which already upper-bounds min(count, placeable) for
    every group in the batch (solver._run_compact derives it from free
    capacity before the scan, and free only shrinks as groups place), so
    the top-k fill is bit-identical to the full sort — top_k's
    lower-index-first tie order matches stable argsort of -score. A full
    [N] sort per scan step was the single largest cost of the compact
    kernel on the VPU-less CPU fallback (~4.5x); on TPU it likewise
    replaces an O(N log N) sort with an O(N log k) partial reduction.
    """
    _, order = lax.top_k(score, k)
    su = units[order]
    prior = jnp.cumsum(su) - su
    take_sorted = jnp.clip(count - prior, 0, su)
    return jnp.zeros_like(units).at[order].set(take_sorted)


def _place_group(cap, carry, xs, fill=_waterfill):
    """One lax.scan step: place count_g instances of one group. `fill`
    picks the waterfill variant (full sort, or top-k where the caller can
    bound the receiving node set)."""
    used = carry
    ask, count, feas_g, bias_g, ucap = xs
    units = _units_for(cap - used, ask, ucap, feas_g, count)
    score = _score_nodes(cap.astype(jnp.float32), used.astype(jnp.float32),
                         ask.astype(jnp.float32), bias_g)
    score = jnp.where(units > 0, score, NEG_INF)
    take = fill(score, units, count)
    used = used + take[:, None] * ask[None, :]
    return used, take


@functools.partial(jax.jit, static_argnames=())
def solve_placement(cap, used, asks, counts, feas, bias, units_cap):
    """Place all groups.

    cap, used: [N, R] i32; asks: [G, R] i32; counts: [G] i32;
    feas: [G, N] bool; bias: [G, N] f32; units_cap: [G, N] i32.
    Returns (assign [G, N] i32, used' [N, R] i32).
    """
    step = functools.partial(_place_group, cap)
    used, takes = lax.scan(step, used, (asks, counts, feas, bias, units_cap))
    return takes, used


def pad_c(c: int) -> int:
    """Instance-count bucket for the compact readback: power of two >= 16."""
    size = 16
    while size < c:
        size *= 2
    return size


@functools.partial(jax.jit, static_argnames=("max_count",))
def solve_placement_compact(
    cap,
    used,
    asks,
    counts,
    feas_packed,
    feas_idx,
    bias_rows,
    bias_idx,
    ucap_rows,
    ucap_idx,
    *,
    max_count: int,
):
    """solve_placement with compressed transfers in BOTH directions.

    The host<->TPU link (a tunnel here, PCIe/DCN generally) is the slow
    resource at c2m scale, not the MXU: the dense [G, N] f32/i32 inputs are
    ~60 MB and the [G, N] result another 20 MB. Three reductions:

      * input dedupe — groups lowered from the same job share identical
        bias/units-cap/feasibility rows (spread sub-groups reference the
        parent's arrays; unconstrained jobs are all-equal). The host sends
        unique rows + a per-group row index; the kernel gathers on device.
      * feasibility rows travel bit-packed ([Uf, N/8] u8, unpacked once on
        device); unit caps travel as i16 (caps beyond the group count are
        equivalent to it).
      * compact result — instead of [G, N] counts, the device emits the
        node index of each placed instance ([G, max_count] i32 via
        searchsorted over the per-group cumsum), plus [N] overflow flags.

    The overflow flags are a defensive invariant check, not an expected
    path: the integer waterfill can never place past free capacity (units
    are floor-divided from it), so `over` is always all-False from this
    kernel. If it ever fires (a future kernel bug, a miscomputed `used`
    input), the host re-verifies flagged nodes with exact integer math
    instead of silently committing an overcommit.

    Returns (inst_node [G, max_count] i32 (-1 past each group's placed
    total), over [N] bool, used' [N, R] i32).
    """
    n = cap.shape[0]
    feas_rows = jnp.unpackbits(feas_packed, axis=1, count=n).astype(bool)

    # top-k waterfill: max_count bounds every group's receiving node set
    # (see _waterfill_topk), so the partial fill is exact; k > N
    # degenerates to the full sort (top-N = every node)
    k = min(max_count, n)

    def step(used_c, xs):
        ask, count, fi, bi, ui = xs
        # gather the group's deduped rows, then the shared scan step
        return _place_group(
            cap,
            used_c,
            (ask, count, feas_rows[fi], bias_rows[bi],
             ucap_rows[ui].astype(jnp.int32)),
            fill=lambda s, u, c: _waterfill_topk(s, u, c, k),
        )

    used_out, takes = lax.scan(
        step, used, (asks, counts, feas_idx, bias_idx, ucap_idx)
    )

    cum = jnp.cumsum(takes, axis=1)  # [G, N]
    idx = jnp.arange(max_count, dtype=jnp.int32)

    def compact_one(cum_g):
        node = jnp.searchsorted(cum_g, idx, side="right").astype(jnp.int32)
        return jnp.where(idx < cum_g[-1], node, -1)

    inst_node = jax.vmap(compact_one)(cum)
    placed_res = used_out - used
    over = jnp.any(placed_res > jnp.maximum(cap - used, 0), axis=1)
    return inst_node, over, used_out


# ---------------------------------------------------------------------------
# Preemption-aware variant: per-priority-tier usage tensors
# ---------------------------------------------------------------------------


def _place_group_preempt(cap, used_exist, prefix_used, carry, xs):
    """Two-phase scan step (reference analog: generic_sched.go:773
    selectNextOption's run-again-with-preemption + preemption.go's
    priority-tier candidate grouping, tensorized):

      phase 1: normal waterfill against remaining real capacity;
      phase 2: the unplaced remainder retries with capacity EXPANDED by
        the usage of preemptible priority tiers (tiers strictly more
        than PRIORITY_DELTA below the group's job priority — `klim`
        indexes the cumulative tier-usage prefix).

    The carry tracks `freed` — preemptible usage already claimed by
    earlier (higher-priority) groups in this batch — so two groups can
    never double-spend the same victim capacity. Phase-2 placements are
    returned separately (`take2`): the host picks exact victim allocs
    per node and emits plan.node_preemptions.
    """
    used_new, freed = carry
    ask, count, feas_g, bias_g, ucap, klim = xs

    avail_exist = used_exist - freed  # existing usage still standing
    used_total = avail_exist + used_new

    # phase 1: normal placement
    units1 = _units_for(cap - used_total, ask, ucap, feas_g, count)
    score1 = _score_nodes(
        cap.astype(jnp.float32),
        used_total.astype(jnp.float32),
        ask.astype(jnp.float32),
        bias_g,
    )
    score1 = jnp.where(units1 > 0, score1, NEG_INF)
    take1 = _waterfill(score1, units1, count)
    used_new = used_new + take1[:, None] * ask[None, :]
    used_total = used_total + take1[:, None] * ask[None, :]
    remaining = count - jnp.sum(take1)

    # phase 2: preemptible capacity (klim = 0 → prefix is all-zero)
    preemptible = jnp.maximum(
        lax.dynamic_index_in_dim(prefix_used, klim, 0, keepdims=False) - freed,
        0,
    )  # [N, R]
    normal_free = cap - used_total
    units2 = _units_for(
        normal_free + preemptible, ask, ucap - take1, feas_g, remaining
    )
    score2 = _score_nodes(
        cap.astype(jnp.float32),
        jnp.maximum(used_total - preemptible, 0).astype(jnp.float32),
        ask.astype(jnp.float32),
        bias_g,
    )
    score2 = jnp.where(units2 > 0, score2, NEG_INF)
    take2 = _waterfill(score2, units2, remaining)

    # How much of phase 2 actually eats into victims (vs leftover free).
    overflow = jnp.maximum(
        take2[:, None] * ask[None, :] - jnp.maximum(normal_free, 0), 0
    )
    freed = freed + jnp.minimum(overflow, preemptible)
    used_new = used_new + take2[:, None] * ask[None, :]
    return (used_new, freed), (take1 + take2, take2)


@jax.jit
def solve_placement_preempt(
    cap, used_exist, prefix_used, asks, counts, feas, bias, units_cap, tier_limit
):
    """Place all groups with preemption tiers.

    cap, used_exist: [N, R] i32; prefix_used: [T+1, N, R] i32 cumulative
    usage of the T priority tiers (ascending priority; prefix_used[k] =
    usage of the k lowest tiers); tier_limit: [G] i32 — how many tiers
    each group may preempt (0 = none). Returns
    (assign [G, N], assign_evict [G, N], used' [N, R]).
    """
    n = cap.shape[0]
    zeros = jnp.zeros((n, cap.shape[1]), dtype=cap.dtype)
    step = functools.partial(_place_group_preempt, cap, used_exist, prefix_used)
    (used_new, freed), (takes, takes_evict) = lax.scan(
        step, (zeros, zeros), (asks, counts, feas, bias, units_cap, tier_limit)
    )
    return takes, takes_evict, used_exist - freed + used_new


# ---------------------------------------------------------------------------
# Sharded variant: node axis split over a device mesh
# ---------------------------------------------------------------------------


def _sharded_waterfill(score_loc, units_loc, count, axis, my, n_local):
    """Replicated waterfill decision from node-sharded score/unit vectors.

    All-gathers the [N/D] local vectors to the full [N] (identical on every
    device — the decision is deterministic and replicated), fills in score
    order, and returns this device's slice of the take vector. The gathered
    vectors are exactly the unsharded kernel's, so placements match the
    single-chip solver bit for bit.
    """
    score = lax.all_gather(score_loc, axis, tiled=True)  # [N]
    units = lax.all_gather(units_loc, axis, tiled=True)  # [N]
    order = jnp.argsort(-score)
    su = units[order]
    prior = jnp.cumsum(su) - su
    take_sorted = jnp.clip(count - prior, 0, su)
    take = jnp.zeros_like(units).at[order].set(take_sorted)
    return lax.dynamic_slice(take, (my * n_local,), (n_local,))


def _topk_fill(score_loc, units_loc, count, axis, my, n_local, k: int):
    """Distributed waterfill whose per-device cost shrinks with the mesh.

    The replicated variant above all-gathers the FULL [N] vectors and
    argsorts them on EVERY device — O(N log N) per device no matter how
    many devices share the work, which is exactly the term that stops a
    node-sharded solve from scaling. This variant keeps per-device work
    ∝ the shard:

      1. each device top-k's its LOCAL [N/D] score slice — O(N/D log k);
      2. the D×k candidate (score, units, global-index) triples are
         all-gathered — O(D·k) bytes over ICI, independent of N;
      3. the waterfill runs replicated over the tiny candidate set —
         O(D·k log D·k), independent of N;
      4. each device keeps its own slice of the take vector.

    Exact vs the full sort whenever k >= min(count, n_local) for every
    group (the caller guarantees it — solver-side the readback-width
    bound already upper-bounds any group's receiving set): the full
    waterfill's receiving set is a prefix of the global score order with
    at most `count` members (each receives >= 1 instance), so every
    receiving node — and every node ranked above one — survives its
    shard's local top-k, and the candidate cumsum reproduces the full
    sort's priors bit for bit. Tie order matches argsort's
    lower-global-index-first: candidates are pre-sorted by global index,
    then stably argsorted by -score.

    Returns (take [n_local], candidate global indices [D*k] in
    waterfill order, candidate takes [D*k]); the candidate arrays are
    replicated on every device — the compact emission
    (_candidates_to_inst) reads them directly."""
    sv, si = lax.top_k(score_loc, k)  # local best-k (ties: lower idx)
    su = units_loc[si]
    gidx = (si + my * n_local).astype(jnp.int32)
    vs = lax.all_gather(sv, axis, tiled=True)  # [D*k]
    us = lax.all_gather(su, axis, tiled=True)
    gs = lax.all_gather(gidx, axis, tiled=True)
    o0 = jnp.argsort(gs)  # global-index order first ...
    order = jnp.argsort(-vs[o0])  # ... so stable -score sort ties by it
    su_s = us[o0][order]
    prior = jnp.cumsum(su_s) - su_s
    take_sorted = jnp.clip(count - prior, 0, su_s)
    gs_s = gs[o0][order]
    loc = gs_s - my * n_local
    mine = (loc >= 0) & (loc < n_local)
    take = (
        jnp.zeros((n_local + 1,), units_loc.dtype)
        .at[jnp.where(mine, loc, n_local)]
        .add(jnp.where(mine, take_sorted, 0))
    )
    return take[:n_local], gs_s, take_sorted


def _candidates_to_inst(gs_s, take_sorted, maxc: int):
    """Compact per-instance node list from the replicated candidate set:
    exactly solve_placement_compact's readback (instances enumerated in
    node-index order, -1 past the placed total) — but computed over the
    D*k candidates instead of the full [N] take vector, so the compact
    emission costs O(D*k log D*k) replicated, independent of N.
    Non-candidate nodes all have take 0, and searchsorted(side=right)
    skips zero-take entries, so the candidate-compressed cumsum yields
    the identical instance sequence."""
    o2 = jnp.argsort(gs_s)  # node-index order, matching compact_one
    gs2 = gs_s[o2]
    cum = jnp.cumsum(take_sorted[o2])
    idxv = jnp.arange(maxc, dtype=jnp.int32)
    pos = jnp.searchsorted(cum, idxv, side="right")
    node = gs2[jnp.clip(pos, 0, gs2.shape[0] - 1)]
    return jnp.where(idxv < cum[-1], node, -1).astype(jnp.int32)


def make_sharded_solver_preempt(mesh: Mesh, axis: str = "nodes"):
    """Node-sharded variant of solve_placement_preempt.

    Same contract: (cap, used_exist, prefix_used, asks, counts, feas, bias,
    units_cap, tier_limit) -> (assign [G,N], assign_evict [G,N], used').
    The tier prefix tensors are sharded over the node axis alongside
    cap/used (each device owns its nodes' preemptible-capacity prefixes);
    per phase, only the [N] score and unit vectors ride ICI. The two-phase
    math mirrors _place_group_preempt exactly, so single-chip and sharded
    solves are equivalence-tested against each other
    (tests/test_tpu_solver.py).
    """

    def sharded_solve(
        cap, used_exist, prefix_used, asks, counts, feas, bias, units_cap,
        tier_limit,
    ):
        def body(cap_l, usede_l, prefix_l, asks_l, counts_l, feas_l, bias_l,
                 ucap_l, tl_l):
            my = lax.axis_index(axis)
            n_local = cap_l.shape[0]

            def step(carry, xs):
                used_new, freed = carry
                ask, count, feas_g, bias_g, ucap, klim = xs
                avail_exist = usede_l - freed
                used_total = avail_exist + used_new

                # phase 1: normal placement on remaining real capacity
                units1 = _units_for(cap_l - used_total, ask, ucap, feas_g, count)
                score1 = _score_nodes(
                    cap_l.astype(jnp.float32),
                    used_total.astype(jnp.float32),
                    ask.astype(jnp.float32),
                    bias_g,
                )
                score1 = jnp.where(units1 > 0, score1, NEG_INF)
                take1 = _sharded_waterfill(
                    score1, units1, count, axis, my, n_local
                )
                used_new = used_new + take1[:, None] * ask[None, :]
                used_total = used_total + take1[:, None] * ask[None, :]
                # remaining must be the GLOBAL remainder: sum local takes
                placed1 = lax.psum(jnp.sum(take1), axis)
                remaining = count - placed1

                # phase 2: retry the remainder on preemptible-tier capacity
                preemptible = jnp.maximum(
                    lax.dynamic_index_in_dim(prefix_l, klim, 0, keepdims=False)
                    - freed,
                    0,
                )
                normal_free = cap_l - used_total
                units2 = _units_for(
                    normal_free + preemptible, ask, ucap - take1, feas_g,
                    remaining,
                )
                score2 = _score_nodes(
                    cap_l.astype(jnp.float32),
                    jnp.maximum(used_total - preemptible, 0).astype(
                        jnp.float32
                    ),
                    ask.astype(jnp.float32),
                    bias_g,
                )
                score2 = jnp.where(units2 > 0, score2, NEG_INF)
                take2 = _sharded_waterfill(
                    score2, units2, remaining, axis, my, n_local
                )

                overflow = jnp.maximum(
                    take2[:, None] * ask[None, :]
                    - jnp.maximum(normal_free, 0),
                    0,
                )
                freed = freed + jnp.minimum(overflow, preemptible)
                used_new = used_new + take2[:, None] * ask[None, :]
                return (used_new, freed), (take1 + take2, take2)

            zeros = jnp.zeros_like(cap_l)
            (used_new, freed), (takes, takes_evict) = lax.scan(
                step, (zeros, zeros),
                (asks_l, counts_l, feas_l, bias_l, ucap_l, tl_l),
            )
            return takes, takes_evict, usede_l - freed + used_new

        return _shard_map(
            body,
            mesh,
            in_specs=(
                P(axis, None),        # cap
                P(axis, None),        # used_exist
                P(None, axis, None),  # prefix_used [T+1, N, R]
                P(),                  # asks
                P(),                  # counts
                P(None, axis),        # feas
                P(None, axis),        # bias
                P(None, axis),        # units_cap
                P(),                  # tier_limit
            ),
            out_specs=(P(None, axis), P(None, axis), P(axis, None)),
        )(cap, used_exist, prefix_used, asks, counts, feas, bias, units_cap,
          tier_limit)

    sharded_solve.__name__ = f"sharded_solver_preempt_d{mesh.shape[axis]}"
    return jax.jit(sharded_solve)


def make_sharded_solver(mesh: Mesh, axis: str = "nodes",
                        max_count: int | None = None,
                        compact: bool = False):
    """Build a pjit'd solver with the node axis sharded over `mesh`.

    Scoring/feasibility/unit math runs on each device's node shard. The
    waterfill decision depends on max_count:

      * None (default, the always-exact reference form): the full [N]
        score and unit vectors are all-gathered per scan step and the
        replicated decision argsorts them — O(G * N * 8 bytes) over ICI
        but O(N log N) compute on EVERY device.
      * an int bounding every group's count: the distributed top-k
        waterfill (_sharded_waterfill_topk) — per-device compute shrinks
        with the mesh (O(N/D) local + O(D*k) replicated) and only the
        D*k candidate triples ride ICI. The production path
        (scheduler/tpu/sharding.py SolverMesh) derives the bound from
        the batch's group counts, bucketed for jit-signature stability.

    compact=True (requires max_count): instead of the dense [G, N]
    assignment, returns (inst_node [G, max_count] i32 replicated,
    over [N] bool, used' [N, R]) — the same readback contract as
    solve_placement_compact, emitted from the replicated candidate set
    so the device->host transfer is [G, maxC], never [G, N]. Bit-equal
    to the single-chip compact kernel (same waterfill, same node-order
    instance enumeration, `over` all-False by the same integer-capacity
    argument).
    """
    n_dev = mesh.shape[axis]
    if compact and max_count is None:
        raise ValueError("compact sharded solver requires max_count")

    def sharded_solve(cap, used, asks, counts, feas, bias, units_cap):
        def body(cap_l, used_l, asks_l, counts_l, feas_l, bias_l, ucap_l):
            # *_l node-sharded: cap_l [N/D, R]; feas_l [G, N/D]; asks/counts
            # replicated.
            my = lax.axis_index(axis)
            n_local = cap_l.shape[0]

            def step(used_loc, xs):
                ask, count, feas_g, bias_g, ucap = xs
                units_loc = _units_for(
                    cap_l - used_loc, ask, ucap, feas_g, count
                )
                score_loc = _score_nodes(
                    cap_l.astype(jnp.float32),
                    used_loc.astype(jnp.float32),
                    ask.astype(jnp.float32),
                    bias_g,
                )
                score_loc = jnp.where(units_loc > 0, score_loc, NEG_INF)
                if max_count is None:
                    take_loc = _sharded_waterfill(
                        score_loc, units_loc, count, axis, my, n_local
                    )
                    return (
                        used_loc + take_loc[:, None] * ask[None, :],
                        take_loc,
                    )
                take_loc, gs_s, take_sorted = _topk_fill(
                    score_loc, units_loc, count, axis, my, n_local,
                    min(max_count, n_local),
                )
                used_loc = used_loc + take_loc[:, None] * ask[None, :]
                if not compact:
                    return used_loc, take_loc
                inst = _candidates_to_inst(gs_s, take_sorted, max_count)
                return used_loc, inst

            used_out, per_group = lax.scan(
                step, used_l, (asks_l, counts_l, feas_l, bias_l, ucap_l)
            )
            if not compact:
                return per_group, used_out
            placed_res = used_out - used_l
            over_loc = jnp.any(
                placed_res > jnp.maximum(cap_l - used_l, 0), axis=1
            )
            return per_group, over_loc, used_out

        out_specs = (
            # inst is computed replicated (candidate math), over and
            # used' stay node-sharded
            (P(None, None), P(axis), P(axis, None))
            if compact
            else (P(None, axis), P(axis, None))
        )
        return _shard_map(
            body,
            mesh,
            in_specs=(
                P(axis, None),  # cap
                P(axis, None),  # used
                P(),  # asks
                P(),  # counts
                P(None, axis),  # feas
                P(None, axis),  # bias
                P(None, axis),  # units_cap
            ),
            out_specs=out_specs,
        )(cap, used, asks, counts, feas, bias, units_cap)

    # ledger identity: per-mesh compile entries are attributable to their
    # device count (the k bucket rides in the caller's signature tuple)
    sharded_solve.__name__ = (
        f"sharded_solver_compact_d{n_dev}" if compact
        else f"sharded_solver_d{n_dev}"
    )
    return jax.jit(sharded_solve)
