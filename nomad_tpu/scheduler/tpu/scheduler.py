"""TPU-backed schedulers, registered through the standard factory seam.

Reference seam: scheduler/scheduler.go BuiltinSchedulers :23 — the TPU
backend plugs in as an alternate implementation of the same
Scheduler/State/Planner contract, so Raft, plan application, and rejection
semantics stay untouched (BASELINE.json north star).

Two operating modes:
  * TPUGenericScheduler / TPUBatchScheduler — drop-in single-eval
    processing (the worker calls process(eval) exactly like the host
    scheduler); the solver batch is just that one eval's groups.
  * solve_eval_batch() — the high-throughput path: many pending evals
    solved in ONE kernel invocation, emitting one plan per eval. The
    server's TPU worker (and bench.py) drive this.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ... import solverobs, trace
from ...structs import Evaluation, Plan
from ...structs.structs import (
    DEPLOYMENT_STATUS_FAILED,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
)
from ...gctune import paused_gc
from ..context import SchedulerConfig
from ..generic import BLOCKED_EVAL_FAILED_PLACEMENTS, GenericScheduler
from ..reconcile import AllocReconciler
from ..util import (
    SchedulerRetryError,
    retry_max,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)
from .solver import BatchSolver, GroupAsk

logger = logging.getLogger("nomad_tpu.scheduler.tpu")


def _mesh_for(config: SchedulerConfig, solve_fn):
    """The configured SolverMesh, or None. Only the default kernel path
    shards (an explicit solve_fn brings its own topology); meshes are
    process-cached so every solver shares the compiled kernels.
    mesh_devices=1 is honored as a real 1-device mesh — the sharded
    bench's scaling baseline runs the SAME kernel at every mesh size.

    A misconfigured mesh (NOMAD_TPU_MESH_DEVICES beyond the backend's
    device count) must not raise: every scheduler process() would fail
    and redeliver its eval forever. Degrade loudly to single-chip and
    clear mesh_devices on the config so the error logs once per config,
    not once per solve (TPUBatchWorker._ensure_resident applies the
    same policy for its resident tensors)."""
    n = getattr(config, "mesh_devices", 0) or 0
    if solve_fn is None and n >= 1:
        from .sharding import solver_mesh

        try:
            return solver_mesh(n)
        except RuntimeError as exc:
            logger.error(
                "mesh_devices=%d unusable (%s); falling back to the "
                "single-chip solver — fix NOMAD_TPU_MESH_DEVICES or "
                "the backend's device count", n, exc,
            )
            config.mesh_devices = 0
    return None


def _bucket_requests(job, place_requests):
    """Group placement requests into solver asks by (group, job version):
    requests carrying a job_override (canary-state downgrades) lower with
    THAT job's task group so old-version resources/constraints hold.

    Requests arrive in contiguous per-group runs (the reconciler emits
    each group's fill as one block), so grouping walks RUNS, not rows —
    one key computation per run instead of 10^5 dict ops per c2m eval.
    A reconcile-minted PlacementRun element (the shared-proto bulk fill)
    is a run BY CONSTRUCTION: when it is a bucket's only content it
    passes through whole, so the lowered group and the SoA fast-mint
    read its (count, names) without per-row request objects ever
    existing; a bucket mixing a run with plain rows (reschedules of the
    same group) materializes the run's rows, the pre-run shape. Output
    order (first-seen keys, original order within a key) is identical
    to the old per-row setdefault walk."""
    from ..reconcile import PlacementRun

    by_group: dict[tuple, list] = {}
    jobs: dict[tuple, object] = {}
    i, n = 0, len(place_requests)
    while i < n:
        req = place_requests[i]
        if isinstance(req, PlacementRun):
            proto = req.proto
            pjob = proto.job_override if proto.job_override is not None \
                else job
            key = (proto.task_group.name, pjob.version)
            by_group.setdefault(key, []).append(req)
            jobs[key] = pjob
            i += 1
            continue
        pjob = req.job_override if req.job_override is not None else job
        key = (req.task_group.name, pjob.version)
        j = i + 1
        tg0 = req.task_group
        ov0 = req.job_override
        while j < n:
            nxt = place_requests[j]
            # identity continuation: a run shares its TaskGroup and
            # override objects; equal-key runs split here re-merge below
            if (
                isinstance(nxt, PlacementRun)
                or nxt.task_group is not tg0
                or nxt.job_override is not ov0
            ):
                break
            j += 1
        by_group.setdefault(key, []).extend(place_requests[i:j])
        jobs[key] = pjob
        i = j
    out = []
    for key, pieces in by_group.items():
        if len(pieces) == 1 and isinstance(pieces[0], PlacementRun):
            reqs = pieces[0]  # pure run: pass the block through whole
        else:
            reqs = []
            for p in pieces:
                if isinstance(p, PlacementRun):
                    reqs.extend(p)  # mixed bucket: rows materialize
                else:
                    reqs.append(p)
        out.append((jobs[key], key[0], reqs))
    return out


class TPUGenericScheduler(GenericScheduler):
    """GenericScheduler with the placement loop replaced by a batched
    tensor solve. Reconciliation, stops, in-place updates, blocked-eval and
    retry semantics are inherited unchanged."""

    scheduler_type = "service"
    solve_fn = None  # overridable: e.g. a mesh-sharded solver
    solve_preempt_fn = None  # its preemption variant (sharded: make_sharded_solver_preempt)

    def _compute_job_allocs(self, job) -> bool:
        eval_obj = self.eval
        allocs = self.state.allocs_by_job(eval_obj.namespace, eval_obj.job_id)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        deployment = None
        if job is not None:
            deployment = self.state.latest_deployment_by_job(
                eval_obj.namespace, eval_obj.job_id
            )
            if deployment is not None and not deployment.active() and (
                deployment.status != DEPLOYMENT_STATUS_FAILED
            ):
                # failed deployments stay attached: they gate placements
                # and their canaries need cleanup (reconcile.py)
                deployment = None

        reconciler = AllocReconciler(
            job if job is not None else self._tombstone(eval_obj),
            eval_obj.job_id,
            allocs,
            tainted,
            eval_obj,
            deployment=deployment,
            batch=self.batch,
        )
        results = reconciler.compute()
        if eval_obj.annotate_plan:
            self._annotate_plan(results)
        self.followup_evals = results.followup_evals
        if results.deployment is not None:
            self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        for alloc, desc, client_status in results.stop:
            self.plan.append_stopped_alloc(alloc, desc, client_status)
        for updated in results.inplace_update:
            self.plan.append_alloc(updated, updated.job)
        for alloc_id, eval_id in results.attr_updates.items():
            existing = self.state.alloc_by_id(alloc_id)
            if existing is not None:
                annotated = existing.copy()
                annotated.followup_eval_id = eval_id
                self.plan.append_alloc(annotated, annotated.job)

        place_requests = []
        for old, req in results.destructive_update:
            self.plan.append_stopped_alloc(
                old, "alloc not needed due to job update", ""
            )
            place_requests.append(req)
        place_requests.extend(results.place)

        if job is None or job.stopped():
            return True

        queued = {
            tg: s.place + s.destructive
            for tg, s in results.desired_tg_updates.items()
        }

        active_deployment = self.state.latest_deployment_by_job(job.namespace, job.id)
        if active_deployment is not None and (
            not active_deployment.active()
            or active_deployment.job_version != job.version
        ):
            active_deployment = None

        # --- the TPU departure: one batched solve instead of the loop ---
        solver = BatchSolver(
            self.state, self.config, solve_fn=self.solve_fn,
            solve_preempt_fn=self.solve_preempt_fn,
            mesh=_mesh_for(self.config, self.solve_fn),
        )
        asks = [
            GroupAsk(eval_obj, pjob, tg_name, reqs, plan=self.plan)
            for pjob, tg_name, reqs in _bucket_requests(job, place_requests)
        ]
        outcome = solver.solve(asks)

        for alloc in outcome.placements.get(eval_obj.id, []):
            tg = job.lookup_task_group(alloc.task_group)
            if self.plan.deployment is not None:
                if tg is not None and tg.update is not None:
                    alloc.deployment_id = self.plan.deployment.id
                    dstate = self.plan.deployment.task_groups.get(alloc.task_group)
                    if dstate is not None:
                        dstate.placed_allocs += 1
            elif job.type == "service" and active_deployment is not None:
                alloc.deployment_id = active_deployment.id
            if alloc.id not in outcome.pre_appended:
                # downgraded placements already carry their (old) job
                self.plan.append_fresh_alloc(alloc, alloc.job or job)
            queued[alloc.task_group] = max(0, queued.get(alloc.task_group, 0) - 1)
        for batch in outcome.batch_placements.get(eval_obj.id, []):
            # SoA placements: deployment stamping and queue accounting
            # are batch-level (one shared deployment_id column, one
            # count decrement) — no per-row objects exist yet
            tg = job.lookup_task_group(batch.task_group)
            if self.plan.deployment is not None:
                if tg is not None and tg.update is not None:
                    batch.deployment_id = self.plan.deployment.id
                    dstate = self.plan.deployment.task_groups.get(
                        batch.task_group
                    )
                    if dstate is not None:
                        dstate.placed_allocs += len(batch)
            elif job.type == "service" and active_deployment is not None:
                batch.deployment_id = active_deployment.id
            self.plan.append_placement_batch(batch)
            queued[batch.task_group] = max(
                0, queued.get(batch.task_group, 0) - len(batch)
            )
        for victim, by_id in outcome.preemptions.get(eval_obj.id, []):
            # a pre-appended preemptOR already carried its victims in
            if by_id not in outcome.pre_appended:
                self.plan.append_preempted_alloc(victim, by_id)

        self.failed_tg_allocs = outcome.failures.get(eval_obj.id, {})
        self.queued_allocs = queued
        self.eval.queued_allocations = queued
        return True

    @staticmethod
    def _tombstone(eval_obj):
        from ...structs import Job

        j = Job(id=eval_obj.job_id, namespace=eval_obj.namespace, stop=True)
        j.task_groups = []
        return j


class TPUBatchScheduler(TPUGenericScheduler):
    scheduler_type = "batch"


def solve_eval_batch(
    state,
    planner,
    evals: list[Evaluation],
    config: Optional[SchedulerConfig] = None,
    solve_fn=None,
    solve_preempt_fn=None,
    resident=None,
) -> dict[str, Plan]:
    """High-throughput path: reconcile every pending eval, solve ALL their
    placements in one kernel invocation, and emit one plan per eval.

    Per-job serialization is the caller's duty (the eval broker already
    guarantees one in-flight eval per job). `resident` — an optional
    ResidentClusterState reused across calls so steady-state solves skip
    the cap/used upload (solver.py)."""
    return solve_eval_batch_begin(
        state, planner, evals, config, solve_fn, solve_preempt_fn, resident
    ).finish()


class PendingEvalBatch:
    """Two-phase solve_eval_batch: begin() has reconciled every eval and
    dispatched the device kernel; finish() blocks on the device,
    materializes Allocations, and assembles the per-eval Plans. The
    pipelined TPU worker hands this across its solve→commit stage
    boundary so the device round-trip and plan materialization of batch
    N overlap batch N+1's reconcile/lower/dispatch."""

    def __init__(self, state, evals, plans, pending, config, solver,
                 asks=None) -> None:
        self.state = state
        self.evals = evals
        self.plans = plans
        self._pending = pending
        self.config = config
        self._solver = solver
        # the reconciled asks, kept for solve_host_fallback: a failed
        # device stage re-solves THESE (reconcile is not re-run, so
        # followup evals created during it are never duplicated)
        self._asks = asks or []
        self._finished = False

    @property
    def chain(self):
        """(node_ids, used' device array) from this batch's solve: the
        NEXT in-flight batch chains on it to stay conflict-free while
        this one's commit is still pending (solver.py used_chain). Read
        live from the solver, not snapshotted at begin(): the
        spread-relaxation retry in finish() refreshes chain_out with its
        own placements, and a reference swap is atomic so a concurrent
        reader sees either consistent tuple."""
        return self._solver.chain_out

    @property
    def used_micro(self) -> bool:
        """Did this solve run the host microsolve kernel? (zero device
        round-trip; the worker's lane telemetry reads it)."""
        return self._solver.used_micro

    @property
    def chain_accepted(self) -> bool:
        """Did this solve actually consume the used_chain it was given?
        False when the host path ran, resident tensors won, or the chain
        was rejected on a node-universe/shape mismatch — in those cases
        the solve saw only committed state and a failed parent commit
        does not invalidate it."""
        return self._solver.chain_accepted

    def finish(self) -> dict[str, Plan]:
        # Idempotent at THIS layer too: PendingSolve caches its outcome,
        # but re-running _attach_outcome would append every placement and
        # preemption to the plans a second time.
        if not self._finished:
            outcome = self._pending.finish()
            with paused_gc():
                t0 = time.monotonic_ns()
                _attach_outcome(self.state, self.evals, self.plans, outcome)
                trace.stage("plan.assemble", time.monotonic_ns() - t0)
            self._finished = True
        return self.plans

    def solve_host_fallback(self) -> dict[str, Plan]:
        """Re-solve this batch's asks entirely on the host oracle after
        a retriable device-stage failure (worker.py device failover).

        Reuses the reconcile output verbatim — the plans' stop/update
        halves and any followup evals already created stay as they are;
        only the placement solve re-runs, with small_batch_threshold
        forced past the batch size so no device dispatch can recur. The
        fresh solver exposes no chain (chain_out None, chain_accepted
        False): the worker marks the batch's chain verdict failed so a
        chained child re-solves against committed state.

        Deliberately degraded semantics, both directions of the chain:
        any used_chain THIS solve consumed is dropped too (the host
        oracle has no device tensor to chain on), so the fallback sees
        only committed state and may double-book nodes the still-
        uncommitted parent batch filled — the plan applier's optimistic
        verification trims those and the evals retry. A custom solve_fn
        is likewise not reused: the fallback's whole point is to avoid
        the failing device path, and the host oracle is the common-
        denominator semantics every kernel is differentially tested
        against."""
        if self._finished:
            return self.plans
        import copy

        cfg = copy.copy(self.config)
        cfg.small_batch_threshold = 1 << 62
        solver = BatchSolver(self.state, cfg)
        with paused_gc():
            outcome = solver.solve(self._asks)
            t0 = time.monotonic_ns()
            _attach_outcome(self.state, self.evals, self.plans, outcome)
            trace.stage("plan.assemble", time.monotonic_ns() - t0)
        self._solver = solver
        self._finished = True
        return self.plans


def solve_eval_batch_begin(
    state,
    planner,
    evals: list[Evaluation],
    config: Optional[SchedulerConfig] = None,
    solve_fn=None,
    solve_preempt_fn=None,
    resident=None,
    used_chain=None,
    extra_usage=None,
) -> PendingEvalBatch:
    """Phase A of solve_eval_batch: reconcile + lower + async device
    dispatch. Returns a PendingEvalBatch; call finish() for the plans.
    used_chain — the previous (still-uncommitted) batch's
    PendingEvalBatch.chain, so this solve sees its placements.
    extra_usage — per-node (cpu, mem, disk) usage deltas beyond the
    snapshot (the worker's interactive-lane ledger), counted by the
    aggregate fast path so a chained solve stays conflict-free with
    lane placements the chain tensor never saw."""
    config = config or SchedulerConfig()
    with paused_gc():
        t0 = time.monotonic_ns()
        plans, asks = _reconcile_eval_batch(state, planner, evals, config)
        trace.stage("reconcile", time.monotonic_ns() - t0)
        # asks-per-batch telemetry: how much work one solver dispatch
        # carries (occupancy's numerator lives solver-side; this is the
        # demand side the broker drained into the batch)
        solverobs.note_asks(
            len(asks), sum(len(a.requests) for a in asks)
        )
        solver = BatchSolver(
            state, config, solve_fn=solve_fn,
            solve_preempt_fn=solve_preempt_fn, resident=resident,
            used_chain=used_chain, mesh=_mesh_for(config, solve_fn),
            extra_usage=extra_usage,
        )
        pending = solver.solve_begin(asks)
    return PendingEvalBatch(
        state, evals, plans, pending, config, solver, asks=asks
    )


def _reconcile_eval_batch(
    state,
    planner,
    evals: list[Evaluation],
    config: SchedulerConfig,
) -> tuple[dict[str, Plan], list[GroupAsk]]:
    plans: dict[str, Plan] = {}
    asks: list[GroupAsk] = []
    deployments: dict[str, object] = {}
    for ev in evals:
        job = state.job_by_id(ev.namespace, ev.job_id)
        plan = ev.make_plan(job)
        plans[ev.id] = plan
        allocs = state.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(state, allocs)
        update_non_terminal_allocs_to_lost(plan, tainted, allocs)
        if job is None or job.stopped():
            for a in allocs:
                if not a.terminal_status():
                    plan.append_stopped_alloc(a, "alloc not needed", "")
            continue
        deployment = state.latest_deployment_by_job(ev.namespace, ev.job_id)
        if deployment is not None and not deployment.active() and (
            deployment.status != DEPLOYMENT_STATUS_FAILED
        ):
            deployment = None
        reconciler = AllocReconciler(
            job,
            ev.job_id,
            allocs,
            tainted,
            ev,
            deployment=deployment,
            batch=(ev.type == "batch"),
        )
        results = reconciler.compute()
        for fe in results.followup_evals:
            planner.create_eval(fe)
        if results.deployment is not None:
            plan.deployment = results.deployment
            deployments[ev.id] = results.deployment
        plan.deployment_updates = results.deployment_updates
        for alloc, desc, client_status in results.stop:
            plan.append_stopped_alloc(alloc, desc, client_status)
        for updated in results.inplace_update:
            plan.append_alloc(updated, updated.job)
        for alloc_id, follow_id in results.attr_updates.items():
            existing = state.alloc_by_id(alloc_id)
            if existing is not None:
                annotated = existing.copy()
                annotated.followup_eval_id = follow_id
                plan.append_alloc(annotated, annotated.job)
        place_requests = []
        for old, req in results.destructive_update:
            plan.append_stopped_alloc(old, "alloc not needed due to job update", "")
            place_requests.append(req)
        place_requests.extend(results.place)
        for pjob, tg_name, reqs in _bucket_requests(job, place_requests):
            asks.append(GroupAsk(ev, pjob, tg_name, reqs, plan=plan))
    return plans, asks


def _attach_outcome(
    state, evals: list[Evaluation], plans: dict[str, Plan], outcome
) -> None:
    """Fold a SolveOutcome back into the per-eval plans (phase B)."""
    for ev in evals:
        plan = plans[ev.id]
        job = state.job_by_id(ev.namespace, ev.job_id)
        deployment = plan.deployment or (
            state.latest_deployment_by_job(ev.namespace, ev.job_id)
            if job is not None
            else None
        )
        if deployment is not None and job is not None and (
            not getattr(deployment, "active", lambda: False)()
            or deployment.job_version != job.version
        ):
            deployment = None
        for alloc in outcome.placements.get(ev.id, []):
            if deployment is not None and job is not None and job.type == "service":
                tg = job.lookup_task_group(alloc.task_group)
                if tg is not None and tg.update is not None:
                    alloc.deployment_id = deployment.id
                    dstate = deployment.task_groups.get(alloc.task_group)
                    if dstate is not None and deployment is plan.deployment:
                        dstate.placed_allocs += 1
            if alloc.id not in outcome.pre_appended:
                # downgraded placements already carry their (old) job
                plan.append_fresh_alloc(alloc, alloc.job or job)
        for batch in outcome.batch_placements.get(ev.id, []):
            # SoA plan assembly: one append per batch; deployment id is
            # the shared column, placed-alloc accounting one increment
            if deployment is not None and job is not None and job.type == "service":
                tg = job.lookup_task_group(batch.task_group)
                if tg is not None and tg.update is not None:
                    batch.deployment_id = deployment.id
                    dstate = deployment.task_groups.get(batch.task_group)
                    if dstate is not None and deployment is plan.deployment:
                        dstate.placed_allocs += len(batch)
            plan.append_placement_batch(batch)
        for victim, by_id in outcome.preemptions.get(ev.id, []):
            # a pre-appended preemptOR already carried its victims in
            if by_id not in outcome.pre_appended:
                plan.append_preempted_alloc(victim, by_id)
        ev.failed_tg_allocs = outcome.failures.get(ev.id, {})
