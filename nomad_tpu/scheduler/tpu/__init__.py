from .kernels import (
    make_sharded_solver,
    make_sharded_solver_preempt,
    pad_g,
    pad_n,
    solve_placement,
)
from .lower import build_node_table, lower_group
from .sharding import SolverMesh, solver_mesh
from .scheduler import (
    PendingEvalBatch,
    TPUBatchScheduler,
    TPUGenericScheduler,
    solve_eval_batch,
    solve_eval_batch_begin,
)
from .solver import BatchSolver, GroupAsk, PendingSolve, ResidentClusterState
