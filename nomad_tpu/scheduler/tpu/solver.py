"""The batched TPU placement solver.

Orchestration (reference analog: the per-eval loop in
scheduler/generic_sched.go computePlacements :472, batched here across all
pending evaluations — SURVEY.md north star):

  1. host: reconcile each eval (unchanged AllocReconciler) → placement asks
  2. host: lower nodes + groups to tensors (lower.py)
  3. device: solve_placement kernel — score + waterfill every group
  4. host: read back [G, N] assignment counts, pick ports (NetworkIndex),
     mint Allocations, split into per-eval Plans, and *verify* every node
     with the exact host-oracle AllocsFit — any overflow is repaired by
     dropping that node's placements back to the failed list.

The plans then feed the standard plan-queue/applier path unchanged; partial
rejection and RefreshIndex semantics are untouched.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field, fields as dataclass_fields
from typing import Optional

import numpy as np

from ...structs import (
    AllocMetric,
    AllocatedResources,
    AllocatedTaskResources,
    Allocation,
    Evaluation,
    Job,
    NetworkIndex,
    Plan,
    generate_uuid,
    generate_uuids,
    now_ns,
)
from ... import solverobs, trace
from ...gctune import paused_gc
from ..context import EvalContext, SchedulerConfig
from ..reconcile import PlacementRequest
from ..util import ready_nodes_in_dcs
from ...structs.structs import AllocDeploymentStatus
from ...structs.placement_batch import PlacementBatch
from ..preemption import PRIORITY_DELTA
from .lower import LoweredGroup, build_node_table, lower_group
from .kernels import (
    pad_c,
    pad_g,
    pad_n,
    solve_placement,
    solve_placement_compact,
    solve_placement_preempt,
)

logger = logging.getLogger("nomad_tpu.scheduler.tpu")


@dataclass
class GroupAsk:
    eval_obj: Evaluation
    job: Job
    tg_name: str
    requests: list[PlacementRequest]
    # The eval's plan-so-far (stops/updates appended by the reconciler pass):
    # distinct_hosts / distinct_property / capacity must see vacated slots.
    plan: Optional[Plan] = None


@dataclass
class SolveOutcome:
    # eval_id -> plan additions
    placements: dict[str, list[Allocation]] = field(default_factory=dict)
    # eval_id -> SoA PlacementBatches (the fast-mint path's columns;
    # structs/placement_batch.py) — plan assembly appends these whole,
    # never as per-row Allocations
    batch_placements: dict[str, list] = field(default_factory=dict)
    # eval_id -> {tg_name: AllocMetric} for failed asks
    failures: dict[str, dict[str, AllocMetric]] = field(default_factory=dict)
    # eval_id -> [(victim alloc, preempting alloc id)] — the caller turns
    # these into plan.node_preemptions entries
    preemptions: dict[str, list[tuple[Allocation, str]]] = field(
        default_factory=dict
    )
    groups: int = 0
    solve_ns: int = 0
    # ids of allocs the solver already appended to their ask's plan
    # (the host fast path accumulates into the plan so later selects see
    # earlier placements); the caller must not append those again.
    # Per-ALLOC because one eval can mix host-path asks (sticky groups)
    # with dense-kernel asks in the same batch.
    pre_appended: set = field(default_factory=set)


def _merge_outcomes(a: SolveOutcome, b: SolveOutcome) -> SolveOutcome:
    """Union of two partial solves (host-path sticky asks + dense rest)."""
    out = SolveOutcome()
    for src in (a, b):
        for ev, allocs in src.placements.items():
            out.placements.setdefault(ev, []).extend(allocs)
        for ev, batches in src.batch_placements.items():
            out.batch_placements.setdefault(ev, []).extend(batches)
        for ev, fails in src.failures.items():
            out.failures.setdefault(ev, {}).update(fails)
        for ev, pre in src.preemptions.items():
            out.preemptions.setdefault(ev, []).extend(pre)
        out.pre_appended |= src.pre_appended
    out.groups = a.groups + b.groups
    out.solve_ns = a.solve_ns + b.solve_ns
    return out


def group_alloc_metric(grp: LoweredGroup, n: int) -> AllocMetric:
    """AllocMetric for a dense-path group: the lowered feasibility mask
    IS the evaluation record, so nodes_evaluated/nodes_filtered fall out
    of it directly and the per-screen attrition (lower_group's
    filtered_dims) maps onto the reference's per-checker counts —
    resource-shaped screens (cores, network capacity/ports) read as
    dimension_exhausted, membership screens (datacenter, driver,
    constraints, volumes) as constraint_filtered. `alloc status` and the
    blackbox timeline explain a fast-mint placement the same way the
    host GenericStack explains an iterator-path one."""
    metric = AllocMetric(nodes_evaluated=n)
    metric.nodes_filtered = n - int(np.sum(grp.feasible))
    for dim, dropped in grp.filtered_dims.items():
        if dim == "cores" or dim.startswith("network."):
            metric.dimension_exhausted[dim] = dropped
        else:
            metric.constraint_filtered[dim] = dropped
    return metric


class ResidentClusterState:
    """Device-resident capacity/usage tensors reused across solves.

    Re-uploading the full [N, 3] cap/used tensors every solve is
    redundant when the node universe is stable between batches: cap
    changes only on node register/update and usage changes only by the
    deltas of applied plans. This keeps both as DEVICE arrays at the
    padded bucket shape and, per solve, ships only the rows that changed
    since the last sync (diffed against the store's incremental per-node
    usage aggregate, state/store.py IDX_NODE_USED). Through a
    high-latency link (the axon tunnel here; PCIe/DCN generally) that
    turns the steady-state upload into the per-batch group tensors plus
    a usually-empty delta — the round-trip amortization VERDICT r4
    item 2 asked for. Single-writer by design: the server's TPU worker
    owns one instance (the eval broker already serializes solves).

    mesh — an optional sharding.SolverMesh: the resident tensors are
    then placed ONCE with the node-axis NamedSharding (each device owns
    its [N/D, R] rows) and never re-upload whole; a delta sync's row
    scatter lands in the owning shard (XLA routes the replicated update
    rows to the shard that holds the index), recorded as ``scatter``
    bytes on the transfer ledger.
    """

    def __init__(self, mesh=None) -> None:
        self.mesh = mesh
        self._node_vers: Optional[tuple] = None
        self._usage: dict[str, tuple] = {}
        self._cap_dev = None
        self._used_dev = None
        self._np = 0
        # host-side NodeTable skeleton cached across solves (same
        # node-universe fingerprint as the device tensors): attribute /
        # driver interning and the capacity columns survive, only the
        # usage rows refresh per solve
        self._host_table = None
        self._host_vers: Optional[tuple] = None
        # the previous solve's returned table, held one solve gap so
        # the skeleton can harvest its lazily-built SoA columns
        self._last_table = None
        # warm eval-context caches (the interactive fast path): ready
        # node lists per dc set, keyed by the nodes-table index, and
        # lowered-group skeletons (feasibility/bias/unit-cap tensors)
        # keyed by (job identity, tg) against the host-table fingerprint
        # — a repeat-shaped eval skips both the node scan and the
        # lowering entirely.
        self._node_cache: dict[tuple, tuple] = {}
        self._lowered: dict[tuple, tuple] = {}
        # telemetry: how the last sync was satisfied
        self.last_sync = "cold"

    def ready_nodes(self, state, datacenters: tuple):
        """Cached ready_nodes_in_dcs keyed by (dc glob set, nodes-table
        index). The nodes-table index moves on any node register /
        status / drain write — exactly the events that change ready-node
        membership — and alloc/usage writes leave it alone, so a warm
        entry survives steady-state scheduling traffic untouched."""
        from ..util import ready_nodes_in_dcs

        idx_fn = getattr(state, "nodes_table_index", None)
        if idx_fn is None:
            return ready_nodes_in_dcs(state, list(datacenters))
        idx = idx_fn()
        entry = self._node_cache.get(datacenters)
        if entry is not None and entry[0] == idx:
            return entry[1], entry[2]
        nodes, counts = ready_nodes_in_dcs(state, list(datacenters))
        if len(self._node_cache) > 64:
            self._node_cache.clear()
        self._node_cache[datacenters] = (idx, nodes, counts)
        return nodes, counts

    def lowered_skeleton(self, vers, job, tg_name: str):
        """Cached (ask, feasible, bias, units_cap, filtered_dims) for
        one task group
        against the host-table fingerprint `vers` (identity compare:
        host_table interns one tuple per node-universe generation).
        Arrays are shared read-only — every consumer (dedupe, spread
        splits, the micro kernel) copies before mutating."""
        key = (job.namespace, job.id, job.version, job.modify_index,
               tg_name)
        entry = self._lowered.get(key)
        if entry is not None and entry[0] is vers:
            return entry[1]
        return None

    def store_lowered(self, vers, job, tg_name: str, tensors) -> None:
        if len(self._lowered) > 256:
            self._lowered.clear()
        self._lowered[
            (job.namespace, job.id, job.version, job.modify_index, tg_name)
        ] = (vers, tensors)

    def host_table(self, nodes: list, allocs_by_node, usage_of):
        """Cached build_node_table for the usage-aggregate path.

        Rebuilding the 100k-row host table every solve was the largest
        steady-state host cost of the sharded bench (~0.7s/solve at c2m
        scale, plus re-interning every constraint attribute). The
        skeleton (cap, index_of, dc codes, attr/driver interning) is
        valid as long as every node's (id, modify_index) is unchanged.

        Every call returns a FRESH NodeTable object that shares only
        the immutable skeleton: pipelined batches overlap (batch N's
        finish runs while batch N+1's begin re-reads usage), so handing
        consecutive solves one mutated-in-place table would race batch
        N's overflow-repair reads against batch N+1's usage refresh.
        Per-solve state — the usage rows, the snapshot accessor, the
        static-port masks — is this table's own; the shared attr/driver
        caches are append-only interning keyed by node attrs the
        fingerprint already pins."""
        from .lower import NodeTable

        def clone(src, used_arr, accessor):
            out = NodeTable(
                nodes=src.nodes,
                index_of=src.index_of,
                cap=src.cap,
                used=used_arr,
                datacenters=src.datacenters,
                dc_values=src.dc_values,
                tier_prios=src.tier_prios,
                tier_used=src.tier_used,
                cores_free=src.cores_free,
                _attr_cache=src._attr_cache,
                _driver_cache=src._driver_cache,
            )
            out._allocs_by_node = accessor
            # SoA id/name columns are node-set-derived: share the
            # interned lists instead of rebuilding 100k-string columns
            for col in ("_node_id_col", "_node_name_col"):
                cached = getattr(src, col, None)
                if cached is not None:
                    setattr(out, col, cached)
            return out

        vers = tuple((node.id, node.modify_index) for node in nodes)
        skel = self._host_table
        if skel is None or self._host_vers != vers:
            t = build_node_table(nodes, allocs_by_node, usage_of=usage_of)
            # The cached skeleton carries NO snapshot accessor: holding
            # this solve's allocs_by_node closure would pin its whole
            # state snapshot for as long as the node fingerprint stays
            # stable (hours on a quiet cluster). The live table keeps
            # its accessor; only the cache copy is stripped.
            self._host_table = clone(t, t.used, None)
            self._host_vers = vers
            self._last_table = t
            return t
        # Harvest SoA columns lazily built on the previous solve's table
        # into the skeleton, then drop the reference — _last_table pins
        # at most one solve's snapshot, the same one the pipelined
        # overlap (finish(N) concurrent with begin(N+1)) keeps live
        # anyway.
        last = self._last_table
        self._last_table = None
        if last is not None:
            for col in ("_node_id_col", "_node_name_col"):
                if getattr(skel, col, None) is None:
                    cached = getattr(last, col, None)
                    if cached is not None:
                        setattr(skel, col, cached)
        n = len(nodes)
        used = np.empty((n, 3), dtype=np.int64)
        for i, node in enumerate(nodes):
            u = usage_of(node.id)
            used[i, 0] = u[0]
            used[i, 1] = u[1]
            used[i, 2] = u[2]
        t2 = clone(skel, used, allocs_by_node)
        self._last_table = t2
        return t2

    def sync(self, snapshot, nodes: list) -> tuple:
        """Return (cap_dev, used_dev) current for `nodes` (table order).

        Full re-upload when the node universe/capacity changed
        (fingerprint: per-node (id, modify_index)); otherwise a
        scatter-update of just the usage rows whose committed aggregate
        moved since the last solve.
        """
        import jax
        import jax.numpy as jnp

        n = len(nodes)
        np_ = self.mesh.pad_nodes(n) if self.mesh is not None else pad_n(n)
        vers = tuple((node.id, node.modify_index) for node in nodes)
        usage = {
            node.id: snapshot.node_usage(node.id) for node in nodes
        }
        if (
            self._node_vers != vers
            or self._np != np_
            or self._cap_dev is None
        ):
            # identical clipping to _lower_small so the resident tensors
            # are bit-equal to what a fresh upload would carry
            cap = np.zeros((np_, 3), dtype=np.int32)
            used = np.zeros((np_, 3), dtype=np.int32)
            cap_rows = np.array(
                [
                    (a.cpu, a.memory_mb, a.disk_mb)
                    for a in (node.available_resources() for node in nodes)
                ],
                dtype=np.int64,
            ).reshape(n, 3)
            used_rows = np.array(
                [usage[node.id][:3] for node in nodes], dtype=np.int64
            ).reshape(n, 3)
            cap[:n] = np.clip(cap_rows, 0, 2**31 - 1)
            used[:n] = np.clip(used_rows, 0, 2**31 - 1)
            t_up0 = now_ns()
            if self.mesh is not None:
                # placed per-shard ONCE: each device gets its own node
                # rows and the full tensors never re-upload again
                sharding = self.mesh.node_sharding()
                self._cap_dev = jax.device_put(cap, sharding)
                self._used_dev = jax.device_put(used, sharding)
            else:
                self._cap_dev = jax.device_put(cap)
                self._used_dev = jax.device_put(used)
            # block before timestamping: device_put only ENQUEUES on
            # async backends, and an un-awaited span would read ~0 on
            # exactly the slow-link deployments the span exists to
            # expose (the full sync is rare — node-universe changes)
            jax.block_until_ready(self._used_dev)
            solverobs.record_transfer(
                "h2d", cap.nbytes + used.nbytes,
                dur_ns=now_ns() - t_up0, span=True,
            )
            self._node_vers = vers
            self._np = np_
            self._usage = usage
            self.last_sync = "full"
            return self._cap_dev, self._used_dev
        prev = self._usage
        changed_idx = [
            i for i, node in enumerate(nodes)
            if usage[node.id] != prev.get(node.id, (0, 0, 0, 0))
        ]
        if changed_idx:
            rows = np.clip(
                np.array(
                    [usage[nodes[i].id][:3] for i in changed_idx],
                    dtype=np.int64,
                ),
                0,
                2**31 - 1,
            ).astype(np.int32)
            idx = np.asarray(changed_idx, dtype=np.int32)
            self._used_dev = _scatter_rows(
                self._used_dev, idx, rows,
                shard_tag=self.mesh.n_dev if self.mesh is not None else 0,
            )
            # bytes only, no span: the scatter call above is a jit
            # DISPATCH (a new idx shape trace/compiles synchronously —
            # timed_call ledgers that as solver.compile), so timing it
            # as a transfer would attribute compile cost to the link
            solverobs.record_transfer("h2d", rows.nbytes + idx.nbytes)
            if self.mesh is not None:
                # sharded resident: the delta rows land in their owning
                # shard — ledgered as scatter traffic so a delta storm
                # is visible next to the all-gather column
                solverobs.record_transfer("scatter", rows.nbytes)
            self._usage = usage
            self.last_sync = f"delta:{len(changed_idx)}"
        else:
            self.last_sync = "clean"
        return self._cap_dev, self._used_dev


def _pad_scatter_args(idx: np.ndarray, rows: np.ndarray):
    """Bucket a row-scatter's update shape (power of two, floor 1024)
    so the jit signature — and so the compile ledger — stays stable
    while the per-solve delta size drifts. Pad indices point past the
    array and the scatter jits run mode="drop", so pad rows never
    land."""
    n = idx.shape[0]
    b = 1024
    while b < n:
        b *= 2
    if b == n:
        return idx, rows
    pad_idx = np.full(b - n, 1 << 30, dtype=idx.dtype)
    pad_rows = np.zeros((b - n, rows.shape[1]), dtype=rows.dtype)
    return (
        np.concatenate([idx, pad_idx]),
        np.concatenate([rows, pad_rows]),
    )


def _scatter_rows(used_dev, idx, rows, donate: bool = True,
                  shard_tag: int = 0):
    """Row-scatter onto a resident device array. donate=True consumes
    the old buffer in place (sync updates — the resident array is
    replaced by its successor); donate=False leaves it intact (a
    per-batch adjusted view for vacated stops / partition placements).
    One jit per flavor, cached. shard_tag (the mesh size, 0 unsharded)
    keys the ledger signature: a sharded operand compiles its own SPMD
    executable even at equal shapes, and the ledger must count it."""
    import jax

    idx, rows = _pad_scatter_args(idx, rows)
    fn = _SCATTER_JITS.get(donate)
    if fn is None:

        def _scatter(used, idx, rows):
            return used.at[idx].set(rows, mode="drop")

        fn = _SCATTER_JITS[donate] = jax.jit(
            _scatter, donate_argnums=(0,) if donate else ()
        )
    return solverobs.timed_call(
        "scatter_rows",
        ("scatter_rows", donate, tuple(used_dev.shape), tuple(idx.shape),
         shard_tag),
        fn, used_dev, idx, rows,
    )


_SCATTER_JITS: dict = {}


def _scatter_add_rows(used_dev, idx, rows, shard_tag: int = 0):
    """Row-scatter-ADD (clamped at zero) onto a non-donated device usage
    array: applies a batch's vacated-stop deltas on top of a CHAINED
    used' tensor. A set-scatter of aggregate rows would clobber the
    chain's in-flight placements; the delta add preserves them."""
    import jax

    idx, rows = _pad_scatter_args(idx, rows)
    fn = _SCATTER_ADD_JIT.get("fn")
    if fn is None:
        import jax.numpy as jnp

        def _scatter_add(used, idx, rows):
            return jnp.maximum(used.at[idx].add(rows, mode="drop"), 0)

        fn = _SCATTER_ADD_JIT["fn"] = jax.jit(_scatter_add)
    return solverobs.timed_call(
        "scatter_add_rows",
        ("scatter_add_rows", tuple(used_dev.shape), tuple(idx.shape),
         shard_tag),
        fn, used_dev, idx, rows,
    )


_SCATTER_ADD_JIT: dict = {}


def _chain_adj_add(used_dev, table, adj, adj_in, shard_tag: int):
    """Apply the committed-gap usage DELTAS (`adj`) for the in-table
    node ids `adj_in` onto a CHAINED used' tensor — the one adj
    application both chain consumers (resident+chain and chain-only)
    share. Deltas, not aggregates: a set-scatter would clobber the
    parent's in-flight placements."""
    idx = np.asarray(
        [table.index_of[nid] for nid in adj_in], dtype=np.int32
    )
    rows = np.clip(
        np.asarray([adj[nid] for nid in adj_in], dtype=np.int64),
        -(2**31) + 1,
        2**31 - 1,
    ).astype(np.int32)
    return _scatter_add_rows(used_dev, idx, rows, shard_tag=shard_tag)


_ALLOC_FIELD_NAMES = tuple(f.name for f in dataclass_fields(Allocation))


class _MintTemplate:
    """Interned per-(job, taskgroup) Allocation prototype for the bulk
    fast-mint path: fresh solver placements within one group differ only
    in (id, name, node), so cloning the prototype via __new__ + slot
    copy-and-patch skips the dataclass constructor and its per-alloc
    default-factory constructions (~4 objects each across 10^5 mints at
    c2m scale). Shared sub-objects — resources, metrics, the empty
    containers — ride the state store's copy-on-write discipline: every
    writer copies an alloc (Allocation.copy deep-copies the mutable
    fields) before mutating, the same rule the shared AllocatedResources
    fast-mint has always relied on.

    With soa_placements the same template seeds whole PlacementBatches
    (shared resources/metrics objects across a group's sub-batches, the
    identical sharing the eager mint had); per-row mint survives as the
    eager comparator and the overflow-repair/cores paths."""

    __slots__ = ("items", "proto")

    def __init__(self, proto: Allocation) -> None:
        self.proto = proto
        self.items = [(n, getattr(proto, n)) for n in _ALLOC_FIELD_NAMES]

    def mint(self, uid: str, name: str, node) -> Allocation:
        a = Allocation.__new__(Allocation)
        for n, v in self.items:
            setattr(a, n, v)
        a.id = uid
        a.name = name
        a.node_id = node.id
        a.node_name = node.name
        return a


class PendingSolve:
    """An in-flight batch solve between its two phases.

    Phase A (already run): host prep + async device dispatch. finish()
    runs phase B — block on the device, injected-RTT wait, readback,
    materialization, spread-relaxation retry — and returns the
    SolveOutcome. Single-shot; the generator is dropped after finish so
    a double finish() returns the cached outcome."""

    __slots__ = ("_gen", "_outcome")

    def __init__(self, gen, outcome: Optional[SolveOutcome]) -> None:
        self._gen = gen
        self._outcome = outcome

    def finish(self) -> SolveOutcome:
        if self._gen is None:
            return self._outcome
        gen, self._gen = self._gen, None
        with paused_gc():
            try:
                next(gen)
            except StopIteration as s:
                self._outcome = s.value
                return self._outcome
        raise AssertionError("solver generator yielded more than once")


class BatchSolver:
    """Solves placement for a batch of evaluations against one snapshot."""

    def __init__(self, state, config: Optional[SchedulerConfig] = None,
                 solve_fn=None, solve_preempt_fn=None,
                 resident: Optional[ResidentClusterState] = None,
                 used_chain: Optional[tuple] = None,
                 mesh=None, extra_usage: Optional[dict] = None) -> None:
        self.state = state
        self.config = config or SchedulerConfig()
        # Multi-chip: a sharding.SolverMesh routes the dense solve
        # through the node-sharded kernels (distributed-top-k waterfill,
        # per-mesh jit cache) and places resident tensors per-shard.
        # The host fast paths (sticky partition, small batches) stay
        # live — the sharded kernel is bit-identical to solve_placement,
        # so the same routing rules hold.
        if mesh is not None and solve_fn is not None:
            raise ValueError("mesh and solve_fn are mutually exclusive")
        self.mesh = mesh
        # Device-resident cap/used tensors shared across solves (the
        # server's TPU worker owns one instance); None = upload per solve.
        self.resident = resident
        # Per-node (cpu, mem, disk) usage DELTAS external to this
        # snapshot that the aggregate fast path must still count — the
        # worker's interactive-lane ledger: placements a priority-lane
        # eval committed after the chain basis, which neither the
        # chained used' tensor nor (for in-flight ones) the committed
        # aggregate carries. Applied only on the usage-aggregate path
        # (the host stack path coordinates through plans instead).
        self.extra_usage = extra_usage
        # set when the solve ran the host microsolve kernel: zero device
        # involvement, chain neither consumed nor produced
        self.used_micro = False
        # host-table fingerprint token for the lowered-skeleton cache
        # (set when the resident host-table path produced this solve's
        # table; None disables the cache for the solve)
        self._lower_vers = None
        # (node_ids tuple, used_dev) — the PREVIOUS batch's post-solve
        # usage tensor, still on device. While that batch's commit is in
        # flight, the committed aggregate hasn't caught up, so a
        # deterministic binpack would re-place the next batch onto the
        # same nodes and the applier would reject everything. Chaining
        # the kernel's own used' output as the next solve's used input
        # keeps consecutive in-flight batches conflict-free WITHOUT
        # blocking on the device (a pure device-graph dependency) —
        # this is what makes the worker's solve/commit overlap pay at
        # high fill (docs/pipeline.md).
        self.used_chain = used_chain
        # set during phase A when the compact path dispatches: the
        # (node_ids, used' device array) the NEXT batch may chain on
        self.chain_out: Optional[tuple] = None
        # did this solve actually CONSUME used_chain? False when the
        # solve took the host/preempt path, the resident tensors won, or
        # the chain was rejected on a node-universe/shape mismatch — the
        # worker's chain-failure cascade only applies when this is True
        self.chain_accepted = False
        self.ctx = EvalContext(state, None, logger, self.config)
        self.solve_fn = solve_fn or solve_placement
        # Preemption kernel seam: defaults to the single-chip tier kernel
        # when the plain kernel is the default; a custom solve_fn (e.g. a
        # mesh-sharded solver) must bring its own preempt variant
        # (make_sharded_solver_preempt) or preemption is disabled for it.
        if solve_preempt_fn is not None:
            self.solve_preempt_fn = solve_preempt_fn
        elif mesh is not None:
            self.solve_preempt_fn = mesh.preempt_solver()
        elif solve_fn is None:
            self.solve_preempt_fn = solve_placement_preempt
        else:
            self.solve_preempt_fn = None
        # Port-accounting index per node, shared across the whole batch so
        # placements in this solve see each other's port reservations.
        self._net_cache: dict[str, NetworkIndex] = {}
        # Per-node device allocator, shared across the batch (like the
        # port index above) so placements see each other's reservations.
        self._dev_cache: dict[str, object] = {}
        # Per-node (free dedicated-core ids, MHz/core), shared across
        # the batch; the list is mutated in place as grants are cut.
        self._core_cache: dict[str, tuple] = {}
        # set by solve(): with no cores ask anywhere in the batch the
        # dense solve's declared-MHz accounting is exact and the ledger
        # (an O(allocs-per-node) state scan per node) is skipped
        self._batch_has_cores = False
        # allocs stopped by this batch's plans: vacated for seeding
        self._stopped_ids: set = set()
        # Per-node cpu MHz ledger. The dense solve packs the DECLARED
        # cpu ask; a `cores` task's granted cpu is DERIVED (cores x
        # MHz/core) and can exceed it, so cores placements re-screen
        # against real remaining MHz (rank.py does the same superset
        # re-check on the host path). _state_cpu is the committed-state
        # baseline; _batch_cpu tracks EVERY placement this solve makes
        # (fast path included) so the screen sees same-batch neighbors.
        self._state_cpu: dict[str, int] = {}
        self._batch_cpu: dict[str, int] = {}
        # Set while solving the dense remainder of a mixed batch: the
        # host partition's placements (capacity) and plans (cross-eval
        # accounting) that this solve must observe.
        self._partition_placed: list = []
        self._partition_plans: list = []
        # (eval_id, id(job), tg_name) -> _MintTemplate, shared across a
        # batch's groups (spread sub-groups and the relaxation retry
        # re-hit it; keyed by eval so same-job evals never cross-stamp).
        self._mint_cache: dict[tuple, _MintTemplate] = {}

    def _pad_n(self, n: int) -> int:
        """Node-axis bucket: the mesh extends pad_n to a multiple of the
        device count so every shard is equal-width (pad rows carry zero
        capacity and can never place)."""
        if self.mesh is not None:
            return self.mesh.pad_nodes(n)
        return pad_n(n)

    def solve(self, asks: list[GroupAsk]) -> SolveOutcome:
        return self.solve_begin(asks).finish()

    def solve_begin(self, asks: list[GroupAsk]) -> "PendingSolve":
        """Phase A of a two-phase solve: reconcile-independent host prep
        (node table, lowering, ledgers) plus the ASYNC device dispatch.
        Returns a PendingSolve whose finish() blocks on the device, reads
        back, and materializes Allocations — the pipelined worker runs
        finish() on its commit stage so batch N's readback/materialization
        overlaps batch N+1's host prep and device round-trip."""
        # One batch is a bounded allocation burst (up to ~100k minted
        # allocs at c2m scale); young-gen GC passes during it cost more
        # than everything they could ever reclaim (gctune.py).
        gen = self._solve_gen(asks)
        with paused_gc():
            try:
                next(gen)
            except StopIteration as s:
                # host-only solve (small batch / empty / host partition):
                # finished without touching the device
                return PendingSolve(None, s.value)
        return PendingSolve(gen, None)

    def _solve_gen(self, asks: list[GroupAsk]):
        out = SolveOutcome()
        self._batch_has_cores = any(
            t.resources.cores > 0
            for ask in asks
            for tg in [ask.job.lookup_task_group(ask.tg_name)]
            if tg is not None
            for t in tg.tasks
        )
        self._outcome = out
        if not asks:
            return out
        # Asks needing per-request node preference — sticky-disk
        # replacements (prefer the previous node) and reschedules with a
        # node penalty (avoid it) — take the host path; the dense kernel
        # only expresses per-GROUP bias. The rest of the batch solves
        # dense, with the host partition's placements counted against
        # node capacity. A custom solve_fn keeps the whole batch (its
        # topology logic must not be bypassed; preference degrades to
        # none there).
        if self.solve_fn is solve_placement:
            from ..reconcile import PlacementRun

            sticky_idx = set()
            for i, ask in enumerate(asks):
                if isinstance(ask.requests, PlacementRun):
                    # shared-proto fresh fills carry no previous alloc
                    # or penalty node by construction — and iterating
                    # the run here would mint every row it exists to
                    # avoid
                    continue
                tg = ask.job.lookup_task_group(ask.tg_name)
                sticky = (
                    tg is not None
                    and tg.ephemeral_disk.sticky
                    and any(r.previous_alloc is not None for r in ask.requests)
                )
                if sticky or any(r.penalty_node for r in ask.requests):
                    sticky_idx.add(i)
            if sticky_idx:
                sticky_asks = [a for i, a in enumerate(asks) if i in sticky_idx]
                host_out = self._solve_host(sticky_asks)
                rest = [a for i, a in enumerate(asks) if i not in sticky_idx]
                if not rest:
                    return host_out
                # the rest-solve must see the host partition's results:
                # its placements consume capacity; its plans feed the
                # host fast path's cross-eval accounting
                self._partition_placed = [
                    a
                    for allocs_ in host_out.placements.values()
                    for a in allocs_
                ]
                self._partition_plans = [
                    a.plan for a in sticky_asks if a.plan is not None
                ]
                try:
                    dense_out = self.solve(rest)
                finally:
                    self._partition_placed = []
                    self._partition_plans = []
                return _merge_outcomes(host_out, dense_out)
        total_requests = sum(len(a.requests) for a in asks)
        # A custom solve_fn (e.g. the mesh-sharded solver) must never be
        # silently bypassed — the fast path exists for the default kernel's
        # device round-trip only (same precedent as the compact path).
        small = (
            total_requests <= self.config.small_batch_threshold
            and self.solve_fn is solve_placement
        )
        # Small batches prefer the MICROSOLVE: the dense pipeline with
        # the numpy kernel (microsolve.py) — zero device round-trip,
        # shared lowering/materialization semantics. Ineligible shapes
        # (cores asks, a preemption-capable batch, a sharded mesh, or a
        # node universe past the n·g threshold) fall back to the host
        # iterator stack exactly as before.
        micro_wanted = (
            small
            and self.mesh is None
            and self.config.micro_solve_threshold > 0
            and not self._batch_has_cores
        )
        if small and not micro_wanted:
            return self._solve_host_timed(asks, total_requests)
        # Priority order: higher-priority jobs consume capacity first
        # (mirrors the eval broker's priority dequeue).
        asks = sorted(asks, key=lambda a: -a.job.priority)

        # One node universe per batch. Union of the jobs' datacenters,
        # scanning the node table once per DISTINCT dc set, not per ask —
        # and skipping the union dict entirely in the common one-dc-set
        # case (it was a million dict writes at c2m scale).
        dc_cache: dict[tuple, list] = {}
        for ask in asks:
            key = tuple(ask.job.datacenters)
            if key not in dc_cache:
                if self.resident is not None:
                    # warm node-list cache keyed by the nodes-table
                    # index (ResidentClusterState.ready_nodes)
                    dc_cache[key] = self.resident.ready_nodes(
                        self.state, key
                    )[0]
                else:
                    dc_cache[key] = ready_nodes_in_dcs(
                        self.state, ask.job.datacenters
                    )[0]
        if len(dc_cache) == 1:
            nodes = next(iter(dc_cache.values()))
        else:
            all_nodes = {}
            for nodes_ in dc_cache.values():
                for node in nodes_:
                    all_nodes[node.id] = node
            nodes = list(all_nodes.values())
        if not nodes:
            for ask in asks:
                self._fail_all(out, ask, {})
            return out

        # Capacity freed by this batch's plans (stops/destructive updates)
        # is usable: plan application re-verifies, so optimistic batching
        # treats all batch stops as vacated (reference: the host oracle's
        # ProposedAllocs does the same per plan, context.go:120).
        stopped_ids: set[str] = set()
        for ask in asks:
            if ask.plan is not None:
                for allocs_ in ask.plan.node_update.values():
                    stopped_ids.update(a.id for a in allocs_)
        # the materializer's per-node seeds (ports/devices/cores/cpu)
        # must see the SAME vacated capacity as the dense table, or an
        # in-place replacement of a full node can never materialize
        self._stopped_ids = stopped_ids

        placed_by_node: dict[str, list] = {}
        for a in self._partition_placed:
            placed_by_node.setdefault(a.node_id, []).append(a)

        def live_allocs(nid: str):
            return [
                a
                for a in self.state.allocs_by_node_terminal(nid, False)
                if a.id not in stopped_ids
            ] + placed_by_node.get(nid, [])

        # Aggregate fast path: when the batch can neither preempt (no
        # tier tensors needed) nor ask for dedicated cores (no core
        # pools), per-node utilization comes straight from the store's
        # incremental aggregate — O(nodes), not O(allocs) — with this
        # batch's vacated stops and the host partition's placements
        # applied as per-node adjustments.
        preempt_possible = self.solve_preempt_fn is not None and any(
            self.config.preemption_enabled(a.job.type) for a in asks
        )
        if preempt_possible and hasattr(self.state, "alloc_priority_tiers"):
            # Exact O(1) refinement: preemption can only trigger when some
            # committed alloc's priority sits PRIORITY_DELTA below a batch
            # job's — the store's priority-count aggregate proves absence
            # without walking allocs (the common all-priority-50 cluster).
            maxprio = max(
                a.job.priority
                for a in asks
                if self.config.preemption_enabled(a.job.type)
            )
            tiers = list(self.state.alloc_priority_tiers())
            # same-batch host-partition placements are preemptible too
            # (they're in the dense table's live view)
            tiers.extend(
                a.job.priority if a.job is not None else 50
                for a in self._partition_placed
            )
            preempt_possible = any(
                maxprio - p >= PRIORITY_DELTA for p in tiers
            )
        if micro_wanted and preempt_possible:
            # preemption needs the tier kernel (or the host stack's
            # per-request evict pass) — keep the host path for it
            return self._solve_host_timed(asks, total_requests)
        usage_of = None
        if (
            not self._batch_has_cores
            and not preempt_possible
            and hasattr(self.state, "node_usage")
        ):
            adj: dict[str, list[int]] = {}

            def _adjust(nid: str, r, sign: int) -> None:
                d = adj.get(nid)
                if d is None:
                    d = adj[nid] = [0, 0, 0]
                d[0] += sign * r.cpu
                d[1] += sign * r.memory_mb
                d[2] += sign * r.disk_mb

            for sid in stopped_ids:
                stored = self.state.alloc_by_id(sid)
                if stored is not None and not stored.terminal_status():
                    _adjust(
                        stored.node_id, stored.comparable_resources(), -1
                    )
            for a in self._partition_placed:
                _adjust(a.node_id, a.comparable_resources(), +1)
            if self.extra_usage:
                # interactive-lane ledger (worker.py): placements the
                # priority lane committed past the chain basis — deltas,
                # so they compose with both the set-scatter and the
                # chained-add paths below
                for nid, vec in self.extra_usage.items():
                    d = adj.get(nid)
                    if d is None:
                        d = adj[nid] = [0, 0, 0]
                    d[0] += vec[0]
                    d[1] += vec[1]
                    d[2] += vec[2]
            state_usage = self.state.node_usage
            if adj:

                def usage_of(nid: str):
                    u = state_usage(nid)
                    d = adj.get(nid)
                    if d is None:
                        return u
                    return (u[0] + d[0], u[1] + d[1], u[2] + d[2])

            else:
                usage_of = state_usage

        if self.resident is not None and usage_of is not None:
            # cross-solve host-table cache: same fingerprint discipline
            # as the resident device tensors (ResidentClusterState)
            table = self.resident.host_table(nodes, live_allocs, usage_of)
            # lowered-skeleton cache rides the same fingerprint: valid
            # only for tables produced by this generation's skeleton
            self._lower_vers = self.resident._host_vers
        else:
            table = build_node_table(nodes, live_allocs, usage_of=usage_of)

        groups: list[LoweredGroup] = []
        base_of: dict[int, LoweredGroup] = {}  # group idx -> unrestricted base
        for ask in asks:
            tg = ask.job.lookup_task_group(ask.tg_name)
            if tg is None or not ask.requests:
                continue
            self.ctx.plan = ask.plan  # plan-aware distinct/property masks
            grp = self._lower_group_cached(table, ask, tg)
            for sub in self._split_for_spread(table, ask.job, tg, grp):
                base_of[len(groups)] = grp
                groups.append(sub)
            self.ctx.plan = None
        if not groups:
            return out
        out.groups = len(groups)

        n = table.n
        self._victimized: set[str] = set()
        used = np.clip(table.used, 0, 2**31 - 1).astype(np.int32)

        tier_limit = np.zeros(len(groups), dtype=np.int32)
        for i, grp in enumerate(groups):
            tier_limit[i] = self._tier_limit(table, grp)
        use_preempt = (
            bool(tier_limit.any()) and self.solve_preempt_fn is not None
        )
        # The compact readback contract covers the default single-chip
        # kernel AND the mesh path (the sharded compact kernel emits the
        # same [G, maxC] instance list); only the preemption kernels and
        # custom solve_fns return the dense [G, N] assignment.
        compact = not use_preempt and self.solve_fn is solve_placement
        # Microsolve verdict (the interactive fast path): the numpy
        # kernel replaces the device dispatch when the problem is tiny.
        # Past the n·g bound the batch keeps its historical host-stack
        # route — the lowering work above is wasted once, on the rare
        # small-requests-huge-cluster shape.
        micro = (
            micro_wanted
            and compact
            and n * len(groups) <= self.config.micro_solve_threshold
        )
        if micro_wanted and not micro:
            return self._solve_host_timed(asks, total_requests)

        t0 = now_ns()
        # Resident device tensors: valid only when the usage-aggregate
        # path produced the table (the sync diffs against the same
        # aggregate) — the batch adjustments are scattered onto a
        # non-donated copy so the resident buffer stays committed-state.
        # On a mesh the resident tensors are placed per-shard
        # (ResidentClusterState.mesh). A micro solve skips all of it:
        # the table's host arrays already carry the aggregate + adj.
        dev_state = None
        if compact and usage_of is not None and not micro:
            shard_tag = self.mesh.n_dev if self.mesh is not None else 0
            chain_used = None
            if self.used_chain is not None:
                chain_ids, chain_used = self.used_chain
                if not (
                    chain_ids == tuple(node.id for node in nodes)
                    and chain_used.shape == (self._pad_n(n), 3)
                ):
                    chain_used = None
            if self.resident is not None:
                cap_dev, used_dev = self.resident.sync(self.state, nodes)
                if chain_used is not None:
                    # Compose resident + chain: the chained used' tensor
                    # IS the resident usage as of the in-flight parent's
                    # solve plus its placements (the parent consumed the
                    # resident tensors), so it supersedes the committed
                    # aggregate while the parent's commit is pending —
                    # without it, a pipelined resident solver would
                    # re-place onto the parent's nodes and lean on
                    # applier rejections. cap still rides the resident
                    # shard (node-capacity changes invalidate the chain
                    # via the fingerprint/node-id check above).
                    used_dev = chain_used
                    self.chain_accepted = True
                # stops can reference nodes outside this batch's dc
                # universe — those rows aren't in the table (or tensors)
                adj_in = [nid for nid in adj if nid in table.index_of]
                if adj_in:
                    if chain_used is not None:
                        used_dev = _chain_adj_add(
                            used_dev, table, adj, adj_in, shard_tag
                        )
                    else:
                        idx = np.array(
                            [table.index_of[nid] for nid in adj_in],
                            dtype=np.int32,
                        )
                        rows = np.clip(
                            np.array(
                                [usage_of(nid)[:3] for nid in adj_in],
                                dtype=np.int64,
                            ),
                            0,
                            2**31 - 1,
                        ).astype(np.int32)
                        used_dev = _scatter_rows(
                            used_dev, idx, rows, donate=False,
                            shard_tag=shard_tag,
                        )
                dev_state = (cap_dev, used_dev)
            elif chain_used is not None:
                # Chain the in-flight previous batch's post-solve usage
                # (device array, never blocked on) so this batch's
                # waterfill sees its placements and stays conflict-free.
                used_dev = chain_used
                adj_in = [nid for nid in adj if nid in table.index_of]
                if adj_in:
                    used_dev = _chain_adj_add(
                        used_dev, table, adj, adj_in, shard_tag
                    )
                dev_state = (None, used_dev)
                self.chain_accepted = True
        if micro:
            inst, over, used_out = self._run_micro(
                table, groups, used, total_requests
            )
            # no chain_out: the micro result is host-known and commits
            # ahead of any in-flight mega-batch; conflict-freedom for
            # followers rides the worker's interactive ledger instead
        elif compact:
            pending = self._run_compact_async(
                table, groups, used, dev_state=dev_state
            )
            # expose this batch's post-solve usage for the NEXT batch's
            # chain (pending[2] is the kernel's used' device output)
            self.chain_out = (tuple(node.id for node in nodes), pending[2])
        else:
            # Exact-repair ledger as plain Python ints: it is touched once
            # per PLACED INSTANCE where small-array numpy ops cost ~10x an
            # int compare.
            self._free = [
                [int(c) for c in row] for row in (table.cap - table.used)
            ]
            pending = self._run_kernel_async(
                table, groups, used, tier_limit=tier_limit,
                use_preempt=use_preempt,
            )
        # -- phase boundary: the kernel is dispatched, nothing has read
        # it back. The pipelined worker parks here and resumes on its
        # commit stage, so the device round-trip (and everything below)
        # overlaps the NEXT batch's dequeue/reconcile/lower/dispatch.
        # A MICRO solve never parks: the result is already on the host,
        # so the whole solve completes in phase A and the worker's
        # commit stage has nothing to wait on (PendingSolve finishes
        # without a generator hop).
        phase_a_ns = now_ns() - t0
        if not micro:
            yield
        t0 = now_ns()
        if compact:
            if not micro:
                inst, over, used_out = self._run_compact_finish(pending)
            free_base = table.cap - table.used
            t_mat0 = now_ns()
            leftovers = self._materialize_compact(
                table, groups, inst, over, free_base
            )
            mat_ns = now_ns() - t_mat0
        else:
            assign, assign_evict, used_out = self._run_kernel_finish(pending)
            t_mat0 = now_ns()
            leftovers = self._materialize(table, groups, assign, assign_evict)
            mat_ns = now_ns() - t_mat0

        # Fallback pass: spread is a soft preference — requests a
        # value-restricted sub-group could not place retry against the
        # unrestricted base feasibility with updated utilization.
        retry: list[LoweredGroup] = []
        final_unplaced: dict[tuple, tuple[LoweredGroup, list]] = {}
        for gi, reqs in leftovers.items():
            grp = groups[gi]
            if reqs and grp.restricted:
                import dataclasses

                retry.append(
                    dataclasses.replace(
                        base_of[gi],
                        count=len(reqs),
                        names=[r.name for r in reqs],
                        requests=reqs,
                        restricted=False,
                    )
                )
            elif reqs:
                key = (grp.key[0], grp.tg.name)
                prev = final_unplaced.get(key)
                final_unplaced[key] = (grp, (prev[1] if prev else []) + reqs)
        if retry:
            # Spread-relaxation retry runs WITHOUT preemption: the tier
            # prefix tensors describe pre-solve usage and a second
            # preemption pass could double-claim the same victims.
            used2 = np.asarray(used_out)[:n]
            if micro:
                inst2, over2, used_retry = self._run_micro(
                    table, retry, used2, sum(g.count for g in retry)
                )
                t_mat0 = now_ns()
                leftovers2 = self._materialize_compact(
                    table, retry, inst2, over2, table.cap - used2
                )
                mat_ns += now_ns() - t_mat0
            elif compact:
                inst2, over2, used_retry = self._run_compact(
                    table, retry, used2
                )
                # Refresh the chain with the retry's used': the next
                # chained batch must see BOTH passes' placements, not the
                # pre-retry tensor. (Host-only overflow repair in
                # _materialize_compact still isn't reflected — the
                # applier's optimistic verification catches that residual
                # over-placement direction.)
                self.chain_out = (
                    tuple(node.id for node in nodes), used_retry
                )
                t_mat0 = now_ns()
                leftovers2 = self._materialize_compact(
                    table, retry, inst2, over2, table.cap - used2
                )
                mat_ns += now_ns() - t_mat0
            else:
                assign2, _, _ = self._run_kernel(
                    table, retry, used2, use_preempt=False
                )
                t_mat0 = now_ns()
                leftovers2 = self._materialize(table, retry, assign2, None)
                mat_ns += now_ns() - t_mat0
            for gi, reqs in leftovers2.items():
                grp = retry[gi]
                key = (grp.key[0], grp.tg.name)
                prev = final_unplaced.get(key)
                final_unplaced[key] = (grp, (prev[1] if prev else []) + reqs)

        # Failure metrics from the FINAL unplaced set (both passes).
        for (eval_id, tg_name), (grp, reqs) in final_unplaced.items():
            metric = group_alloc_metric(grp, n)
            metric.coalesced_failures = len(reqs) - 1
            out.failures.setdefault(eval_id, {})[tg_name] = metric
        # solve_ns excludes any pipeline gap between the two phases
        out.solve_ns = phase_a_ns + (now_ns() - t0)
        from ... import metrics

        metrics.time_ns("nomad.tpu.solve_seconds", out.solve_ns)
        # Alloc materialization joins the host_prep/device/readback stage
        # registry so the bench's breakdown covers the full commit half.
        metrics.time_ns("nomad.tpu.materialize_seconds", mat_ns)
        trace.stage("materialize", mat_ns)
        metrics.observe("nomad.tpu.solve_groups", out.groups)
        return out

    def _solve_host_timed(self, asks: list[GroupAsk],
                          total_requests: int) -> SolveOutcome:
        """The host-stack fast path with its historical telemetry."""
        from ... import metrics

        t0 = now_ns()
        out = self._solve_host(asks)
        out.solve_ns = now_ns() - t0
        metrics.time_ns("nomad.tpu.solve_seconds", out.solve_ns)
        metrics.observe("nomad.tpu.small_batch_requests", total_requests)
        trace.stage("host_solve", out.solve_ns)
        return out

    def _run_micro(self, table, groups: list[LoweredGroup], used_n,
                   total_requests: int):
        """Host microsolve dispatch: the numpy compact kernel over the
        UNPADDED table arrays — same readback contract as
        _run_compact_finish ((inst [G, maxC], over [N], used' [N, 3])),
        zero device involvement, zero jit signatures. The instance width
        is the groups' raw count bound (no pad_c bucketing: nothing is
        transferred, so width stability buys nothing)."""
        from ... import metrics
        from .microsolve import solve_placement_compact_micro

        t0 = now_ns()
        self.used_micro = True
        n = table.n
        maxc = max(1, max(int(grp.count) for grp in groups)) if groups \
            else 1
        inst, over, used_out = solve_placement_compact_micro(
            table.cap,
            np.asarray(used_n)[:n],
            [
                (
                    np.asarray(grp.ask, dtype=np.int64),
                    int(grp.count),
                    grp.feasible,
                    grp.bias,
                    np.asarray(grp.units_cap, dtype=np.int64),
                )
                for grp in groups
            ],
            maxc,
        )
        micro_ns = now_ns() - t0
        metrics.time_ns("nomad.tpu.micro_seconds", micro_ns)
        metrics.observe("nomad.tpu.micro_batch_requests", total_requests)
        trace.stage("micro_solve", micro_ns)
        return inst, over, used_out

    def _lower_group_cached(self, table, ask: GroupAsk, tg) -> LoweredGroup:
        """lower_group through the warm lowered-skeleton cache: a
        repeat-shaped eval (same job version, same node universe) reuses
        the feasibility/bias/unit-cap tensors instead of re-lowering.
        The cache holds the STATIC part only (no spread addend) — groups
        qualify via lower.group_lower_static_cacheable (no distinct_*
        constraints, volumes, static ports, or cores, whose masks read
        live state beyond the fingerprint); spread-carrying groups reuse
        the static tensors and re-add lower.spread_bias per solve."""
        from .lower import group_lower_static_cacheable, spread_bias

        res = self.resident
        vers = self._lower_vers
        if res is None or vers is None:
            return lower_group(
                self.ctx, table, ask.job, tg, ask.requests, ask.eval_obj.id
            )
        cached = res.lowered_skeleton(vers, ask.job, tg.name)
        if cached is not None:
            from .lower import request_names

            ask_vec, feas, bias, ucap, fdims = cached
            sb = spread_bias(self.ctx, table, ask.job, tg)
            if sb is not None:
                bias = bias + sb  # new array: the cached one is shared
            reqs = ask.requests
            return LoweredGroup(
                key=(ask.eval_obj.id, tg.name),
                job=ask.job,
                tg=tg,
                count=len(reqs),
                ask=ask_vec,
                feasible=feas,
                bias=bias,
                units_cap=ucap,
                priority=ask.job.priority,
                names=request_names(reqs),
                requests=reqs,
                filtered_dims=dict(fdims),
            )
        grp = lower_group(
            self.ctx, table, ask.job, tg, ask.requests, ask.eval_obj.id
        )
        if group_lower_static_cacheable(ask.job, tg):
            res.store_lowered(
                vers, ask.job, tg.name,
                (grp.ask, grp.feasible, grp.bias_static, grp.units_cap,
                 grp.filtered_dims),
            )
        return grp

    def _solve_host(self, asks: list[GroupAsk]) -> SolveOutcome:
        """Small-batch fast path (VERDICT r3 #3): below the threshold the
        device round-trip dominates any kernel win, so the asks run
        through the host GenericStack — the exact iterator chain the host
        oracle uses (reference stack.go:43) — with placements appended to
        each ask's plan as they land, so distinct/property/capacity
        checks see earlier placements exactly as generic.py's loop does
        (computePlacements, generic_sched.go:472)."""
        from ..stack import GenericStack
        from ..util import annotate_previous_alloc

        out = SolveOutcome()
        asks = sorted(asks, key=lambda a: -a.job.priority)
        # Cross-eval accounting: every eval's stack must see every OTHER
        # plan in this batch (via ctx.extra_plans) or two evals would
        # double-book one node's capacity/ports — the dense path
        # coordinates through its shared lowered table instead.
        batch_plans: list = list(self._partition_plans)
        seen_plans: set[int] = {id(p) for p in batch_plans}
        for ask in asks:
            if ask.plan is not None and id(ask.plan) not in seen_plans:
                seen_plans.add(id(ask.plan))
                batch_plans.append(ask.plan)
        dc_cache: dict[tuple, tuple] = {}
        stacks: dict[tuple, GenericStack] = {}
        for ask in asks:
            tg = ask.job.lookup_task_group(ask.tg_name)
            if tg is None or not ask.requests:
                continue
            key = tuple(ask.job.datacenters)
            cached = dc_cache.get(key)
            if cached is None:
                cached = ready_nodes_in_dcs(self.state, ask.job.datacenters)
                dc_cache[key] = cached
            nodes, dc_counts = cached
            if not nodes:
                self._fail_all(out, ask, dc_counts)
                continue
            # keyed by version too: one eval can carry asks for two job
            # versions (canary-state downgrades), each needing its own
            # job-level constraint set
            skey = (ask.eval_obj.id, ask.job.id, ask.job.version)
            stack = stacks.get(skey)
            if stack is None:
                ctx = EvalContext(
                    self.state,
                    ask.plan,
                    logger,
                    self.config,
                    extra_plans=[p for p in batch_plans if p is not ask.plan],
                )
                stack = GenericStack(ask.eval_obj.type == "batch", ctx)
                stack.set_nodes(nodes)
                stack.set_job(ask.job)
                stacks[skey] = stack
            ctx = stack.ctx
            placements = out.placements.setdefault(ask.eval_obj.id, [])
            preemptions = out.preemptions.setdefault(ask.eval_obj.id, [])
            preempt_ok = self.config.preemption_enabled(ask.job.type)
            sticky = tg.ephemeral_disk.sticky
            for req in ask.requests:
                penalty = {req.penalty_node} if req.penalty_node else None
                metric = AllocMetric(nodes_available=dict(dc_counts))
                start = now_ns()
                option = None
                prev = req.previous_alloc
                if sticky and prev is not None and prev.node_id:
                    # sticky disk: try the previous node first (reference
                    # computePlacements -> SelectOptions.PreferredNodes);
                    # a tainted/drained previous node is never preferred
                    prev_node = self.state.node_by_id(prev.node_id)
                    if prev_node is not None and prev_node.ready():
                        option = stack.select(
                            tg, penalty_nodes=penalty, metrics=metric,
                            selected_nodes=[prev_node],
                        )
                if option is None:
                    option = stack.select(
                        tg, penalty_nodes=penalty, metrics=metric
                    )
                if option is None and preempt_ok:
                    option = stack.select(
                        tg, penalty_nodes=penalty, metrics=metric, evict=True
                    )
                metric.allocation_time_ns = now_ns() - start
                metric.nodes_evaluated = ctx.metrics_nodes_evaluated
                if option is None:
                    existing = out.failures.get(ask.eval_obj.id, {}).get(
                        ask.tg_name
                    )
                    if existing is not None:
                        existing.coalesced_failures += 1
                    else:
                        out.failures.setdefault(ask.eval_obj.id, {})[
                            ask.tg_name
                        ] = metric
                    continue
                alloc = Allocation(
                    id=generate_uuid(),
                    namespace=ask.eval_obj.namespace,
                    eval_id=ask.eval_obj.id,
                    name=req.name,
                    node_id=option.node.id,
                    node_name=option.node.name,
                    job_id=ask.job.id,
                    job=ask.job,
                    task_group=tg.name,
                    resources=option.alloc_resources,
                    metrics=metric,
                    desired_status="run",
                    client_status="pending",
                )
                if req.canary:
                    alloc.deployment_status = AllocDeploymentStatus(canary=True)
                if option.preempted_allocs:
                    alloc.preempted_allocations = [
                        p.id for p in option.preempted_allocs
                    ]
                    for p in option.preempted_allocs:
                        ask.plan.append_preempted_alloc(p, alloc.id)
                        preemptions.append((p, alloc.id))
                annotate_previous_alloc(alloc, req)
                ask.plan.append_fresh_alloc(alloc, ask.job)
                out.pre_appended.add(alloc.id)
                placements.append(alloc)
        return out

    def _tier_limit(self, table, grp: LoweredGroup) -> int:
        """How many of the node table's (ascending) priority tiers this
        group may preempt: tiers more than PRIORITY_DELTA below the
        job's priority, when the operator enabled preemption for the
        job's scheduler type."""
        if not self.config.preemption_enabled(grp.job.type):
            return 0
        k = 0
        for p in table.tier_prios:
            if grp.priority - p >= PRIORITY_DELTA:
                k += 1
            else:
                break  # ascending order: no later tier qualifies
        return k

    def _lower_small(self, table, groups: list[LoweredGroup]):
        """The per-batch small tensors shared by both kernel paths:
        (np_, gp, cap [np_,3], used-zeros [np_,3], asks [gp,3], counts [gp])."""
        n, g = table.n, len(groups)
        np_, gp = self._pad_n(n), pad_g(g)
        cap = np.zeros((np_, 3), dtype=np.int32)
        used = np.zeros((np_, 3), dtype=np.int32)
        cap[:n] = np.clip(table.cap, 0, 2**31 - 1)
        asks_arr = np.zeros((gp, 3), dtype=np.int32)
        counts = np.zeros(gp, dtype=np.int32)
        for i, grp in enumerate(groups):
            asks_arr[i] = grp.ask
            counts[i] = grp.count
        return np_, gp, cap, used, asks_arr, counts

    @staticmethod
    def _dense_group_rows(n: int, np_: int, gp: int,
                          groups: list[LoweredGroup]):
        """Densify per-group feasibility/bias/unit-cap rows to the
        padded [gp, np_] bucket (shared by the preempt / custom-solve_fn
        lowering and the mesh compact dispatch)."""
        feas = np.zeros((gp, np_), dtype=bool)
        bias = np.zeros((gp, np_), dtype=np.float32)
        ucap = np.zeros((gp, np_), dtype=np.int32)
        for i, grp in enumerate(groups):
            feas[i, :n] = grp.feasible
            bias[i, :n] = grp.bias
            ucap[i, :n] = np.clip(grp.units_cap, 0, 2**31 - 1)
        return feas, bias, ucap

    def _lower_arrays(self, table, groups: list[LoweredGroup]):
        """Pad + stack the groups' tensors to the jit bucket shapes
        (dense [G, N] form, used by the preempt / custom-solve_fn path)."""
        n = table.n
        np_, gp, cap, used, asks_arr, counts = self._lower_small(table, groups)
        feas, bias, ucap = self._dense_group_rows(n, np_, gp, groups)
        return cap, used, asks_arr, counts, feas, bias, ucap

    @staticmethod
    def _dedupe_rows(
        arrays: list[np.ndarray], gp: int, np_: int, dtype
    ) -> tuple[np.ndarray, np.ndarray]:
        """Unique row table + per-group index for host->device compression.

        Groups lowered from one job share bias/ucap array OBJECTS (spread
        splits keep the parent's references) and unconstrained jobs have
        value-identical rows, so dedupe is first by identity then by
        content. Row count pads to a multiple of 8 for jit-shape stability.
        """
        by_id: dict[int, int] = {}
        by_content: dict[bytes, int] = {}
        rows: list[np.ndarray] = []
        idx = np.zeros(gp, dtype=np.int32)
        for i, arr in enumerate(arrays):
            j = by_id.get(id(arr))
            if j is None:
                a = np.asarray(arr, dtype=dtype)
                key = a.tobytes()
                j = by_content.get(key)
                if j is None:
                    j = len(rows)
                    rows.append(a)
                    by_content[key] = j
                by_id[id(arr)] = j
            idx[i] = j
        up = max(8, -(-len(rows) // 8) * 8)
        out = np.zeros((up, np_), dtype=dtype)
        for j, a in enumerate(rows):
            out[j, : a.shape[0]] = a
        return out, idx

    def _readback_bound(self, cap, used, groups: list[LoweredGroup],
                        n: int) -> int:
        """Bound any group's receiving node set. Guards the compact
        readback width ([G, maxC] vs the dense [G, N] transfer) and
        sizes the sharded solver's top-k (kernels._topk_fill stays
        exact because free only shrinks as groups place).

        Two regimes: normally the free-capacity refinement — a group
        can never place more instances than sum over nodes of
        free // ask. When this solve CONSUMED a chain, the kernel's
        usage tensor is the in-flight parent's view, which can hold
        MORE free capacity than the committed `used` here whenever the
        parent's plan vacated stops — a host-derived refinement could
        then under-bound the device's receiving set and silently
        truncate placements, so the bound falls back to the groups'
        raw counts (always sufficient: a group never receives on more
        than `count` nodes)."""
        if self.chain_accepted:
            return max(int(grp.count) for grp in groups)
        free = np.maximum(cap[:n].astype(np.int64) - used[:n], 0)
        units_by_ask: dict[bytes, np.ndarray] = {}
        placeable_cap = 0
        for grp in groups:
            ask = np.asarray(grp.ask, dtype=np.int64)
            key = ask.tobytes()
            per_node = units_by_ask.get(key)
            if per_node is None:
                per_res = np.where(
                    ask[None, :] > 0,
                    free // np.maximum(ask[None, :], 1),
                    np.int64(1 << 30),
                )
                per_node = units_by_ask[key] = per_res.min(axis=1)
            count = int(grp.count)
            placeable = min(count, int(np.minimum(per_node, count).sum()))
            if placeable > placeable_cap:
                placeable_cap = placeable
        return placeable_cap

    def _run_compact(
        self, table, groups: list[LoweredGroup], used_n, dev_state=None
    ):
        """Synchronous form: async dispatch + finish in one call (the
        spread-relaxation retry and direct callers use this)."""
        return self._run_compact_finish(
            self._run_compact_async(table, groups, used_n, dev_state)
        )

    def _run_compact_async(
        self, table, groups: list[LoweredGroup], used_n, dev_state=None
    ):
        """Default kernel with deduped/bit-packed uploads and device-side
        compaction, DISPATCH HALF: lowers, uploads, and queues the kernel
        without blocking. Returns a pending tuple for
        _run_compact_finish, which blocks, reads back, and returns
        (inst_node [G, maxC], over [N] bool, used' device array).

        dev_state — optional (cap_dev, used_dev) resident device tensors
        at this table's padded shape; when given, the [N, 3] host arrays
        are used only for the readback-width bound and the upload ships
        just the per-batch group tensors. Phase timings land in the
        telemetry registry (nomad.tpu.{host_prep,device,readback}_seconds)
        so the bench can publish the device/transfer/host split.
        """
        from ... import metrics

        t_prep0 = now_ns()
        n, g = table.n, len(groups)
        np_, gp, cap, used, asks_arr, counts = self._lower_small(table, groups)
        used[:n] = used_n[:n]
        if self.mesh is not None:
            pending = self._dispatch_mesh_compact(
                table, groups, np_, gp, cap, used, asks_arr, counts,
                dev_state,
            )
            prep_ns = now_ns() - t_prep0
            metrics.time_ns("nomad.tpu.host_prep_seconds", prep_ns)
            trace.stage("host_prep", prep_ns)
            return pending
        feas_rows, feas_idx = self._dedupe_rows(
            [grp.feasible for grp in groups], gp, np_, np.bool_
        )
        feas_packed = np.packbits(feas_rows, axis=1)
        bias_rows, bias_idx = self._dedupe_rows(
            [grp.bias for grp in groups], gp, np_, np.float32
        )
        # Dedupe on the ORIGINAL arrays (spread sub-groups share the
        # parent's reference — the identity fast path), then shrink the few
        # unique rows. Caps beyond a group's count are equivalent to it
        # (the kernel clips units to count), so i16 loses nothing as long
        # as every count fits; gigantic single-group batches keep i32.
        ucap_rows, ucap_idx = self._dedupe_rows(
            [grp.units_cap for grp in groups], gp, np_, np.int64
        )
        max_count = max(int(grp.count) for grp in groups)
        if max_count < 2**15:
            ucap_rows = np.clip(ucap_rows, 0, 2**15 - 1).astype(np.int16)
        else:
            ucap_rows = np.clip(ucap_rows, 0, 2**31 - 1).astype(np.int32)
        maxc = pad_c(max(1, self._readback_bound(cap, used, groups, n)))
        # resident/chained device tensors replace the cap and/or used
        # upload when their padded shape matches this table's bucket
        cap_in, used_in = cap, used
        if dev_state is not None:
            dcap, dused = dev_state
            if dcap is not None and dcap.shape == (np_, 3):
                cap_in = dcap
            if dused is not None and dused.shape == (np_, 3):
                used_in = dused
        solverobs.record_batch(n, g, np_, gp)
        # host->device bytes: exactly the numpy arguments this dispatch
        # uploads (a device-resident cap/used input ships nothing)
        solverobs.record_transfer("h2d", sum(
            a.nbytes
            for a in (
                cap_in, used_in, asks_arr, counts, feas_packed, feas_idx,
                bias_rows, bias_idx, ucap_rows, ucap_idx,
            )
            if isinstance(a, np.ndarray)
        ))
        sig = (
            "solve_placement_compact", np_, gp, feas_packed.shape[0],
            bias_rows.shape[0], ucap_rows.shape[0], str(ucap_rows.dtype),
            maxc,
        )
        inst, over, used_out = solverobs.timed_call(
            "solve_placement_compact", sig, solve_placement_compact,
            cap_in,
            used_in,
            asks_arr,
            counts,
            feas_packed,
            feas_idx,
            bias_rows,
            bias_idx,
            ucap_rows,
            ucap_idx,
            max_count=maxc,
        )
        prep_ns = now_ns() - t_prep0
        metrics.time_ns("nomad.tpu.host_prep_seconds", prep_ns)
        trace.stage("host_prep", prep_ns)
        return inst, over, used_out, g, n, time.perf_counter()

    def _dispatch_mesh_compact(
        self, table, groups, np_, gp, cap, used, asks_arr, counts, dev_state
    ):
        """Node-sharded dispatch with the compact readback contract:
        the mesh's top-k compact kernel returns the same
        (inst [G, maxC], over [N], used') as solve_placement_compact, so
        everything downstream (_run_compact_finish, _materialize_compact,
        the SoA fast-mint, the chain) is shared with the single-chip
        path. Group tensors upload dense (the node axis is what shards;
        the input-dedupe trick stays single-chip-only — with resident
        cap/used the group tensors ARE the whole upload); per-shard
        occupancy and the modeled all-gather bytes land on the ledger.
        """
        mesh = self.mesh
        n, g = table.n, len(groups)
        feas, bias, ucap = self._dense_group_rows(n, np_, gp, groups)
        maxc = pad_c(max(1, self._readback_bound(cap, used, groups, n)))
        fn, k = mesh.solver(maxc, compact=True)
        cap_in, used_in = cap, used
        if dev_state is not None:
            dcap, dused = dev_state
            if dcap is not None and dcap.shape == (np_, 3):
                cap_in = dcap
            if dused is not None and dused.shape == (np_, 3):
                used_in = dused
        solverobs.record_batch(n, g, np_, gp)
        # host->device bytes: only what this dispatch actually uploads
        # (resident/chained device inputs ship nothing)
        solverobs.record_transfer("h2d", sum(
            a.nbytes
            for a in (cap_in, used_in, asks_arr, counts, feas, bias, ucap)
            if isinstance(a, np.ndarray)
        ))
        solverobs.record_shards(mesh.n_dev, mesh.shard_occupancy(n, np_))
        solverobs.record_transfer(
            # gp, not g: the kernel's scan runs over the PADDED group
            # axis, and each step all-gathers its candidates (matching
            # the preempt path's accounting below)
            "allgather", mesh.allgather_bytes(gp, np_, k)
        )
        kname = getattr(fn, "__name__", "sharded_solver_compact")
        inst, over, used_out = solverobs.timed_call(
            kname, (kname, np_, gp, k), fn,
            cap_in, used_in, asks_arr, counts, feas, bias, ucap,
        )
        return inst, over, used_out, g, n, time.perf_counter()

    def _run_compact_finish(self, pending):
        """Block on the dispatched compact kernel and read back."""
        import jax

        from ... import metrics

        inst, over, used_out, g, n, t_disp = pending
        # device compute vs readback split: block on the async dispatch
        # first, then transfer — so the bench's breakdown distinguishes
        # chip time from the (tunnel) link time
        t_dev0 = now_ns()
        jax.block_until_ready(used_out)
        self._inject_rtt(t_disp)
        dev_ns = now_ns() - t_dev0
        metrics.time_ns("nomad.tpu.device_seconds", dev_ns)
        trace.stage("device.wait", dev_ns)
        t_rb0 = now_ns()
        # slice on-device before the host transfer: the pad region is
        # noise and the tunnel to the chip is the slow link
        result = np.asarray(inst[:g]), np.asarray(over[:n]), used_out
        rb_ns = now_ns() - t_rb0
        metrics.time_ns("nomad.tpu.readback_seconds", rb_ns)
        trace.stage("readback", rb_ns)
        # device->host bytes actually moved (used_out stays on device
        # for the chain); plus a post-solve device-memory census
        solverobs.record_transfer(
            "d2h", result[0].nbytes + result[1].nbytes,
            dur_ns=rb_ns, span=True,
        )
        solverobs.sample_device_memory()
        return result

    def _run_kernel(
        self,
        table,
        groups: list[LoweredGroup],
        used_n: np.ndarray,
        tier_limit: Optional[np.ndarray] = None,
        use_preempt: bool = False,
    ):
        """Synchronous form: async dispatch + finish in one call."""
        return self._run_kernel_finish(
            self._run_kernel_async(
                table, groups, used_n, tier_limit=tier_limit,
                use_preempt=use_preempt,
            )
        )

    def _run_kernel_async(
        self,
        table,
        groups: list[LoweredGroup],
        used_n: np.ndarray,
        tier_limit: Optional[np.ndarray] = None,
        use_preempt: bool = False,
    ):
        n, g = table.n, len(groups)
        np_, gp = self._pad_n(n), pad_g(g)
        cap, used, asks_arr, counts, feas, bias, ucap = self._lower_arrays(
            table, groups
        )
        used[:n] = used_n[:n]
        solverobs.record_batch(n, g, np_, gp)
        if self.mesh is not None and use_preempt:
            # shard accounting for the preempt mesh dispatch (the
            # non-preempt mesh path rides _run_compact_async)
            solverobs.record_shards(
                self.mesh.n_dev, self.mesh.shard_occupancy(n, np_)
            )
            solverobs.record_transfer(
                "allgather",
                # two all-gather phases per preempt scan step
                2 * self.mesh.allgather_bytes(gp, np_, None),
            )
        solverobs.record_transfer("h2d", sum(
            a.nbytes for a in (cap, used, asks_arr, counts, feas, bias, ucap)
        ))
        if use_preempt:
            tl = np.zeros(gp, dtype=np.int32)
            tl[:g] = tier_limit[:g]
            tier_limit = tl
            t = len(table.tier_prios)
            # Pad the tier axis to a bucket (like pad_n/pad_g): the jit
            # kernel must not recompile every time the number of
            # distinct alloc priorities in the cluster changes.
            tp = max(4, -(-(t + 1) // 4) * 4)
            prefix = np.zeros((tp, np_, 3), dtype=np.int32)
            if t:
                cum = np.cumsum(
                    np.clip(table.tier_used, 0, 2**31 - 1), axis=0
                )
                prefix[1 : t + 1, :n] = cum.astype(np.int32)
                # padded tail repeats the full sum so any (unused)
                # out-of-range index still reads a valid prefix
                prefix[t + 1 :, :n] = cum[-1].astype(np.int32)
            solverobs.record_transfer("h2d", prefix.nbytes + tier_limit.nbytes)
            # factory-built preempt variants (mesh-sharded) ledger under
            # their own name so per-mesh recompiles are attributable
            kname = getattr(
                self.solve_preempt_fn, "__name__", "solve_placement_preempt"
            )
            assign, assign_evict, used_out = solverobs.timed_call(
                kname, (kname, np_, gp, tp), self.solve_preempt_fn,
                cap, used, prefix, asks_arr, counts, feas, bias, ucap,
                tier_limit,
            )
            return assign, assign_evict, used_out, g, n, time.perf_counter()
        kname = getattr(self.solve_fn, "__name__", "solve_placement")
        assign, used_out = solverobs.timed_call(
            kname, (kname, np_, gp), self.solve_fn,
            cap, used, asks_arr, counts, feas, bias, ucap
        )
        return assign, None, used_out, g, n, time.perf_counter()

    def _run_kernel_finish(self, pending):
        """Block on the dispatched dense kernel and read back. The
        on-device slice happens before the host transfer: the pad region
        is zeros and the tunnel to the chip is the slow link."""
        assign, assign_evict, used_out, g, n, t_disp = pending
        t_dev0 = now_ns()
        self._inject_rtt(t_disp)
        result = (
            np.asarray(assign[:g, :n]),
            None if assign_evict is None else np.asarray(assign_evict[:g, :n]),
            used_out,
        )
        # dense path: blocking transfer includes the device wait, so the
        # two land as one combined stage span
        rb_ns = now_ns() - t_dev0
        trace.stage("device.readback", rb_ns)
        solverobs.record_transfer(
            "d2h",
            result[0].nbytes
            + (result[1].nbytes if result[1] is not None else 0),
            dur_ns=rb_ns, span=True,
        )
        solverobs.sample_device_memory()
        return result

    def _inject_rtt(self, t_disp: float) -> None:
        """Simulated chip round-trip (docs/pipeline.md): results become
        available inject_device_latency_s AFTER DISPATCH, the way a real
        async device computes while the host works — NOT a fixed sleep at
        readback, which would model a device that only starts when asked
        for results and would serialize the simulated RTT behind the
        commit stage's own host work. Lets the worker's solve/commit
        overlap be proven on CPU fallback.

        The modeled device is a serially-busy queue: a dispatch that
        lands while an earlier batch's window is still open starts AFTER
        it (`_device_free_at` rides the shared SchedulerConfig, the one
        object that spans a worker's batches). Without this, two
        in-flight batches' windows overlapped and the model behaved like
        a second chip — overstating pipeline overlap and sharded
        scaling alike."""
        lat = self.config.inject_device_latency_s
        if lat > 0:
            start = max(
                getattr(self.config, "_device_free_at", 0.0), t_disp
            )
            ready = start + lat
            self.config._device_free_at = ready
            remain = ready - time.perf_counter()
            if remain > 0:
                time.sleep(remain)

    # ------------------------------------------------------------------

    def _split_for_spread(
        self, table, job: Job, tg, grp: LoweredGroup
    ) -> list[LoweredGroup]:
        """Spread stanzas become per-value sub-groups with quota counts.

        The waterfill scan is greedy per group, so a within-batch spread
        can't be expressed as a static score bias — instead the group is
        split: one sub-group per attribute value, count = that value's
        remaining desired share, feasibility ANDed with value membership.
        Leftover instances become an unrestricted remainder sub-group.
        (Multiple spread stanzas: the highest-weight one drives the split;
        the rest stay score bias.)
        """
        import dataclasses

        from .lower import _property_counts, _spread_desired

        spreads = list(tg.spreads) + [
            s
            for s in job.spreads
            if s.attribute not in {t.attribute for t in tg.spreads}
        ]
        if not spreads:
            return [grp]
        s = max(spreads, key=lambda x: x.weight)
        from .lower import request_names

        codes, values, exists = table.attr_codes(s.attribute)
        counts_v = _property_counts(self.ctx, table, job, s.attribute, tg.name)
        desired = _spread_desired(s, values, tg.count)
        quotas = np.maximum(0, desired - counts_v).astype(np.int64)
        # slicing (not list()-ing) keeps PlacementRun fills as runs —
        # the sub-groups' rows never materialize on the fast path
        reqs = grp.requests
        out: list[LoweredGroup] = []
        order = np.argsort(-(quotas / np.maximum(desired, 1)))
        for vi in order:
            if not len(reqs):
                break
            take = min(int(quotas[vi]), len(reqs))
            if take <= 0:
                continue
            sub_reqs, reqs = reqs[:take], reqs[take:]
            out.append(
                dataclasses.replace(
                    grp,
                    count=take,
                    feasible=grp.feasible & (codes == vi) & exists,
                    names=request_names(sub_reqs),
                    requests=sub_reqs,
                    restricted=True,
                )
            )
        if len(reqs):
            out.append(
                dataclasses.replace(
                    grp,
                    count=len(reqs),
                    names=request_names(reqs),
                    requests=reqs,
                )
            )
        return out

    @staticmethod
    def _node_id_col(table) -> list:
        """Node-id column for PlacementBatches, built once per table and
        shared by every batch of the solve (string references, no copies)."""
        col = getattr(table, "_node_id_col", None)
        if col is None:
            col = table._node_id_col = [n.id for n in table.nodes]
        return col

    @staticmethod
    def _node_name_col(table) -> list:
        col = getattr(table, "_node_name_col", None)
        if col is None:
            col = table._node_name_col = [n.name for n in table.nodes]
        return col

    def _materialize_compact(
        self,
        table,
        groups: list[LoweredGroup],
        inst: np.ndarray,
        over: np.ndarray,
        free_base: np.ndarray,
    ) -> dict[int, list]:
        """Mint Allocations from the compact per-instance node list.

        inst[gi] holds the node index of each placed instance of group gi
        (-1 padded past the placed total); `over` flags nodes where the
        device ledger detected capacity overflow. The integer kernel never
        overflows by construction, so `over` is a defensive invariant
        check (kernel regressions, bad `used` inputs): placements on
        flagged nodes are re-verified host-side with exact integer math
        against `free_base`, the node free vector at the start of this
        pass, instead of being committed blindly.

        Fast-mint groups (no network asks, no previous-alloc rewiring)
        share ONE AllocatedResources and ONE AllocMetric across all their
        instances: the state store's copy-on-write discipline — every
        writer copies an alloc before mutating — makes stored sub-object
        sharing safe, and it removes ~100k object constructions per c2m
        solve (VERDICT r2 weak #2).
        """
        out = self._outcome
        nodes = table.nodes
        n = table.n
        leftovers: dict[int, list] = {}
        over_set = (
            set(np.nonzero(over)[0].tolist()) if over.any() else None
        )
        over_free: dict[int, list[int]] = {}
        for gi, grp in enumerate(groups):
            eval_id = grp.key[0]
            placements = out.placements.setdefault(eval_id, [])
            row = inst[gi]
            placed = int((row != -1).sum())
            reqs = grp.requests
            placed = min(placed, len(reqs))
            row_placed = row[:placed]
            node_idx = None  # listified lazily — the SoA path never does
            unplaced: list = []
            tg = grp.tg
            a0, a1, a2 = (int(grp.ask[0]), int(grp.ask[1]), int(grp.ask[2]))

            def _check_over(ni: int) -> bool:
                """Exact replay on an overflow-flagged node; True = fits."""
                fr = over_free.get(ni)
                if fr is None:
                    fr = over_free[ni] = [int(c) for c in free_base[ni]]
                if fr[0] < a0 or fr[1] < a1 or fr[2] < a2:
                    return False
                fr[0] -= a0
                fr[1] -= a1
                fr[2] -= a2
                return True

            # A PlacementRun answers the per-request checks from its
            # shared proto: iterating the run here would mint ~10^5
            # request rows (dataclasses.replace each) per c2m solve —
            # the exact cost the run exists to avoid, and the single
            # hottest host site of the r10 profile when it regressed.
            run_proto = getattr(reqs, "proto", None)
            slow = (
                bool(tg.networks)
                or any(t.resources.networks for t in tg.tasks)
                or any(t.resources.devices for t in tg.tasks)
                # dedicated cores need per-placement id assignment
                or any(t.resources.cores > 0 for t in tg.tasks)
                # canaries carry a per-alloc deployment status
                or (
                    (run_proto.previous_alloc is not None or run_proto.canary)
                    if run_proto is not None
                    else any(
                        r.previous_alloc is not None or r.canary for r in reqs
                    )
                )
            )
            if slow:
                node_idx = row_placed.tolist()
                for i, ni in enumerate(node_idx):
                    req = reqs[i]
                    if over_set is not None and ni in over_set:
                        if not _check_over(ni):
                            unplaced.append(req)
                            continue
                    alloc = self._build_alloc(table, grp, nodes[ni], req)
                    if alloc is None:
                        unplaced.append(req)  # port assignment failed
                        continue
                    placements.append(alloc)
            else:
                # keyed by eval too: the broker serializes evals per job,
                # but solve_eval_batch is public API — two evals of one
                # job in a batch must not stamp each other's eval_id
                # (the intended reuse — spread sub-groups, the
                # relaxation retry — is all within one eval)
                tmpl_key = (eval_id, id(grp.job), tg.name)
                tmpl = self._mint_cache.get(tmpl_key)
                if tmpl is None:
                    shared_res = AllocatedResources(
                        tasks={
                            t.name: AllocatedTaskResources(
                                cpu=t.resources.cpu,
                                memory_mb=t.resources.memory_mb,
                            )
                            for t in tg.tasks
                        },
                        shared_disk_mb=tg.ephemeral_disk.size_mb,
                    )
                    tmpl = self._mint_cache[tmpl_key] = _MintTemplate(
                        Allocation(
                            namespace=grp.job.namespace,
                            eval_id=eval_id,
                            job_id=grp.job.id,
                            job=grp.job,
                            task_group=tg.name,
                            resources=shared_res,
                            metrics=group_alloc_metric(grp, n),
                        )
                    )
                uuids = generate_uuids(placed) if placed else []
                group_cpu = sum(t.resources.cpu for t in tg.tasks)
                ap = placements.append
                mint = tmpl.mint
                if over_set is None and not self._batch_has_cores:
                    if self.config.soa_placements and placed:
                        # the array-native case: the kernel's node-index
                        # readback BECOMES the placement column — no
                        # per-row Python objects exist until an API/
                        # client boundary materializes them lazily
                        # (structs/placement_batch.py)
                        proto = tmpl.proto
                        batch = PlacementBatch(
                            namespace=proto.namespace,
                            eval_id=eval_id,
                            job_id=proto.job_id,
                            job=proto.job,
                            task_group=proto.task_group,
                            resources=proto.resources,
                            metrics=proto.metrics,
                            ids=uuids,
                            names=(
                                grp.names[:placed]
                                if len(grp.names) == len(reqs)
                                else [r.name for r in reqs[:placed]]
                            ),
                            node_idx_raw=np.ascontiguousarray(
                                row_placed, dtype=np.int32
                            ).tobytes(),
                            node_ids=self._node_id_col(table),
                            node_names=self._node_name_col(table),
                        )
                        out.batch_placements.setdefault(
                            eval_id, []
                        ).append(batch)
                    else:
                        # the eager bulk case (the SoA comparator): one
                        # tight mint loop, ~100k iterations/solve
                        node_idx = row_placed.tolist()
                        for uid, ni, req in zip(uuids, node_idx, reqs):
                            ap(mint(uid, req.name, nodes[ni]))
                    node_idx = ()
                elif node_idx is None:
                    node_idx = row_placed.tolist()
                for i, ni in enumerate(node_idx):
                    if over_set is not None and ni in over_set:
                        if not _check_over(ni):
                            unplaced.append(reqs[i])
                            continue
                    node = nodes[ni]
                    if self._batch_has_cores:
                        # the dense solve can't see the derived-MHz
                        # excess of cores groups materialized earlier
                        # in this batch — the shared ledger can
                        if group_cpu > self._remaining_cpu(node):
                            unplaced.append(reqs[i])
                            continue
                        self._batch_cpu[node.id] = (
                            self._batch_cpu.get(node.id, 0) + group_cpu
                        )
                    ap(mint(uuids[i], reqs[i].name, node))
            unplaced.extend(reqs[placed:])
            if unplaced:
                leftovers[gi] = unplaced
        return leftovers

    def _materialize(
        self,
        table,
        groups: list[LoweredGroup],
        assign: np.ndarray,
        assign_evict: Optional[np.ndarray] = None,
    ) -> dict[int, list]:
        """Turn [G, N] counts into Allocations; verify + repair per node.

        Returns leftover (unplaced) requests per group index; the caller
        aggregates failures after all passes. Host-side exact capacity
        verification replays the solver's placements with integer math and
        drops overflow (the kernel is integer too, so this only fires when
        two passes race the same capacity).

        assign_evict marks placements the kernel made on PREEMPTIBLE
        capacity: for those, exact victim allocs are picked here
        (lowest priority tier first, then closest resource distance —
        the host Preemptor's rules) and reported on outcome.preemptions.
        """
        n = table.n
        free = self._free
        out = self._outcome
        leftovers: dict[int, list] = {}
        for gi, grp in enumerate(groups):
            eval_id = grp.key[0]
            placements = out.placements.setdefault(eval_id, [])
            req_iter = iter(grp.requests)
            unplaced: list = []
            a0, a1, a2 = (int(grp.ask[0]), int(grp.ask[1]), int(grp.ask[2]))
            node_indices = np.nonzero(assign[gi, :n])[0]
            for ni in node_indices:
                node = table.nodes[ni]
                take = int(assign[gi, ni])
                evict_budget = (
                    int(assign_evict[gi, ni]) if assign_evict is not None else 0
                )
                row = free[ni]
                for _ in range(take):
                    req = next(req_iter, None)
                    if req is None:
                        break
                    victims: list = []
                    if row[0] < a0 or row[1] < a1 or row[2] < a2:
                        if evict_budget > 0:
                            victims = self._pick_victims(table, ni, grp) or []
                        if not victims:
                            unplaced.append(req)  # out of exact capacity
                            continue
                    alloc = self._build_alloc(table, grp, node, req)
                    if alloc is None:
                        unplaced.append(req)  # port assignment failed
                        continue
                    if victims:
                        evict_budget -= 1
                        alloc.preempted_allocations = [v.id for v in victims]
                        pre = out.preemptions.setdefault(eval_id, [])
                        for v in victims:
                            self._victimized.add(v.id)
                            r = v.comparable_resources()
                            row[0] += r.cpu
                            row[1] += r.memory_mb
                            row[2] += r.disk_mb
                            pre.append((v, alloc.id))
                    row[0] -= a0
                    row[1] -= a1
                    row[2] -= a2
                    placements.append(alloc)
            unplaced.extend(req_iter)  # instances the kernel never placed
            if unplaced:
                leftovers[gi] = unplaced
        return leftovers

    def _pick_victims(self, table, ni: int, grp: LoweredGroup):
        """Exact victim selection for one instance on one node: free
        enough for grp.ask from preemptible allocs, lowest priority tier
        first, closest resource distance within a tier (the Preemptor's
        scoring, reference preemption.go:198)."""
        from ...structs import Resources
        from ..preemption import PRIORITY_DELTA, basic_resource_distance

        row = self._free[ni]
        shortage = [max(int(grp.ask[i]) - row[i], 0) for i in range(3)]
        need = Resources(
            cpu=shortage[0], memory_mb=shortage[1], disk_mb=shortage[2]
        )
        cands = []
        for a in table._allocs_by_node(table.nodes[ni].id):
            if a.id in self._victimized:
                continue
            if (
                a.job_id == grp.job.id
                and a.namespace == grp.job.namespace
            ):
                continue
            prio = a.job.priority if a.job is not None else 50
            if grp.priority - prio < PRIORITY_DELTA:
                continue
            cands.append((prio, a))
        if not cands:
            return None
        cands.sort(
            key=lambda pa: (
                pa[0],
                basic_resource_distance(need, pa[1].comparable_resources()),
            )
        )
        freed = [0, 0, 0]
        picks = []
        for _, a in cands:
            r = a.comparable_resources()
            freed[0] += r.cpu
            freed[1] += r.memory_mb
            freed[2] += r.disk_mb
            picks.append(a)
            if (
                freed[0] >= shortage[0]
                and freed[1] >= shortage[1]
                and freed[2] >= shortage[2]
            ):
                return picks
        return None

    def _live_allocs(self, node_id: str):
        """Non-terminal allocs minus this batch's plan-stops — the same
        vacated view the dense table packs against."""
        return [
            a
            for a in self.state.allocs_by_node_terminal(node_id, False)
            if a.id not in self._stopped_ids
        ]

    def _remaining_cpu(self, node) -> int:
        """Node MHz still grantable: committed-state baseline minus
        every placement this batch already made (either path)."""
        base = self._state_cpu.get(node.id)
        if base is None:
            base = node.available_resources().cpu - sum(
                a.comparable_resources().cpu
                for a in self._live_allocs(node.id)
            )
            self._state_cpu[node.id] = base
        return base - self._batch_cpu.get(node.id, 0)

    def _build_alloc(
        self, table, grp: LoweredGroup, node, req: PlacementRequest
    ) -> Optional[Allocation]:
        tg = grp.tg
        net_idx = self._net_cache.get(node.id)
        if net_idx is None:
            net_idx = NetworkIndex()
            net_idx.set_node(node)
            net_idx.add_allocs(self._live_allocs(node.id))
            self._net_cache[node.id] = net_idx

        # Device instance assignment (mirrors rank.py's DeviceAllocator
        # use on the host path): instances already claimed by live allocs
        # AND by this batch's placements on the node are excluded.
        dev_alloc = None
        if any(t.resources.devices for t in tg.tasks):
            from ..device import DeviceAllocator

            dev_alloc = self._dev_cache.get(node.id)
            if dev_alloc is None:
                dev_alloc = DeviceAllocator(self.ctx, node)
                dev_alloc.add_allocs(self._live_allocs(node.id))
                self._dev_cache[node.id] = dev_alloc

        remaining_cpu = (
            self._remaining_cpu(node) if self._batch_has_cores else 0
        )

        # Dedicated-core id pool per node (mirrors rank.py): the dense
        # solve reserved core COUNTS (the 4th resource column); ids are
        # assigned here on materialization, shared across the batch via
        # the cache so two placements never collide.
        free_cores = None
        mhz_per_core = 0
        if any(t.resources.cores > 0 for t in tg.tasks):
            from ...structs.funcs import node_core_pool

            cached = self._core_cache.get(node.id)
            if cached is None:
                cached = node_core_pool(node, self._live_allocs(node.id))
                self._core_cache[node.id] = cached
            free_cores, mhz_per_core = cached

        if self._batch_has_cores:
            # the dense solve screened DECLARED MHz asks; cores grants
            # are DERIVED (cores x MHz/core) and may exceed them, so in
            # a cores-bearing batch EVERY slow-path group re-screens
            # against the shared ledger (rank.py does the same superset
            # re-check on the host path). Before any reservation, so no
            # rollback needed.
            group_cpu = sum(
                t.resources.cores * mhz_per_core
                if t.resources.cores > 0
                else t.resources.cpu
                for t in tg.tasks
            )
            if group_cpu > remaining_cpu:
                return None

        # Track reservations for rollback: the shared per-node caches
        # outlive this call, so a half-built placement that fails a later
        # ask must return everything it grabbed or subsequent groups see
        # phantom usage.
        granted_offers: list = []
        granted_devs: list = []

        granted_cores: list = []
        granted_cpu = 0

        def _rollback():
            for offer in granted_offers:
                net_idx.remove_reserved(offer)
            if dev_alloc is not None:
                for got in granted_devs:
                    dev_alloc.free[got["id"]].update(got["device_ids"])
            if free_cores is not None:
                free_cores.extend(granted_cores)

        task_resources: dict[str, AllocatedTaskResources] = {}
        for task in tg.tasks:
            tr = AllocatedTaskResources(
                cpu=task.resources.cpu, memory_mb=task.resources.memory_mb
            )
            if task.resources.cores > 0:
                if free_cores is None or len(free_cores) < task.resources.cores:
                    _rollback()
                    return None
                tr.reserved_cores = free_cores[: task.resources.cores]
                del free_cores[: task.resources.cores]
                granted_cores.extend(tr.reserved_cores)
                tr.cpu = task.resources.cores * mhz_per_core
            for ask in task.resources.networks:
                offer = net_idx.assign_network(ask)
                if offer is None:
                    _rollback()
                    return None
                net_idx.add_reserved(offer)
                granted_offers.append(offer)
                tr.networks.append(offer)
            for dev_ask in task.resources.devices:
                # assign() removes the picked ids from the free set, so
                # the shared per-node allocator naturally serializes the
                # batch's placements
                got = dev_alloc.assign(dev_ask) if dev_alloc else None
                if got is None:
                    _rollback()
                    return None  # instances exhausted on this node
                granted_devs.append(got)
                tr.devices.append(got)
            granted_cpu += tr.cpu
            task_resources[task.name] = tr
        shared_networks = []
        for ask in tg.networks:
            offer = net_idx.assign_network(ask)
            if offer is None:
                _rollback()
                return None
            net_idx.add_reserved(offer)
            granted_offers.append(offer)
            shared_networks.append(offer)

        if self._batch_has_cores:
            self._batch_cpu[node.id] = (
                self._batch_cpu.get(node.id, 0) + granted_cpu
            )
        alloc = Allocation(
            id=generate_uuid(),
            namespace=grp.job.namespace,
            eval_id=grp.key[0],
            name=req.name,
            node_id=node.id,
            node_name=node.name,
            job_id=grp.job.id,
            job=grp.job,
            task_group=tg.name,
            resources=AllocatedResources(
                tasks=task_resources,
                shared_disk_mb=tg.ephemeral_disk.size_mb,
                shared_networks=shared_networks,
            ),
            metrics=group_alloc_metric(grp, table.n),
        )
        if req.canary:
            alloc.deployment_status = AllocDeploymentStatus(canary=True)
        from ..util import annotate_previous_alloc

        annotate_previous_alloc(alloc, req)
        return alloc

    def _fail_all(self, out: SolveOutcome, ask: GroupAsk, dc_counts) -> None:
        metric = AllocMetric(nodes_available=dict(dc_counts))
        metric.coalesced_failures = max(0, len(ask.requests) - 1)
        out.failures.setdefault(ask.eval_obj.id, {})[ask.tg_name] = metric
