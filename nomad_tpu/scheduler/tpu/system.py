"""TPU-backend system/sysbatch scheduler.

Reference seam: scheduler/system_sched.go — same contract as the host
SystemScheduler, but the per-node iterator walk (select → checkers →
binpack per node) collapses into ONE vectorized pass per task group:

  1. lower the group's feasibility mask over the candidate nodes with the
     SAME interning machinery the batch solver uses (lower.py — identical
     semantics to the host checkers by construction);
  2. capacity fit is an elementwise ask <= cap - used over the node table;
  3. feasible+fitting nodes fast-mint allocations (shared resources /
     metrics sub-objects, bulk uuids — the solver's discipline).

Nodes that fail the vectorized pass but might succeed via preemption (or
need per-node port selection) fall back to the host's per-node walk, so
semantics match the host scheduler exactly where it matters and the O(N)
Python loop only runs for the exceptional nodes.

This closes the round-2 caveat that system/sysbatch evals always ran the
host path under the TPU backend (drain-churn loads were half host-bound).
"""

from __future__ import annotations

import numpy as np

from ...structs import (
    AllocMetric,
    AllocatedResources,
    AllocatedTaskResources,
    Allocation,
    generate_uuids,
)
from ..context import EvalContext
from ..system import SystemScheduler
from .lower import build_node_table, lower_group


class TPUSystemScheduler(SystemScheduler):
    scheduler_type = "system"

    def _place_group(self, job, eval_obj, stack, tg, nodes, queued) -> None:
        # Per-node paths the vectorized mint can't cover: dynamic port
        # selection, exact device instance picks, and distinct_property
        # budgets (a SHARED per-value cap — the one-shot mask can't stop
        # the Nth node of a value once N-1 placed in the same pass).
        from ...structs.structs import CONSTRAINT_DISTINCT_PROPERTY

        all_constraints = list(job.constraints) + list(tg.constraints)
        for t in tg.tasks:
            all_constraints.extend(t.constraints)
        needs_per_node = (
            bool(tg.networks)
            or any(t.resources.networks for t in tg.tasks)
            or any(t.resources.devices for t in tg.tasks)
            # dedicated cores need per-node id grants (disjointness)
            or any(t.resources.cores > 0 for t in tg.tasks)
            or any(
                c.operand == CONSTRAINT_DISTINCT_PROPERTY
                for c in all_constraints
            )
        )
        if needs_per_node or len(nodes) < 8:
            # tiny batches aren't worth the lowering overhead
            return super()._place_group(job, eval_obj, stack, tg, nodes, queued)

        ctx = EvalContext(self.state, self.plan, self.logger, self.config)
        stopped: set[str] = set()
        for allocs_ in self.plan.node_update.values():
            stopped.update(a.id for a in allocs_)

        def live_allocs(nid: str):
            # Mirrors ctx.proposed_allocs: committed state MINUS this
            # plan's stops PLUS this plan's placements — without the plan
            # adds, a second task group of the same eval would overcommit
            # nodes the first group already filled and the applier would
            # reject them wholesale.
            out = [
                a
                for a in self.state.allocs_by_node_terminal(nid, False)
                if a.id not in stopped
            ]
            out.extend(self.plan.node_allocation.get(nid, []))
            return out

        table = build_node_table(list(nodes), live_allocs)
        from types import SimpleNamespace

        # one instance per node; lower_group only reads .name off these
        reqs = [
            SimpleNamespace(name=f"{job.id}.{tg.name}[0]") for _ in nodes
        ]
        grp = lower_group(ctx, table, job, tg, reqs, eval_obj.id)
        ask = np.asarray(grp.ask, dtype=np.int64)
        free = table.cap - table.used
        fits = np.all(free >= ask[None, :], axis=1)
        # units_cap: distinct_hosts folds to a 0/1 per-node budget here
        # (distinct_property already routed to the host walk above).
        ok = grp.feasible & fits & (grp.units_cap >= 1)

        ok_idx = np.nonzero(ok)[0].tolist()
        shared_metric = AllocMetric(
            nodes_available=dict(self._dc_counts),
            nodes_evaluated=len(nodes),
        )
        shared_res = AllocatedResources(
            tasks={
                t.name: AllocatedTaskResources(
                    cpu=t.resources.cpu, memory_mb=t.resources.memory_mb
                )
                for t in tg.tasks
            },
            shared_disk_mb=tg.ephemeral_disk.size_mb,
        )
        uuids = generate_uuids(len(ok_idx)) if ok_idx else []
        for u, i in zip(uuids, ok_idx):
            node = table.nodes[i]
            self.plan.append_fresh_alloc(
                Allocation(
                    id=u,
                    namespace=eval_obj.namespace,
                    eval_id=eval_obj.id,
                    name=f"{job.id}.{tg.name}[0]",
                    node_id=node.id,
                    node_name=node.name,
                    job_id=job.id,
                    task_group=tg.name,
                    resources=shared_res,
                    metrics=shared_metric,
                ),
                job,
            )
        # Failed nodes retry the host walk: preemption may evict room,
        # and the per-node metrics land in failed_tg_allocs as usual.
        for i in np.nonzero(~ok)[0].tolist():
            self._place_one(job, eval_obj, stack, tg, table.nodes[i], queued)


class TPUSysbatchScheduler(TPUSystemScheduler):
    scheduler_type = "sysbatch"
