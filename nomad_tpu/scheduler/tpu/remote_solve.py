"""Member side of the solver-pool tier (docs/solver-pool.md).

A pool member is any server (follower or dedicated ``solver``-role
agent) that hosts a warm mesh + ResidentClusterState replica and solves
lowered eval batches the leader streams out over ``SolverPool.Solve``.
The member never touches raft: plan-apply authority stays with the
leader, whose existing plan verification catches anything a slightly
stale replica solved optimistically — the same optimistic-concurrency
bet the plan queue already makes for local solves.

What makes the tier worth having is that THIS state — the compiled
kernels, the device-resident cap/used tensors, the warm eval-context
caches — lives outside the leader. Leadership churn re-points the
dispatch stream at the same warm replicas instead of cold-starting a
new worker's solver (the zero-warmup-on-failover property the chaos
scenario gates).

This module lives under scheduler/tpu and may import jax eagerly (the
nomad-vet layering map path-exempts the subtree); the server-side
tracker (server/solver_pool.py) must not.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ... import metrics
from ..context import SchedulerConfig
from .scheduler import solve_eval_batch_begin
from .solver import ResidentClusterState


class CollectingPlanner:
    """Planner stand-in for a remote solve: followup evals minted by
    reconcile (``results.followup_evals``) are COLLECTED and shipped
    back to the leader instead of raft-applied here — a follower's
    ``raft_apply`` would only bounce with NotLeaderError. The leader
    applies them on its own planner when the batch lands
    (RemotePendingBatch.finish)."""

    def __init__(self) -> None:
        self.followups: list = []

    def create_eval(self, eval_obj) -> None:
        self.followups.append(eval_obj)

    def update_eval(self, eval_obj) -> None:
        self.followups.append(eval_obj)


class RemoteSolver:
    """One pool member's warm solve engine.

    ``host`` is anything with a ``.state`` exposing
    ``snapshot_min_index(index, timeout_s)`` — a ClusterServer in
    production, a plain shim in the bench (which models
    perfectly-synced replicas by sharing one store). The member keeps
    its OWN SchedulerConfig instance: the serially-busy device model
    (``config._device_free_at``) is per-config, so every member is an
    independent chip and pool throughput scales with membership.

    Single-writer per member: a lock serializes solves the same way the
    leader's eval broker serializes the local worker's (the resident
    tensors are single-writer by design)."""

    def __init__(self, host, config: Optional[SchedulerConfig] = None,
                 node_id: str = "") -> None:
        self.host = host
        self.node_id = node_id
        self.config = config or SchedulerConfig(backend="tpu")
        self._lock = threading.Lock()
        self._resident: Optional[ResidentClusterState] = None
        # warmups counts COLD STARTS (resident-state construction): the
        # chaos gate "kill-the-leader costs zero solver warmup" reads
        # this counter's delta on the surviving members.
        self.warmups = 0
        self.solves = 0
        self.syncs = 0
        self.in_flight = 0

    def _ensure_resident(self) -> ResidentClusterState:
        if self._resident is None:
            mesh = None
            if (getattr(self.config, "mesh_devices", 0) or 0) > 1:
                from .sharding import solver_mesh

                try:
                    mesh = solver_mesh(self.config.mesh_devices)
                except RuntimeError:
                    self.config.mesh_devices = 0
            self._resident = ResidentClusterState(mesh=mesh)
            self.warmups += 1
            metrics.incr("nomad.solver.pool.warmups")
        return self._resident

    @property
    def last_sync(self) -> str:
        return self._resident.last_sync if self._resident else "cold"

    def warm(self, min_index: int = 0,
             datacenters: tuple = ("*",)) -> str:
        """Periodic delta sync (the member's sync loop): pull the local
        replica forward and ship only the changed usage rows to the
        device. ``ready_nodes_in_dcs`` iterates the store's node table
        in a stable order, so the ``("*",)`` warm universe carries the
        same (id, modify_index) fingerprint as a matching solve's dc
        set — the first dispatched batch after a warm hits the delta
        path, not a full re-upload."""
        with self._lock:
            resident = self._ensure_resident()
            snapshot = self.host.state.snapshot_min_index(
                min_index, timeout_s=2
            )
            nodes, _ = resident.ready_nodes(snapshot, tuple(datacenters))
            if nodes:
                resident.sync(snapshot, nodes)
            self.syncs += 1
            return resident.last_sync

    def solve(self, evals: list, min_index: int,
              extra_usage: Optional[dict] = None,
              timeout_s: float = 5.0) -> dict:
        """One dispatched batch: wait for the local replica to reach the
        leader's snapshot index, solve on the warm resident state, and
        return the plan columns + collected followup evals. Raises if
        the replica can't catch up in time — the leader's dispatch
        fault path (host fallback) covers it."""
        self.in_flight += 1
        try:
            with self._lock:
                resident = self._ensure_resident()
                snapshot = self.host.state.snapshot_min_index(
                    min_index, timeout_s=timeout_s
                )
                planner = CollectingPlanner()
                t0 = time.perf_counter()
                pending = solve_eval_batch_begin(
                    snapshot, planner, evals, self.config,
                    resident=resident, extra_usage=extra_usage,
                )
                plans = pending.finish()
                dt = time.perf_counter() - t0
                self.solves += 1
                metrics.incr("nomad.solver.pool.solves")
                metrics.observe("nomad.solver.pool.solve_seconds", dt)
                return {
                    "plans": plans,
                    "followups": planner.followups,
                    "telemetry": {
                        "member": self.node_id,
                        "last_sync": resident.last_sync,
                        "used_micro": bool(pending.used_micro),
                        "solve_seconds": dt,
                    },
                }
        finally:
            self.in_flight -= 1

    def stats(self) -> dict:
        """Live member counters for SolverPool.Status / /v1/solver/pool
        (same stats_snapshot() idiom as the broker/plan-queue gauges)."""
        return {
            "node_id": self.node_id,
            "warmups": self.warmups,
            "solves": self.solves,
            "syncs": self.syncs,
            "in_flight": self.in_flight,
            "last_sync": self.last_sync,
            "resident": self._resident is not None,
        }
