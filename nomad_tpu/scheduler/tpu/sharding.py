"""Node-axis mesh sharding for the batched solver.

A real TPU is a *mesh*, not one chip (SNIPPETS.md's t5x mesh/pjit
partitioning is the pattern). This module owns everything the solver
needs to split the node axis over a `jax.sharding.Mesh`:

  * `SolverMesh` — the mesh itself plus the per-mesh jit cache: the
    distributed-top-k solver (kernels.make_sharded_solver, one jit per
    readback-width bucket so group-count drift never recompiles), the
    preemption variant, and the `NamedSharding` the resident tensors are
    placed with.
  * node-axis padding — `pad_nodes()` extends the pad_n bucket to a
    multiple of the mesh size, so every device owns an equal [N/D, R]
    shard regardless of the cluster's real node count (the shard-padding
    edge: n not divisible by the mesh is absorbed by the bucket, and the
    pad rows carry zero capacity so they can never place).
  * shard accounting — per-shard real-row occupancy for solverobs and
    the modeled ICI bytes an all-gather solve moves (the transfer ledger
    records them under the ``allgather`` direction; the CPU-fallback
    mesh has no real ICI, so the model IS the measurement and is
    documented as such in docs/sharding.md).

Layering: this module lives under scheduler/tpu, the one package allowed
to import jax eagerly (nomad-vet NV-layering); the control plane reaches
sharding state only through solverobs snapshots.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .kernels import (
    _pad_to,
    make_sharded_solver,
    make_sharded_solver_preempt,
    pad_c,
    pad_n,
)


class SolverMesh:
    """One device mesh with the node axis sharded, plus its jit cache.

    Build once per (device count) and reuse — the factory jits compile
    per mesh, and a fresh SolverMesh per solve would recompile every
    batch (the ledger would show the storm). `solver_mesh()` below is
    the process-global cache production paths go through.
    """

    def __init__(
        self,
        n_devices: Optional[int] = None,
        axis: str = "nodes",
        devices=None,
    ) -> None:
        if devices is None:
            devices = jax.devices()
            if n_devices is not None:
                if len(devices) < n_devices:
                    raise RuntimeError(
                        f"mesh wants {n_devices} devices, backend has "
                        f"{len(devices)}"
                    )
                devices = devices[:n_devices]
        self.axis = axis
        self.mesh = Mesh(np.asarray(devices), axis_names=(axis,))
        self.n_dev = int(self.mesh.shape[axis])
        self._lock = threading.Lock()
        self._solvers: dict = {}  # k bucket (or None) -> jit
        self._preempt = None

    # -- kernels --------------------------------------------------------

    def solver(self, max_count: Optional[int] = None,
               compact: bool = False):
        """(jit, k_bucket) for the node-sharded solve. max_count bounds
        every group's count in the batch; it is bucketed (pad_c) so the
        jit signature — and the compile ledger — stay stable while the
        batch's biggest group drifts. None = the always-exact full
        argsort waterfill (tests, tiny meshes). compact=True returns
        the [G, maxC] instance-list readback (requires max_count)."""
        k = None if max_count is None else pad_c(max(1, int(max_count)))
        key = (k, compact)
        with self._lock:
            fn = self._solvers.get(key)
            if fn is None:
                fn = self._solvers[key] = make_sharded_solver(
                    self.mesh, self.axis, max_count=k, compact=compact
                )
            return fn, k

    def preempt_solver(self):
        with self._lock:
            if self._preempt is None:
                self._preempt = make_sharded_solver_preempt(
                    self.mesh, self.axis
                )
            return self._preempt

    # -- placement of resident tensors ----------------------------------

    def node_sharding(self) -> NamedSharding:
        """Row-sharded [N, R]: each device owns its node rows once;
        delta syncs scatter into the owning shard (solver.py
        ResidentClusterState)."""
        return NamedSharding(self.mesh, P(self.axis, None))

    def pad_nodes(self, n: int) -> int:
        """pad_n extended to a multiple of the mesh size. pad_n buckets
        (powers of two >= 256, then 2048-multiples) already divide any
        power-of-two mesh <= 256; the round-up only moves for odd mesh
        sizes, and stays a stable bucket either way."""
        return _pad_to(pad_n(n), self.n_dev)

    # -- shard accounting ----------------------------------------------

    def shard_occupancy(self, n: int, np_: int) -> list[dict]:
        """Per-shard real-row occupancy of one dispatch: shard d owns
        rows [d*w, (d+1)*w); rows past the cluster's real n are pad."""
        w = np_ // self.n_dev
        out = []
        for d in range(self.n_dev):
            real = min(max(n - d * w, 0), w)
            out.append({
                "shard": d,
                "rows": w,
                "real_rows": real,
                "occupancy": round(real / w, 4) if w else 0.0,
            })
        return out

    def allgather_bytes(self, g: int, np_: int, k: Optional[int]) -> int:
        """Modeled ICI bytes one solve's all-gathers move (the transfer
        ledger's ``allgather`` direction). Per scan step each device
        receives the other shards' contribution:

          top-k path: (D-1) * k candidate triples (score f32 + units
          i32 + index i32 = 12B) per device, D devices;
          argsort path: the full remote score+units vectors,
          (N - N/D) * 8B per device, D devices.
        """
        d = self.n_dev
        if k is not None:
            per_step = d * (d - 1) * k * 12
        else:
            per_step = d * (np_ - np_ // d) * 8
        return g * per_step


_MESHES: dict[int, SolverMesh] = {}
_MESHES_LOCK = threading.Lock()


def solver_mesh(n_devices: int) -> SolverMesh:
    """Process-global per-device-count cache: every worker/bench caller
    sharing a mesh size shares its compiled kernels."""
    with _MESHES_LOCK:
        m = _MESHES.get(n_devices)
        if m is None:
            m = _MESHES[n_devices] = SolverMesh(n_devices)
        return m
