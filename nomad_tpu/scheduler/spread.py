"""Spread-stanza scoring boost.

Reference: scheduler/spread.go — SpreadIterator :15, evenSpreadScoreBoost
:178. Targeted spreads score nodes by how far each attribute value is below
its desired share; even spreads boost the least-used value.
"""

from __future__ import annotations

import math
from typing import Iterator

from .context import EvalContext
from .feasible import resolve_target
from .propertyset import PropertySet
from .rank import SPREAD_SCORER, RankedNode


class SpreadScorer:
    def __init__(self, ctx: EvalContext, job, tg, metrics=None) -> None:
        self.ctx = ctx
        self.job = job
        self.tg = tg
        self.metrics = metrics
        # spread stanzas: task group's take priority over job-level
        self.spreads = list(tg.spreads) + [
            s for s in job.spreads if s.attribute not in {t.attribute for t in tg.spreads}
        ]
        self.psets: dict[str, PropertySet] = {}
        for s in self.spreads:
            pset = PropertySet(ctx, job)
            pset.set_target_attribute(s.attribute, tg.name)
            self.psets[s.attribute] = pset
        self.sum_weights = sum(abs(s.weight) for s in self.spreads) or 1
        self.desired_count = tg.count

    def boost_for(self, node) -> float:
        if not self.spreads:
            return 0.0
        total = 0.0
        for s in self.spreads:
            pset = self.psets[s.attribute]
            val, ok = resolve_target(node, s.attribute)
            if not ok:
                continue
            counts = pset.used_counts()
            if s.targets:
                boost = self._target_boost(s, val, counts)
            else:
                boost = self._even_boost(val, counts)
            total += boost * (s.weight / self.sum_weights)
        return total

    def _target_boost(self, s, val: str, counts: dict[str, int]) -> float:
        """(desired − used)/desired for this value's target share
        (reference: spread.go scoreBoost)."""
        percent = 0
        explicit = {t.value: t.percent for t in s.targets}
        if val in explicit:
            percent = explicit[val]
        else:
            remaining = 100 - sum(explicit.values())
            # implicit targets share the remainder evenly over unseen values
            others = {v for v in counts if v not in explicit} | {val}
            percent = remaining // max(1, len(others))
        desired = math.ceil(percent / 100.0 * self.desired_count)
        if desired <= 0:
            return -1.0
        used = counts.get(val, 0)
        return (desired - used) / desired

    def _even_boost(self, val: str, counts: dict[str, int]) -> float:
        """Boost least-used values (reference: spread.go:178)."""
        if not counts:
            return 0.0
        used = counts.get(val, 0)
        min_count = min(list(counts.values()) + [used])
        max_count = max(list(counts.values()) + [used])
        if max_count == min_count:
            return 0.0
        # below-average values get a positive boost, above-average negative
        return (min_count - used) / max(1, max_count)


def spread_rank(
    ctx: EvalContext,
    options: Iterator[RankedNode],
    scorer: SpreadScorer,
    metrics=None,
) -> Iterator[RankedNode]:
    for option in options:
        boost = scorer.boost_for(option.node)
        if boost != 0.0:
            option.add_score(SPREAD_SCORER, boost)
            if metrics is not None:
                metrics.score_node(option.node.id, SPREAD_SCORER, boost)
        yield option
