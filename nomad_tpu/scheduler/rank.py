"""Node scoring pipeline.

Reference: scheduler/rank.go — RankedNode :21, BinPackIterator.Next :193-527
(the reference's hot loop), JobAntiAffinityIterator :536,
NodeReschedulingPenaltyIterator :606, NodeAffinityIterator :650,
ScoreNormalizationIterator :740.

The host pipeline below is the correctness oracle; the TPU backend computes
the same scores for all (alloc, node) pairs at once in
nomad_tpu/scheduler/tpu/kernels.py. Keep formula changes mirrored there.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

from ..structs import (
    AllocatedResources,
    AllocatedTaskResources,
    NetworkIndex,
    Node,
    Resources,
    TaskGroup,
)
from ..structs.funcs import score_fit_binpack, score_fit_spread
from .context import EvalContext
from .device import DeviceAllocator

BINPACK_SCORER = "binpack"
JOB_ANTI_AFFINITY_SCORER = "job-anti-affinity"
NODE_RESCHED_PENALTY_SCORER = "node-reschedule-penalty"
NODE_AFFINITY_SCORER = "node-affinity"
SPREAD_SCORER = "allocation-spread"


@dataclass
class RankedNode:
    node: Node
    scores: dict[str, float] = field(default_factory=dict)
    final_score: float = 0.0
    task_resources: dict[str, AllocatedTaskResources] = field(default_factory=dict)
    alloc_resources: Optional[AllocatedResources] = None
    proposed_allocs: Optional[list] = None
    # allocs that must be evicted for this placement to fit
    # (reference rank.go:33 PreemptedAllocs)
    preempted_allocs: Optional[list] = None

    def add_score(self, name: str, value: float) -> None:
        self.scores[name] = value


def binpack_rank(
    ctx: EvalContext,
    candidates: Iterator[Node],
    tg: TaskGroup,
    metrics=None,
    algorithm: Optional[str] = None,
    evict: bool = False,
    job=None,
) -> Iterator[RankedNode]:
    """Fit-check + score each candidate node for the task group.

    Per node: proposed utilization (existing − stops + placements), per-task
    network/device assignment, cumulative fit, ScoreFit. Infeasible nodes are
    recorded as exhausted and skipped. Reference: rank.go BinPackIterator.

    With evict=True (the scheduler's second pass after normal placement
    fails), a node that doesn't fit runs the Preemptor (reference
    rank.go:233): lower-priority allocs are chosen for eviction and the
    fit re-checked without them; picks land on RankedNode.preempted_allocs.
    Scope matches PreemptForTaskGroup (cpu/mem/disk); the network/device
    preemption paths are not implemented.
    """
    algo = algorithm or ctx.scheduler_config.algorithm
    for node in candidates:
        proposed = ctx.proposed_allocs(node.id)
        available = node.available_resources()
        total_ask = tg.combined_resources()

        def _utilization(allocs):
            util = Resources(
                cpu=total_ask.cpu,
                memory_mb=total_ask.memory_mb,
                disk_mb=total_ask.disk_mb,
            )
            for alloc in allocs:
                r = alloc.comparable_resources()
                util.cpu += r.cpu
                util.memory_mb += r.memory_mb
                util.disk_mb += r.disk_mb
            return util

        util = _utilization(proposed)
        preempted_allocs = None
        ok, dim = available.superset(util)
        if not ok and evict and job is not None:
            from .preemption import Preemptor

            preemptor = Preemptor(
                job.priority, job.namespace, job.id, ctx.plan
            )
            preemptor.set_node(node)
            preemptor.set_candidates(proposed)
            picks = preemptor.preempt_for_task_group(total_ask)
            if picks:
                picked_ids = {a.id for a in picks}
                without = [a for a in proposed if a.id not in picked_ids]
                util = _utilization(without)
                ok, dim = available.superset(util)
                if ok:
                    preempted_allocs = picks
                    proposed = without
        if not ok:
            if metrics is not None:
                metrics.exhausted_node(node, dim)
            continue

        net_idx = NetworkIndex()
        net_idx.set_node(node)
        net_idx.add_allocs(proposed)

        dev_alloc = DeviceAllocator(ctx, node)
        dev_alloc.add_allocs(proposed)

        # Dedicated cores (reference rank.go: AllocatedCpuResources via
        # idset): free ids = node's cores minus every proposed alloc's
        # reservations; a `cores` task gets the lowest free ids and a
        # DERIVED cpu share (cores x node MHz/core) so MHz accounting
        # stays consistent with share-based tasks.
        free_cores: list = []
        mhz_per_core = 0
        if any(t.resources.cores > 0 for t in tg.tasks):
            from ..structs.funcs import node_core_pool

            free_cores, mhz_per_core = node_core_pool(node, proposed)

        # Per-task port/bandwidth + device assignment.
        task_resources: dict[str, AllocatedTaskResources] = {}
        feasible = True
        for task in tg.tasks:
            tr = AllocatedTaskResources(
                cpu=task.resources.cpu, memory_mb=task.resources.memory_mb
            )
            if task.resources.cores > 0:
                if len(free_cores) < task.resources.cores:
                    if metrics is not None:
                        metrics.exhausted_node(node, "cores")
                    feasible = False
                    break
                tr.reserved_cores = free_cores[: task.resources.cores]
                free_cores = free_cores[task.resources.cores :]
                tr.cpu = task.resources.cores * mhz_per_core
                util.cpu += tr.cpu - task.resources.cpu
                ok, dim = available.superset(util)
                if not ok:
                    if metrics is not None:
                        metrics.exhausted_node(node, dim)
                    feasible = False
                    break
            for ask in task.resources.networks:
                offer = net_idx.assign_network(ask)
                if offer is None:
                    if metrics is not None:
                        metrics.exhausted_node(node, "network")
                    feasible = False
                    break
                net_idx.add_reserved(offer)
                tr.networks.append(offer)
            if not feasible:
                break
            for dev_ask in task.resources.devices:
                got = dev_alloc.assign(dev_ask)
                if got is None:
                    if metrics is not None:
                        metrics.exhausted_node(node, "devices")
                    feasible = False
                    break
                tr.devices.append(got)
            if not feasible:
                break
            task_resources[task.name] = tr
        if not feasible:
            continue

        # Group-level networks (bridge/port asks at the group level).
        shared_networks = []
        for ask in tg.networks:
            offer = net_idx.assign_network(ask)
            if offer is None:
                if metrics is not None:
                    metrics.exhausted_node(node, "network")
                feasible = False
                break
            net_idx.add_reserved(offer)
            shared_networks.append(offer)
        if not feasible:
            continue

        if algo == "spread":
            fit_score = score_fit_spread(node, util)
        else:
            fit_score = score_fit_binpack(node, util)
        # Normalize [0,18] → [0,1] like the reference (rank.go:504).
        normalized = fit_score / 18.0

        ranked = RankedNode(
            node=node,
            task_resources=task_resources,
            alloc_resources=AllocatedResources(
                tasks=task_resources,
                shared_disk_mb=tg.ephemeral_disk.size_mb,
                shared_networks=shared_networks,
            ),
            proposed_allocs=proposed,
            preempted_allocs=preempted_allocs,
        )
        ranked.add_score(BINPACK_SCORER, normalized)
        if metrics is not None:
            metrics.score_node(node.id, BINPACK_SCORER, normalized)
        yield ranked


def job_anti_affinity_rank(
    ctx: EvalContext,
    options: Iterator[RankedNode],
    job_id: str,
    tg_name: str,
    desired_count: int,
    metrics=None,
) -> Iterator[RankedNode]:
    """Penalize placing multiple allocs of one task group on a node
    (reference: rank.go:536)."""
    for option in options:
        proposed = (
            option.proposed_allocs
            if option.proposed_allocs is not None
            else ctx.proposed_allocs(option.node.id)
        )
        collisions = sum(
            1
            for a in proposed
            if a.job_id == job_id and a.task_group == tg_name
        )
        if collisions > 0 and desired_count > 0:
            penalty = -1.0 * float(collisions + 1) / float(desired_count)
            option.add_score(JOB_ANTI_AFFINITY_SCORER, penalty)
            if metrics is not None:
                metrics.score_node(option.node.id, JOB_ANTI_AFFINITY_SCORER, penalty)
        yield option


def node_resched_penalty_rank(
    options: Iterator[RankedNode],
    penalty_nodes: set[str],
    metrics=None,
) -> Iterator[RankedNode]:
    """Penalize the node a failed alloc is being rescheduled away from
    (reference: rank.go:606)."""
    for option in options:
        if option.node.id in penalty_nodes:
            option.add_score(NODE_RESCHED_PENALTY_SCORER, -1.0)
            if metrics is not None:
                metrics.score_node(option.node.id, NODE_RESCHED_PENALTY_SCORER, -1.0)
        yield option


def node_affinity_rank(
    ctx: EvalContext,
    options: Iterator[RankedNode],
    affinities: list,
    metrics=None,
) -> Iterator[RankedNode]:
    """Soft-preference scoring, normalized by total |weight|
    (reference: rank.go:650)."""
    from .feasible import node_matches_constraint

    if not affinities:
        yield from options
        return
    total_weight = sum(abs(a.weight) for a in affinities) or 1
    for option in options:
        total = 0.0
        for aff in affinities:
            if node_matches_constraint(ctx, option.node, aff):
                total += float(aff.weight)
        if total != 0.0:
            norm = total / float(total_weight)
            option.add_score(NODE_AFFINITY_SCORER, norm)
            if metrics is not None:
                metrics.score_node(option.node.id, NODE_AFFINITY_SCORER, norm)
        yield option


def score_normalization(
    options: Iterator[RankedNode], metrics=None
) -> Iterator[RankedNode]:
    """final = mean of component scores (reference: rank.go:740)."""
    for option in options:
        if option.scores:
            option.final_score = sum(option.scores.values()) / len(option.scores)
        if metrics is not None:
            metrics.score_node(option.node.id, "normalized", option.final_score)
        yield option
