"""Placement stacks: the composed feasibility → rank → select pipeline.

Reference: scheduler/stack.go — GenericStack :43 (shuffled source, log₂(n)
candidate limit :83-90), Select :117, SystemStack :183.
"""

from __future__ import annotations

import math
import random
from typing import Iterable, Optional

from ..structs import Constraint, Job, Node, TaskGroup
from ..structs.structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    JOB_TYPE_BATCH,
)
from .context import EvalContext
from .feasible import (
    ConstraintChecker,
    DeviceChecker,
    DistinctHostsChecker,
    DriverChecker,
    FeasibilityChecker,
    HostVolumeChecker,
    CSIVolumeChecker,
    NetworkChecker,
    feasibility_pipeline,
)
from .propertyset import PropertySet
from .rank import (
    RankedNode,
    binpack_rank,
    job_anti_affinity_rank,
    node_affinity_rank,
    node_resched_penalty_rank,
    score_normalization,
)
from .select import limit_select, max_score_select
from .spread import SpreadScorer, spread_rank


def _tg_drivers(tg: TaskGroup) -> set[str]:
    return {t.driver for t in tg.tasks}


def _distinct_property_constraints(
    constraints: list[Constraint],
) -> list[Constraint]:
    return [c for c in constraints if c.operand == CONSTRAINT_DISTINCT_PROPERTY]


def _has_distinct_hosts(constraints: list[Constraint]) -> bool:
    return any(c.operand == CONSTRAINT_DISTINCT_HOSTS for c in constraints)


def _distinct_property_checkers(ctx, job, tg) -> list:
    """Stateful distinct_property checkers for one task group — job,
    group, AND task level (lower.py folds task constraints into
    units_cap the same way, so both backends agree)."""
    post = []
    for c in _distinct_property_constraints(job.constraints):
        pset = PropertySet(ctx, job)
        pset.set_job_constraint(c)
        post.append(_DistinctPropertyChecker(pset))
    tg_level = list(tg.constraints)
    for t in tg.tasks:
        tg_level.extend(t.constraints)
    for c in _distinct_property_constraints(tg_level):
        pset = PropertySet(ctx, job)
        pset.set_tg_constraint(c, tg.name)
        post.append(_DistinctPropertyChecker(pset))
    return post


class _DistinctPropertyChecker(FeasibilityChecker):
    def __init__(self, pset: PropertySet) -> None:
        self.pset = pset

    def feasible(self, node: Node) -> tuple[bool, str]:
        return self.pset.satisfies_distinct_property(node)


class GenericStack:
    """Service/batch placement stack (reference: stack.go:43)."""

    def __init__(self, batch: bool, ctx: EvalContext) -> None:
        self.batch = batch
        self.ctx = ctx
        self.nodes: list[Node] = []
        self.limit = 2
        self.job: Optional[Job] = None
        # Per-eval caches: PropertySets scan all existing allocs once; the
        # plan delta is merged per call (reference caches these on Context).
        self._post_checkers: dict[str, list[FeasibilityChecker]] = {}
        self._spread_scorers: dict[str, SpreadScorer] = {}

    def set_nodes(self, nodes: list[Node]) -> None:
        """Shuffle for scheduler-worker decorrelation and set the candidate
        limit: log₂(n) for service (power-of-N-choices), 2 for batch
        (reference: stack.go:71-90)."""
        self.nodes = list(nodes)
        random.shuffle(self.nodes)
        n = len(self.nodes)
        if self.batch:
            self.limit = 2
        else:
            self.limit = max(2, int(math.ceil(math.log2(n)))) if n > 0 else 2

    def set_job(self, job: Job) -> None:
        self.job = job
        self.ctx.eligibility.set_job(job)
        self._post_checkers.clear()
        self._spread_scorers.clear()

    def select(
        self,
        tg: TaskGroup,
        penalty_nodes: Optional[set[str]] = None,
        metrics=None,
        selected_nodes: Optional[list[Node]] = None,
        evict: bool = False,
    ) -> Optional[RankedNode]:
        """Pick the best node for one instance of the task group.
        evict=True enables the preemption pass in binpack ranking."""
        job = self.job
        assert job is not None, "set_job must be called first"
        source: Iterable[Node] = (
            selected_nodes if selected_nodes is not None else self.nodes
        )

        job_checkers: list[FeasibilityChecker] = [
            ConstraintChecker(self.ctx, job.constraints),
        ]
        all_constraints = list(tg.constraints)
        for t in tg.tasks:
            all_constraints.extend(t.constraints)
        tg_checkers: list[FeasibilityChecker] = [
            DriverChecker(self.ctx, _tg_drivers(tg)),
            ConstraintChecker(self.ctx, all_constraints),
            HostVolumeChecker(self.ctx, tg.volumes, namespace=job.namespace),
            CSIVolumeChecker(self.ctx, tg.volumes, namespace=job.namespace),
            NetworkChecker(self.ctx, tg),
            DeviceChecker(self.ctx, tg),
        ]

        feasible = feasibility_pipeline(
            self.ctx, source, job_checkers, tg_checkers, tg.name, metrics
        )

        # Stateful per-plan checkers sit outside the class memoization.
        post = self._post_checkers.get(tg.name)
        if post is None:
            post = []
            if _has_distinct_hosts(job.constraints):
                post.append(DistinctHostsChecker(self.ctx, job.id, tg.name, True))
            elif _has_distinct_hosts(tg.constraints):
                post.append(DistinctHostsChecker(self.ctx, job.id, tg.name, False))
            post.extend(_distinct_property_checkers(self.ctx, job, tg))
            self._post_checkers[tg.name] = post
        if post:
            def _post_filter(nodes):
                for node in nodes:
                    ok = True
                    for checker in post:
                        good, reason = checker.feasible(node)
                        if not good:
                            if metrics is not None:
                                metrics.filter_node(node, reason)
                            ok = False
                            break
                    if ok:
                        yield node

            feasible = _post_filter(feasible)

        options = binpack_rank(
            self.ctx, feasible, tg, metrics, evict=evict, job=job
        )
        options = job_anti_affinity_rank(
            self.ctx, options, job.id, tg.name, tg.count, metrics
        )
        if penalty_nodes:
            options = node_resched_penalty_rank(options, penalty_nodes, metrics)
        affinities = list(job.affinities) + list(tg.affinities)
        for t in tg.tasks:
            affinities.extend(t.affinities)
        options = node_affinity_rank(self.ctx, options, affinities, metrics)
        if tg.spreads or job.spreads:
            scorer = self._spread_scorers.get(tg.name)
            if scorer is None:
                scorer = SpreadScorer(self.ctx, job, tg, metrics)
                self._spread_scorers[tg.name] = scorer
            options = spread_rank(self.ctx, options, scorer, metrics)
        options = score_normalization(options, metrics)
        shortlist = limit_select(options, self.limit)
        return max_score_select(shortlist)


class SystemStack:
    """System/sysbatch stack: every feasible node, no shuffle/limit
    (reference: stack.go:183)."""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.nodes: list[Node] = []
        self.job: Optional[Job] = None
        self._post_checkers: dict[str, list] = {}

    def set_nodes(self, nodes: list[Node]) -> None:
        self.nodes = list(nodes)

    def set_job(self, job: Job) -> None:
        self.job = job
        self.ctx.eligibility.set_job(job)
        self._post_checkers = {}

    def select(
        self, tg: TaskGroup, node: Node, metrics=None, evict: bool = False
    ) -> Optional[RankedNode]:
        """Fit one instance of tg on one specific node."""
        job = self.job
        assert job is not None
        job_checkers = [ConstraintChecker(self.ctx, job.constraints)]
        all_constraints = list(tg.constraints)
        for t in tg.tasks:
            all_constraints.extend(t.constraints)
        tg_checkers = [
            DriverChecker(self.ctx, _tg_drivers(tg)),
            ConstraintChecker(self.ctx, all_constraints),
            HostVolumeChecker(self.ctx, tg.volumes, namespace=job.namespace),
            CSIVolumeChecker(self.ctx, tg.volumes, namespace=job.namespace),
            NetworkChecker(self.ctx, tg),
            DeviceChecker(self.ctx, tg),
        ]
        feasible = feasibility_pipeline(
            self.ctx, [node], job_checkers, tg_checkers, tg.name, metrics
        )
        # distinct_property budgets are shared across the walk's own
        # placements (reference SystemStack wires DistinctPropertyIterator
        # AFTER the feasibility chain, stack.go:197-259, so filter
        # metrics match the generic stack); PropertySet reads the live
        # plan so each placed node decrements the per-value budget.
        post = self._post_checkers.get(tg.name)
        if post is None:
            post = _distinct_property_checkers(self.ctx, job, tg)
            self._post_checkers[tg.name] = post
        if post:
            def _post_filter(nodes):
                for n in nodes:
                    ok = True
                    for checker in post:
                        good, reason = checker.feasible(n)
                        if not good:
                            if metrics is not None:
                                metrics.filter_node(n, reason)
                            ok = False
                            break
                    if ok:
                        yield n

            feasible = _post_filter(feasible)
        options = binpack_rank(
            self.ctx, feasible, tg, metrics, evict=evict, job=job
        )
        options = score_normalization(options, metrics)
        got = list(options)
        return got[0] if got else None
