"""Alloc counts per node-attribute value, for distinct_property and spread.

Reference: scheduler/propertyset.go — propertySet :14, UsedCount :231,
GetCombinedUseMap :250.
"""

from __future__ import annotations

from typing import Optional

from ..structs import Constraint, Node
from .context import EvalContext
from .feasible import resolve_target


class PropertySet:
    """Counts existing + planned + proposed allocs per value of one node
    attribute, scoped to a job or one task group."""

    def __init__(self, ctx: EvalContext, job) -> None:
        self.ctx = ctx
        self.job = job
        self.namespace = job.namespace
        self.target_attribute: str = ""
        self.target_values: set[str] = set()  # spread explicit targets
        self.tg_name: str = ""  # empty = job scope
        self.allowed_count: int = 0  # distinct_property limit (0 = spread use)
        self._existing: Optional[dict[str, int]] = None
        self._cleared: dict[str, int] = {}

    def set_job_constraint(self, constraint: Constraint) -> None:
        self.target_attribute = constraint.ltarget
        self.allowed_count = int(constraint.rtarget) if constraint.rtarget else 1

    def set_tg_constraint(self, constraint: Constraint, tg_name: str) -> None:
        self.set_job_constraint(constraint)
        self.tg_name = tg_name

    def set_target_attribute(self, attribute: str, tg_name: str = "") -> None:
        self.target_attribute = attribute
        self.tg_name = tg_name

    def _relevant(self, alloc) -> bool:
        if alloc.job_id != self.job.id or alloc.namespace != self.namespace:
            return False
        if self.tg_name and alloc.task_group != self.tg_name:
            return False
        return True

    def _value_of(self, node: Optional[Node]) -> tuple[str, bool]:
        if node is None:
            return "", False
        return resolve_target(node, self.target_attribute)

    def _compute_existing(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        node_cache: dict[str, Optional[Node]] = {}
        for alloc in self.ctx.state.allocs():
            if alloc.terminal_status() or not self._relevant(alloc):
                continue
            node = node_cache.get(alloc.node_id, ...)
            if node is ...:
                node = self.ctx.state.node_by_id(alloc.node_id)
                node_cache[alloc.node_id] = node
            val, ok = self._value_of(node)
            if ok:
                counts[val] = counts.get(val, 0) + 1
        return counts

    def used_counts(self) -> dict[str, int]:
        """existing − plan stops + plan placements, per attribute value
        (reference: GetCombinedUseMap :250)."""
        if self._existing is None:
            self._existing = self._compute_existing()
        combined = dict(self._existing)
        plan = self.ctx.plan
        if plan is not None:
            for node_id, allocs in plan.node_allocation.items():
                node = self.ctx.state.node_by_id(node_id)
                val, ok = self._value_of(node)
                if not ok:
                    continue
                for alloc in allocs:
                    if self._relevant(alloc):
                        combined[val] = combined.get(val, 0) + 1
            for node_id, allocs in list(plan.node_update.items()) + list(
                plan.node_preemptions.items()
            ):
                node = self.ctx.state.node_by_id(node_id)
                val, ok = self._value_of(node)
                if not ok:
                    continue
                for alloc in allocs:
                    if self._relevant(alloc):
                        combined[val] = max(0, combined.get(val, 0) - 1)
        return combined

    def satisfies_distinct_property(self, node: Node) -> tuple[bool, str]:
        val, ok = self._value_of(node)
        if not ok:
            return False, f"missing property {self.target_attribute}"
        used = self.used_counts().get(val, 0)
        if used >= self.allowed_count:
            return (
                False,
                f"distinct_property: {self.target_attribute}={val} used by {used} allocs",
            )
        return True, ""
