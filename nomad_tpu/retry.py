"""Unified retry policy: bounded exponential backoff + jitter + deadline.

One policy shape shared by every path that must survive leadership
churn — RPC leader-forwarding (cluster.py _Forwarder), the scheduler
workers' dequeue/submit loops on NotLeaderError (server/worker.py), and
recovery-time reads (testing/chaos.py scenarios). Before this existed
each of those either failed on the first NotLeaderError or hot-looped
with no backoff (the worker burned a core re-nacking during the
revoke window).

Retry activity is first-class observability: every retry increments
``nomad.rpc.retry_count.<label>`` and records a ``retry.backoff`` span
on the calling thread's trace, so `operator trace` shows *why* a call
was slow and `operator top` shows churn as a counter rate.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

_rng = random.Random()


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule: attempt k sleeps in
    ``[d*(1-jitter), d]`` where ``d = min(max_s, base_s * multiplier**(k-1))``.
    ``deadline_s`` bounds the total budget of :func:`call_with_retry`;
    a bare :meth:`backoff` iterator (worker loops) has no deadline —
    the loop's own stop event bounds it."""

    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: float = 10.0

    def delay_s(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        raw = min(self.max_s, self.base_s * self.multiplier ** max(0, attempt - 1))
        r = (rng or _rng).random()
        return raw * (1.0 - self.jitter) + raw * self.jitter * r

    def backoff(self, rng: Optional[random.Random] = None) -> "Backoff":
        return Backoff(self, rng)


# Defaults by call path. One source of truth so the chaos tests can
# reason about worst-case convergence bounds.
FORWARD_POLICY = RetryPolicy(base_s=0.05, max_s=1.0, deadline_s=10.0)
WORKER_POLICY = RetryPolicy(base_s=0.05, max_s=2.0, deadline_s=0.0)


class Backoff:
    """Per-loop backoff state: ``next()`` returns the next delay,
    ``reset()`` on success so one bad window doesn't tax the next."""

    __slots__ = ("policy", "attempt", "rng")

    def __init__(self, policy: RetryPolicy, rng: Optional[random.Random] = None):
        self.policy = policy
        self.attempt = 0
        self.rng = rng

    def next(self) -> float:
        self.attempt += 1
        return self.policy.delay_s(self.attempt, self.rng)

    def reset(self) -> None:
        self.attempt = 0


def call_with_retry(
    fn: Callable,
    *,
    policy: RetryPolicy,
    retry_if: Callable[[BaseException], bool],
    label: str,
    stop=None,
    rng: Optional[random.Random] = None,
):
    """Run ``fn()``; on an exception ``retry_if`` accepts, back off and
    retry until ``policy.deadline_s`` is spent (then the last error
    re-raises). ``stop`` (a threading.Event) aborts the backoff sleep
    early and re-raises — a revoked subsystem must not finish its nap
    before noticing it was stopped.

    Emits ``nomad.rpc.retry_count.<label>`` per retry and a
    ``retry.backoff`` span on the current trace.
    """
    from . import metrics, trace

    deadline = time.monotonic() + policy.deadline_s
    bo = policy.backoff(rng)
    while True:
        try:
            return fn()
        except BaseException as e:
            if not retry_if(e):
                raise
            delay = bo.next()
            # Server-provided backoff hint (429 Retry-After riding a
            # RateLimitError/APIError as `retry_after_s`): a FLOOR on
            # the computed delay — retrying sooner than the server said
            # guarantees another rejection.
            hint = getattr(e, "retry_after_s", None)
            if hint:
                try:
                    delay = max(delay, float(hint))
                except (TypeError, ValueError):
                    pass
            if time.monotonic() + delay > deadline:
                raise
            metrics.incr(f"nomad.rpc.retry_count.{label}")
            with trace.span(
                trace.current(), "retry.backoff",
                target=label, attempt=bo.attempt, error=type(e).__name__,
            ):
                if stop is not None:
                    if stop.wait(delay):
                        raise
                else:
                    time.sleep(delay)
