"""State-store change → stream event conversion.

Reference: nomad/state/events.go (eventFromChange maps memdb change objects
+ raft message type to typed stream events). Here the store's publish hook
hands us (index, table, objs, etype); we map tables to topics and objects
to keys/filter-keys, then hand blocks to the EventBroker.
"""

from __future__ import annotations

from ..stream.event_broker import (
    TOPIC_ALLOC,
    TOPIC_DEPLOYMENT,
    TOPIC_EVAL,
    TOPIC_JOB,
    TOPIC_NODE,
    TOPIC_SERVICE,
    TOPIC_VOLUME,
    Event,
    EventBroker,
)
from .store import (
    TABLE_ALLOCS,
    TABLE_DEPLOYMENTS,
    TABLE_EVALS,
    TABLE_JOBS,
    TABLE_NODES,
    TABLE_SERVICES,
    TABLE_VOLUMES,
    StateStore,
)

_TABLE_TOPICS = {
    TABLE_NODES: TOPIC_NODE,
    TABLE_JOBS: TOPIC_JOB,
    TABLE_EVALS: TOPIC_EVAL,
    TABLE_ALLOCS: TOPIC_ALLOC,
    TABLE_DEPLOYMENTS: TOPIC_DEPLOYMENT,
    TABLE_SERVICES: TOPIC_SERVICE,
    TABLE_VOLUMES: TOPIC_VOLUME,
}

_DEFAULT_TYPES = {
    TABLE_NODES: "NodeEvent",
    TABLE_JOBS: "JobEvent",
    TABLE_EVALS: "EvaluationUpdated",
    TABLE_ALLOCS: "AllocationUpdated",
    TABLE_DEPLOYMENTS: "DeploymentStatusUpdate",
    TABLE_SERVICES: "ServiceRegistration",
    TABLE_VOLUMES: "VolumeEvent",
}


def _event_for(index: int, table: str, obj, etype: str) -> Event:
    topic = _TABLE_TOPICS[table]
    etype = etype or _DEFAULT_TYPES[table]
    namespace = getattr(obj, "namespace", "") or ""
    filter_keys: tuple = ()
    if table == TABLE_NODES:
        key = obj.id
    elif table == TABLE_JOBS:
        key = obj.id
    elif table == TABLE_EVALS:
        key = obj.id
        filter_keys = (obj.job_id,)
    elif table == TABLE_ALLOCS:
        key = obj.id
        # Filterable by job and node (reference events.go AllocationEvent
        # FilterKeys: JobID, DeploymentID).
        filter_keys = tuple(
            k for k in (obj.job_id, obj.node_id, obj.deployment_id) if k
        )
    elif table == TABLE_SERVICES:
        key = obj.service_name
        filter_keys = tuple(
            k for k in (obj.job_id, obj.alloc_id, obj.node_id) if k
        )
    elif table == TABLE_VOLUMES:
        key = obj.id
        filter_keys = (obj.plugin_id,) if obj.plugin_id else ()
    else:
        key = obj.id
        filter_keys = (obj.job_id,)
    return Event(
        topic=topic,
        type=etype,
        key=key,
        index=index,
        payload=obj,
        namespace=namespace,
        filter_keys=filter_keys,
    )


def wire_events(store: StateStore, broker: EventBroker) -> None:
    """Subscribe the broker to every state-store write."""

    def on_change(index: int, table: str, objs: list, etype: str) -> None:
        if table not in _TABLE_TOPICS or not objs:
            return
        broker.publish([_event_for(index, table, o, etype) for o in objs])

    store.subscribe(on_change)
