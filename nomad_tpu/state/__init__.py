from .store import (
    ALL_TABLES,
    JobSummary,
    StateSnapshot,
    StateSnapshotImpl,
    StateStore,
    TABLE_ALLOCS,
    TABLE_DEPLOYMENTS,
    TABLE_EVALS,
    TABLE_JOBS,
    TABLE_NODES,
)
