"""In-memory MVCC state store with watch support.

Reference: nomad/state/state_store.go (6,445 LoC, go-memdb immutable radix)
and nomad/state/schema.go:39-60 for the table set. The TPU-native redesign
keeps the same contract the schedulers and plan applier rely on:

  * copy-on-write discipline — structs are immutable once stored; writers
    insert fresh copies, never mutate in place;
  * O(1) snapshots — `snapshot()` marks tables shared and the next write to
    a shared table forks the dict (table-granular COW instead of the
    reference's radix-node-granular COW);
  * every write stamps a monotonically increasing index, and blocking reads
    (`wait_for_index`, the analog of memdb watch channels +
    SnapshotMinIndex, reference nomad/state/state_store.go SnapshotMinIndex)
    park on a condition variable.

The schedulers only read snapshots; the plan applier and FSM write through
the live store.
"""

from __future__ import annotations

import dataclasses
import threading

from ..gctune import paused_gc
from typing import Callable, Iterable, Optional

from ..structs import (
    Allocation,
    Deployment,
    Evaluation,
    Job,
    Node,
    PlanResult,
)
from ..structs.placement_batch import AllocRow, PlacementBatch
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_EVICT,
    ALLOC_DESIRED_STATUS_STOP,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    DEPLOYMENT_STATUSES_TERMINAL,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_PENDING,
    JOB_STATUS_DEAD,
    JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    NODE_SCHEDULING_ELIGIBLE,
    NODE_SCHEDULING_INELIGIBLE,
    NODE_STATUS_DOWN,
    DrainStrategy,
    now_ns,
)

# Table names (reference: nomad/state/schema.go:39-60)
TABLE_NODES = "nodes"
TABLE_JOBS = "jobs"
TABLE_JOB_VERSIONS = "job_version"
TABLE_JOB_SUMMARIES = "job_summary"
TABLE_EVALS = "evals"
TABLE_ALLOCS = "allocs"
TABLE_DEPLOYMENTS = "deployment"
TABLE_ACL_POLICIES = "acl_policy"
TABLE_ACL_TOKENS = "acl_token"
TABLE_VOLUMES = "volumes"
TABLE_NAMESPACES = "namespaces"
TABLE_SERVICES = "services"
TABLE_SECRETS = "secrets"
TABLE_OPERATOR = "operator_config"
TABLE_SCALING_POLICIES = "scaling_policy"
# (ns, job_id) -> {group: [event dicts]} — bounded scale-event journal
# (reference state_store.go UpsertScalingEvent, JOB_TRACKED_SCALING_EVENTS)
TABLE_SCALING_EVENTS = "scaling_event"
ALL_TABLES = (
    TABLE_NODES,
    TABLE_JOBS,
    TABLE_JOB_VERSIONS,
    TABLE_JOB_SUMMARIES,
    TABLE_EVALS,
    TABLE_ALLOCS,
    TABLE_DEPLOYMENTS,
    TABLE_ACL_POLICIES,
    TABLE_ACL_TOKENS,
    TABLE_VOLUMES,
    TABLE_NAMESPACES,
    TABLE_SERVICES,
    TABLE_SECRETS,
    TABLE_OPERATOR,
    TABLE_SCALING_POLICIES,
    TABLE_SCALING_EVENTS,
)

# Secondary indexes: key -> {alloc_id: Allocation}. Kept under the same
# table-granular COW regime so snapshots see consistent index views.
IDX_ALLOCS_NODE = "_idx_allocs_node"
IDX_ALLOCS_JOB = "_idx_allocs_job"
IDX_ALLOCS_EVAL = "_idx_allocs_eval"
# node_id -> (cpu, memory_mb, disk_mb, complex_count): committed
# non-terminal resource usage per node, maintained incrementally on every
# alloc write. This is what lets the plan applier verify a plan's node set
# with one vectorized compare instead of re-summing each node's allocs
# (reference parallelizes the re-sum over a pool, plan_apply_pool.go:18;
# here the sum is pre-maintained and the compare is numpy). complex_count
# counts non-terminal allocs whose fit cannot be expressed as a 3-vector
# compare (reserved cores, port/network asks) — those nodes take the exact
# per-node path. Values are immutable tuples, replaced wholesale, so the
# table obeys the same COW discipline as every other table.
IDX_NODE_USED = "_idx_node_used"
# priority -> count of non-terminal allocs at that job priority. A few
# integers that let the batch solver prove "no preemptible tier exists
# below this batch's priorities" in O(1) and take the aggregate-usage
# lowering path (O(nodes)) instead of walking every live alloc to build
# tier tensors it would never use.
IDX_PRIO_COUNT = "_idx_prio_count"
INDEX_TABLES = (
    IDX_ALLOCS_NODE, IDX_ALLOCS_JOB, IDX_ALLOCS_EVAL, IDX_NODE_USED,
    IDX_PRIO_COUNT,
)


def usage_contribution(alloc) -> "Optional[tuple[int, int, int, int]]":
    """What this alloc adds to its node's committed usage: (cpu, memory_mb,
    disk_mb, complex) — None for terminal allocs (they hold nothing, the
    same rule allocs_fit applies). complex=1 when the alloc carries
    reserved cores or network/port reservations."""
    if alloc.terminal_status():
        return None
    r = alloc.comparable_resources()
    cx = 0
    ar = alloc.resources
    if ar is not None:
        if ar.shared_networks:
            cx = 1
        else:
            for tr in ar.tasks.values():
                if tr.reserved_cores or tr.networks:
                    cx = 1
                    break
    return (r.cpu, r.memory_mb, r.disk_mb, cx)


def _usage_add(ut: dict, node_id: str, c) -> None:
    if c is None or not node_id:
        return
    cur = ut.get(node_id)
    if cur is None:
        ut[node_id] = c
    else:
        ut[node_id] = (cur[0] + c[0], cur[1] + c[1], cur[2] + c[2], cur[3] + c[3])


def _usage_sub(ut: dict, node_id: str, c) -> None:
    if c is None or not node_id:
        return
    cur = ut.get(node_id)
    if cur is None:
        return
    nxt = (cur[0] - c[0], cur[1] - c[1], cur[2] - c[2], cur[3] - c[3])
    if nxt == (0, 0, 0, 0):
        del ut[node_id]
    else:
        ut[node_id] = nxt


def rebuild_node_usage(allocs: dict) -> dict:
    """Recompute the per-node usage table from scratch (restore path, and
    the test invariant that the incremental table never drifts)."""
    ut: dict[str, tuple[int, int, int, int]] = {}
    for alloc in allocs.values():
        _usage_add(ut, alloc.node_id, usage_contribution(alloc))
    return ut


def _alloc_priority(alloc) -> int:
    return alloc.job.priority if alloc.job is not None else 50


def _prio_add(pt: dict, alloc, c) -> None:
    """Count a non-terminal alloc (c = its usage contribution; None
    means terminal and uncounted — the same rule the usage table uses)."""
    if c is None:
        return
    p = _alloc_priority(alloc)
    pt[p] = pt.get(p, 0) + 1


def _prio_sub(pt: dict, alloc, c) -> None:
    if c is None:
        return
    p = _alloc_priority(alloc)
    cur = pt.get(p, 0) - 1
    if cur <= 0:
        pt.pop(p, None)
    else:
        pt[p] = cur


def rebuild_prio_counts(allocs: dict) -> dict:
    pt: dict[int, int] = {}
    for alloc in allocs.values():
        _prio_add(pt, alloc, usage_contribution(alloc))
    return pt

JOB_TRACKED_VERSIONS = 6


class JobSummary:
    """Queued/running counts per task group (reference structs.go JobSummary)."""

    def __init__(self, job_id: str, namespace: str) -> None:
        self.job_id = job_id
        self.namespace = namespace
        # group -> {queued, complete, failed, running, starting, lost}
        self.summary: dict[str, dict[str, int]] = {}
        self.children_pending = 0
        self.children_running = 0
        self.children_dead = 0
        self.create_index = 0
        self.modify_index = 0

    def copy(self) -> "JobSummary":
        c = JobSummary(self.job_id, self.namespace)
        c.summary = {g: dict(v) for g, v in self.summary.items()}
        c.children_pending = self.children_pending
        c.children_running = self.children_running
        c.children_dead = self.children_dead
        c.create_index = self.create_index
        c.modify_index = self.modify_index
        return c


class StateSnapshot:
    """A consistent read-only view at one index."""

    def __init__(self, tables: dict[str, dict], indexes: dict[str, int], index: int):
        self._tables = tables
        self._indexes = indexes
        self.index = index

    # -- reads shared with the live store (mixin below) --


def _locked_on_live(fn):
    """Guard for readers that ITERATE a table with a Python-level
    predicate: on the LIVE store (which has a _lock) they must hold it,
    because unshared tables and owned inner index dicts mutate in place —
    a concurrent bulk plan apply would raise 'dict changed size during
    iteration' mid-loop. Snapshots have no _lock and read lock-free (their
    tables are frozen). C-atomic reads (dict.get, list(d.values())) don't
    need this. Apply it to any NEW iterating reader added to the mixin."""

    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        lock = getattr(self, "_lock", None)
        if lock is None:
            return fn(self, *args, **kwargs)
        with lock:
            return fn(self, *args, **kwargs)

    return wrapper


class _ReadMixin:
    _tables: dict[str, dict]

    # nodes ------------------------------------------------------------
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._tables[TABLE_NODES].get(node_id)

    def nodes(self) -> list[Node]:
        return list(self._tables[TABLE_NODES].values())

    def nodes_table_index(self) -> int:
        """Raft index of the last nodes-table write — the O(1)
        invalidation key for node-universe caches (the solver's warm
        ready-node lists): node register/update/drain writes move it,
        alloc and usage writes do not."""
        return self._indexes.get(TABLE_NODES, 0)

    @_locked_on_live
    def nodes_by_prefix(self, prefix: str) -> list[Node]:
        return [n for i, n in self._tables[TABLE_NODES].items() if i.startswith(prefix)]

    # jobs -------------------------------------------------------------
    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self._tables[TABLE_JOBS].get((namespace, job_id))

    @_locked_on_live
    def jobs(self, namespace: Optional[str] = None) -> list[Job]:
        if namespace is None:
            return list(self._tables[TABLE_JOBS].values())
        return [j for (ns, _), j in self._tables[TABLE_JOBS].items() if ns == namespace]

    @_locked_on_live
    def jobs_by_prefix(self, namespace: str, prefix: str) -> list[Job]:
        return [
            j
            for (ns, jid), j in self._tables[TABLE_JOBS].items()
            if ns == namespace and jid.startswith(prefix)
        ]

    def job_version(self, namespace: str, job_id: str, version: int) -> Optional[Job]:
        return self._tables[TABLE_JOB_VERSIONS].get((namespace, job_id, version))

    @_locked_on_live
    def job_versions(self, namespace: str, job_id: str) -> list[Job]:
        out = [
            j
            for (ns, jid, _), j in self._tables[TABLE_JOB_VERSIONS].items()
            if ns == namespace and jid == job_id
        ]
        out.sort(key=lambda j: j.version, reverse=True)
        return out

    @_locked_on_live
    def jobs_by_periodic(self) -> list[Job]:
        return [j for j in self._tables[TABLE_JOBS].values() if j.is_periodic()]

    @_locked_on_live
    def jobs_by_parent(self, namespace: str, parent_id: str) -> list[Job]:
        return [
            j
            for (ns, _), j in self._tables[TABLE_JOBS].items()
            if ns == namespace and j.parent_id == parent_id
        ]

    def job_summary_by_id(self, namespace: str, job_id: str) -> Optional[JobSummary]:
        return self._tables[TABLE_JOB_SUMMARIES].get((namespace, job_id))

    # evals ------------------------------------------------------------
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._tables[TABLE_EVALS].get(eval_id)

    def evals(self) -> list[Evaluation]:
        return list(self._tables[TABLE_EVALS].values())

    @_locked_on_live
    def evals_by_job(self, namespace: str, job_id: str) -> list[Evaluation]:
        return [
            e
            for e in self._tables[TABLE_EVALS].values()
            if e.namespace == namespace and e.job_id == job_id
        ]

    # allocs -----------------------------------------------------------
    #
    # Alloc tables may hold lazy AllocRow handles (SoA placements,
    # structs/placement_batch.py): the read mixin is THE materialization
    # boundary — readers always receive Allocation objects, minted on
    # first access and cached in the owning batch, so repeated reads
    # don't re-pay. Handles never escape the store/event layer.

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        a = self._tables[TABLE_ALLOCS].get(alloc_id)
        return a.get() if a.__class__ is AllocRow else a

    def allocs(self) -> list[Allocation]:
        return [
            a.get() if a.__class__ is AllocRow else a
            for a in list(self._tables[TABLE_ALLOCS].values())
        ]

    def allocs_by_node(self, node_id: str) -> list[Allocation]:
        return [
            a.get() if a.__class__ is AllocRow else a
            for a in list(
                self._tables[IDX_ALLOCS_NODE].get(node_id, {}).values()
            )
        ]

    def node_usage(self, node_id: str) -> tuple[int, int, int, int]:
        """Committed non-terminal usage on one node: (cpu, memory_mb,
        disk_mb, complex_count). Maintained incrementally on every alloc
        write; the plan applier's vectorized verifier reads this instead of
        re-summing the node's allocs. (No lock needed: a single dict.get
        of an immutable tuple.)"""
        return self._tables[IDX_NODE_USED].get(node_id, (0, 0, 0, 0))

    def alloc_priority_tiers(self) -> list[int]:
        """Ascending job priorities that have at least one committed
        non-terminal alloc — the O(1) preemption-possibility signal the
        batch solver gates its aggregate lowering path on."""
        return sorted(self._tables[IDX_PRIO_COUNT])

    @_locked_on_live
    def allocs_by_node_terminal(
        self, node_id: str, terminal: bool
    ) -> list[Allocation]:
        # the terminal predicate answers from the handle's columns (a
        # fresh SoA row is non-terminal by construction); only returned
        # rows materialize
        return [
            a.get() if a.__class__ is AllocRow else a
            for a in self._tables[IDX_ALLOCS_NODE].get(node_id, {}).values()
            if a.terminal_status() == terminal
        ]

    def allocs_by_job(self, namespace: str, job_id: str) -> list[Allocation]:
        return [
            a.get() if a.__class__ is AllocRow else a
            for a in list(
                self._tables[IDX_ALLOCS_JOB]
                .get((namespace, job_id), {})
                .values()
            )
        ]

    def allocs_by_eval(self, eval_id: str) -> list[Allocation]:
        return [
            a.get() if a.__class__ is AllocRow else a
            for a in list(
                self._tables[IDX_ALLOCS_EVAL].get(eval_id, {}).values()
            )
        ]

    @_locked_on_live
    def allocs_by_deployment(self, deployment_id: str) -> list[Allocation]:
        return [
            a.get() if a.__class__ is AllocRow else a
            for a in self._tables[TABLE_ALLOCS].values()
            if a.deployment_id == deployment_id
        ]

    # namespaces -------------------------------------------------------
    def namespace_by_name(self, name: str):
        return self._tables[TABLE_NAMESPACES].get(name)

    def namespaces(self) -> list:
        return list(self._tables[TABLE_NAMESPACES].values())

    # volumes ----------------------------------------------------------
    def volume_by_id(self, namespace: str, vol_id: str):
        return self._tables[TABLE_VOLUMES].get((namespace, vol_id))

    @_locked_on_live
    def volumes(self, namespace: Optional[str] = None) -> list:
        if namespace is None:
            return list(self._tables[TABLE_VOLUMES].values())
        return [
            v
            for (ns, _), v in self._tables[TABLE_VOLUMES].items()
            if ns == namespace
        ]

    @_locked_on_live
    def volumes_by_name(self, namespace: str, name: str) -> list:
        """Volumes satisfying a group volume.source ask."""
        return [
            v
            for (ns, _), v in self._tables[TABLE_VOLUMES].items()
            if ns == namespace and v.name == name
        ]

    # services ---------------------------------------------------------
    @_locked_on_live
    def service_names(self, namespace: Optional[str] = None) -> list[dict]:
        """Catalog summary: one row per service name (reference:
        ServiceRegistrationsByNamespace)."""
        agg: dict[tuple[str, str], dict] = {}
        for reg in self._tables[TABLE_SERVICES].values():
            if namespace is not None and reg.namespace != namespace:
                continue
            row = agg.setdefault(
                (reg.namespace, reg.service_name),
                {
                    "namespace": reg.namespace,
                    "service_name": reg.service_name,
                    "tags": set(),
                    "instances": 0,
                },
            )
            row["tags"].update(reg.tags)
            row["instances"] += 1
        out = [
            {**r, "tags": sorted(r["tags"])}
            for r in agg.values()
        ]
        out.sort(key=lambda r: (r["namespace"], r["service_name"]))
        return out

    @_locked_on_live
    def service_registrations(self, namespace: str, name: str) -> list:
        out = [
            r
            for r in self._tables[TABLE_SERVICES].values()
            if r.namespace == namespace and r.service_name == name
        ]
        out.sort(key=lambda r: r.id)
        return out

    def service_registration_by_id(self, reg_id: str):
        return self._tables[TABLE_SERVICES].get(reg_id)

    # scaling policies -------------------------------------------------
    def scaling_policies(self, namespace: Optional[str] = None) -> list:
        out = [
            p
            for p in self._tables[TABLE_SCALING_POLICIES].values()
            if namespace is None or p.namespace == namespace
        ]
        out.sort(key=lambda p: (p.namespace, p.job_id, p.group))
        return out

    def scaling_policy_by_id(self, policy_id: str):
        return self._tables[TABLE_SCALING_POLICIES].get(policy_id)

    def scaling_events(self, namespace: str, job_id: str) -> dict:
        """group -> [events], newest first (reference JobScalingEvents)."""
        return self._tables[TABLE_SCALING_EVENTS].get(
            (namespace, job_id), {}
        )

    def scaling_policies_by_job(self, namespace: str, job_id: str) -> list:
        return [
            p
            for p in self._tables[TABLE_SCALING_POLICIES].values()
            if p.namespace == namespace and p.job_id == job_id
        ]

    # operator config --------------------------------------------------
    def operator_config(self, key: str):
        return self._tables[TABLE_OPERATOR].get(key)

    # secrets ----------------------------------------------------------
    def secret_by_path(self, namespace: str, path: str):
        return self._tables[TABLE_SECRETS].get((namespace, path))

    @_locked_on_live
    def secrets(self, namespace: Optional[str] = None) -> list:
        if namespace is None:
            return list(self._tables[TABLE_SECRETS].values())
        return [
            e
            for (ns, _), e in self._tables[TABLE_SECRETS].items()
            if ns == namespace
        ]

    @_locked_on_live
    def expired_acl_tokens(self, now_ns_: int) -> list:
        """Tokens past their expiration (the token-gc sweep's read;
        reference: 1.4 ExpiredACLTokenGC)."""
        return [
            t
            for t in self._tables[TABLE_ACL_TOKENS].values()
            if t.expiration_time_ns and t.expiration_time_ns < now_ns_
        ]

    @_locked_on_live
    def services_by_alloc(self, alloc_id: str) -> list:
        return [
            r
            for r in self._tables[TABLE_SERVICES].values()
            if r.alloc_id == alloc_id
        ]

    @_locked_on_live
    def volumes_for_alloc(self, alloc_id: str) -> list:
        """Volumes holding a claim by this alloc (the client's mount hook
        fetches these; reference: CSIVolume.Get per claimed volume)."""
        return [
            v
            for v in self._tables[TABLE_VOLUMES].values()
            if alloc_id in v.claims
        ]

    @_locked_on_live
    def csi_plugins(self) -> dict[str, dict]:
        """Aggregate CSI plugin health across nodes (reference: the
        CSIPlugin table nomad/state/state_store.go maintains on node
        updates; here computed at read time from the nodes table)."""
        out: dict[str, dict] = {}
        for node in self._tables[TABLE_NODES].values():
            for plugin_id, info in node.csi_plugins.items():
                agg = out.setdefault(plugin_id, {
                    "id": plugin_id,
                    "version": info.get("version", ""),
                    "controllers_healthy": 0,
                    "controllers_expected": 0,
                    "nodes_healthy": 0,
                    "nodes_expected": 0,
                })
                healthy = bool(info.get("healthy"))
                if info.get("controller"):
                    agg["controllers_expected"] += 1
                    agg["controllers_healthy"] += int(healthy)
                if info.get("node", True):
                    agg["nodes_expected"] += 1
                    agg["nodes_healthy"] += int(healthy)
                if info.get("version"):
                    agg["version"] = info["version"]
        return out

    # deployments ------------------------------------------------------
    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self._tables[TABLE_DEPLOYMENTS].get(deployment_id)

    def deployments(self) -> list[Deployment]:
        return list(self._tables[TABLE_DEPLOYMENTS].values())

    @_locked_on_live
    def deployments_by_job(self, namespace: str, job_id: str) -> list[Deployment]:
        return [
            d
            for d in self._tables[TABLE_DEPLOYMENTS].values()
            if d.namespace == namespace and d.job_id == job_id
        ]

    @_locked_on_live
    def latest_deployment_by_job(
        self, namespace: str, job_id: str
    ) -> Optional[Deployment]:
        best = None
        for d in self._tables[TABLE_DEPLOYMENTS].values():
            if d.namespace == namespace and d.job_id == job_id:
                if best is None or d.create_index > best.create_index:
                    best = d
        return best


class StateSnapshotImpl(StateSnapshot, _ReadMixin):
    pass


class StateStore(_ReadMixin):
    def __init__(self) -> None:
        self._tables: dict[str, dict] = {t: {} for t in ALL_TABLES + INDEX_TABLES}
        self._indexes: dict[str, int] = {t: 0 for t in ALL_TABLES}
        self._latest_index = 0
        self._shared: set[str] = set()
        # Inner-index COW ownership: (table, key) pairs whose inner
        # {alloc_id: Allocation} dict is exclusively owned by the live
        # store (no snapshot shares it) and may be mutated in place.
        # Cleared whenever a snapshot is taken. Without this, every index
        # insert copies the inner dict — O(n²) across a bulk plan apply.
        self._idx_owned: set[tuple[str, object]] = set()
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        # Event hooks: called under lock with
        # (index, table, list-of-objects, event-type). The event type mirrors
        # the reference's raft-message-derived stream event types
        # (nomad/state/events.go eventFromChange).
        self._subscribers: list[Callable[[int, str, list, str], None]] = []
        # Restore hooks: called under lock AFTER a snapshot restore (or
        # index rebase) replaces the tables, with (index, alloc node-ids).
        # Separate from _subscribers so internal watch routers can
        # re-prime without emitting synthetic stream events — external
        # stream consumers re-subscribe after a restore, as in the
        # reference.
        self._restore_subs: list[Callable[[int, set], None]] = []

    # -- snapshot / watch ----------------------------------------------

    def snapshot(self) -> StateSnapshotImpl:
        with self._lock:
            self._shared.update(ALL_TABLES + INDEX_TABLES)
            self._idx_owned.clear()
            return StateSnapshotImpl(
                dict(self._tables), dict(self._indexes), self._latest_index
            )

    def latest_index(self) -> int:
        with self._lock:
            return self._latest_index

    def table_index(self, *tables: str) -> int:
        with self._lock:
            return max(self._indexes[t] for t in tables)

    def snapshot_min_index(
        self, index: int, timeout_s: float = 5.0
    ) -> StateSnapshotImpl:
        """Block until the store has applied `index`, then snapshot.

        Reference: nomad/worker.go:228 snapshotMinIndex /
        state_store.go SnapshotMinIndex.
        """
        deadline = now_ns() + int(timeout_s * 1e9)
        with self._cv:
            while self._latest_index < index:
                remaining = (deadline - now_ns()) / 1e9
                if remaining <= 0:
                    raise TimeoutError(
                        f"timed out waiting for index {index} (at {self._latest_index})"
                    )
                self._cv.wait(remaining)
        return self.snapshot()

    def wait_for_index(
        self, tables: Iterable[str], min_index: int, timeout_s: float = 30.0
    ) -> int:
        """Block until any of `tables` reaches min_index (blocking query)."""
        tables = list(tables)
        deadline = now_ns() + int(timeout_s * 1e9)
        with self._cv:
            while True:
                cur = max(self._indexes[t] for t in tables)
                if cur >= min_index:
                    return cur
                remaining = (deadline - now_ns()) / 1e9
                if remaining <= 0:
                    return cur
                self._cv.wait(remaining)

    def subscribe(self, fn: Callable[[int, str, list, str], None]) -> None:
        self._subscribers.append(fn)

    def subscribe_restore(self, fn: Callable[[int, set], None]) -> None:
        self._restore_subs.append(fn)

    def _notify_restore(self) -> None:
        """Caller holds the lock: hand restore hooks the rebased index
        plus every node that owns allocs in the restored world."""
        if not self._restore_subs:
            return
        node_ids = {
            getattr(a, "node_id", "")
            for a in self._tables[TABLE_ALLOCS].values()
        }
        node_ids.discard("")
        for fn in self._restore_subs:
            fn(self._latest_index, node_ids)

    # -- ACL -----------------------------------------------------------

    def upsert_acl_policies(self, index: int, policies: list) -> None:
        with self._lock:
            t = self._wtable(TABLE_ACL_POLICIES)
            for pol in policies:
                pol = pol.copy()
                existing = t.get(pol.name)
                pol.create_index = existing.create_index if existing else index
                pol.modify_index = index
                t[pol.name] = pol
            self._stamp(index, TABLE_ACL_POLICIES)

    def delete_acl_policies(self, index: int, names: list[str]) -> None:
        with self._lock:
            t = self._wtable(TABLE_ACL_POLICIES)
            for name in names:
                t.pop(name, None)
            self._stamp(index, TABLE_ACL_POLICIES)

    def acl_policy_by_name(self, name: str):
        return self._tables[TABLE_ACL_POLICIES].get(name)

    def acl_policies(self) -> list:
        return list(self._tables[TABLE_ACL_POLICIES].values())

    def upsert_acl_tokens(self, index: int, tokens: list) -> None:
        with self._lock:
            t = self._wtable(TABLE_ACL_TOKENS)
            for tok in tokens:
                tok = tok.copy()
                existing = t.get(tok.accessor_id)
                tok.create_index = existing.create_index if existing else index
                tok.modify_index = index
                t[tok.accessor_id] = tok
            self._stamp(index, TABLE_ACL_TOKENS)

    def delete_acl_tokens(self, index: int, accessor_ids: list[str]) -> None:
        with self._lock:
            t = self._wtable(TABLE_ACL_TOKENS)
            for aid in accessor_ids:
                t.pop(aid, None)
            self._stamp(index, TABLE_ACL_TOKENS)

    def acl_token_by_accessor(self, accessor_id: str):
        return self._tables[TABLE_ACL_TOKENS].get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        # Locked: iterates a live table with a Python predicate (see the
        # _locked_reader note at the bottom of this module).
        with self._lock:
            for tok in self._tables[TABLE_ACL_TOKENS].values():
                if tok.secret_id == secret_id:
                    return tok
            return None

    def acl_tokens(self) -> list:
        return list(self._tables[TABLE_ACL_TOKENS].values())

    def acl_has_management_token(self) -> bool:
        with self._lock:
            return any(
                t.type == "management"
                for t in self._tables[TABLE_ACL_TOKENS].values()
            )

    # -- snapshot persistence ------------------------------------------

    def serialize(self) -> bytes:
        """Full-state snapshot bytes (reference fsm.go:1860 Persist streams
        every table; here one codec blob — tables, indexes, latest)."""
        from .. import codec

        with self._lock:
            self._materialize_rows_locked()
            return codec.pack(
                {
                    "tables": self._tables,
                    "indexes": self._indexes,
                    "latest": self._latest_index,
                }
            )

    def _materialize_rows_locked(self) -> None:
        """Swap any lazy AllocRow handles for their materialized rows in
        place, so the native encoder sees only registered structs. A
        handle and its cached row are the same logical value (snapshot
        readers holding either see identical state), so the in-place
        swap is COW-safe — it is a representation change, not a write."""
        t = self._tables[TABLE_ALLOCS]
        lazy = [
            (k, v) for k, v in t.items() if v.__class__ is AllocRow
        ]
        if not lazy:
            return
        for k, v in lazy:
            t[k] = v.get()
        for table in (IDX_ALLOCS_NODE, IDX_ALLOCS_JOB, IDX_ALLOCS_EVAL):
            for inner in self._tables[table].values():
                for k in list(inner):
                    v = inner[k]
                    if v.__class__ is AllocRow:
                        inner[k] = v.get()

    def restore_from(self, raw: bytes) -> None:
        """Replace all state from snapshot bytes (reference fsm.go:1381
        Restore). Watchers are woken; subscribers are NOT replayed — stream
        consumers must re-subscribe after a restore, as in the reference."""
        from .. import codec

        data = codec.unpack(raw)
        # Forward compatibility: snapshots from before a table existed
        # restore with that table empty instead of KeyError-ing later.
        for t in ALL_TABLES + INDEX_TABLES:
            data["tables"].setdefault(t, {})
        for t in ALL_TABLES:
            data["indexes"].setdefault(t, 0)
        # The usage table's tuple values round-trip as lists through the
        # codec; rebuild from the allocs table rather than trusting them.
        data["tables"][IDX_NODE_USED] = rebuild_node_usage(
            data["tables"][TABLE_ALLOCS]
        )
        data["tables"][IDX_PRIO_COUNT] = rebuild_prio_counts(
            data["tables"][TABLE_ALLOCS]
        )
        with self._cv:
            self._tables = data["tables"]
            self._indexes = data["indexes"]
            self._latest_index = data["latest"]
            self._shared = set()
            self._idx_owned.clear()
            self._notify_restore()
            self._cv.notify_all()

    def rebase_indexes(self, index: int) -> None:
        """Re-stamp every table index to `index` after an operator
        snapshot restore.

        The snapshot carries the indexes of the CLUSTER IT WAS SAVED
        FROM; the restoring cluster's raft log continues from its own
        position. Without rebasing, a snapshot saved at index 5000
        restored into a cluster at index 4 leaves _latest_index=5000
        while new writes stamp 5,6,... — wait_for_index goes stale and
        blocking queries hang (the reference avoids this by resetting
        raft itself to a post-snapshot index in helper/snapshot)."""
        with self._cv:
            for t in self._indexes:
                self._indexes[t] = index
            self._latest_index = index
            self._notify_restore()
            self._cv.notify_all()

    # -- write plumbing ------------------------------------------------

    def _wtable(self, table: str) -> dict:
        """Copy-on-write fork of a table that a live snapshot may share."""
        if table in self._shared:
            self._tables[table] = dict(self._tables[table])
            self._shared.discard(table)
        return self._tables[table]

    def _stamp(self, index: int, *tables: str) -> None:
        for t in tables:
            self._indexes[t] = index
        if index > self._latest_index:
            self._latest_index = index
        self._cv.notify_all()

    def _publish(
        self, index: int, table: str, objs: list, etype: str = ""
    ) -> None:
        for fn in self._subscribers:
            fn(index, table, objs, etype)

    def _idx_put(self, table: str, key, alloc: Allocation) -> None:
        t = self._wtable(table)
        inner = t.get(key)
        if inner is not None and (table, key) in self._idx_owned:
            inner[alloc.id] = alloc
            return
        inner = dict(inner) if inner is not None else {}
        inner[alloc.id] = alloc
        t[key] = inner
        self._idx_owned.add((table, key))

    def _idx_del(self, table: str, key, alloc_id: str) -> None:
        t = self._wtable(table)
        inner = t.get(key)
        if inner and alloc_id in inner:
            if (table, key) not in self._idx_owned:
                inner = dict(inner)
                self._idx_owned.add((table, key))
            del inner[alloc_id]
            if inner:
                t[key] = inner
            else:
                del t[key]
                self._idx_owned.discard((table, key))

    def _put_alloc(self, alloc: Allocation, existing: Optional[Allocation]) -> None:
        """Insert an alloc into the main table and every secondary index."""
        self._wtable(TABLE_ALLOCS)[alloc.id] = alloc
        ut = self._wtable(IDX_NODE_USED)
        pt = self._wtable(IDX_PRIO_COUNT)
        if existing is not None:
            ce = usage_contribution(existing)
            _usage_sub(ut, existing.node_id, ce)
            _prio_sub(pt, existing, ce)
        ca = usage_contribution(alloc)
        _usage_add(ut, alloc.node_id, ca)
        _prio_add(pt, alloc, ca)
        if existing is not None:
            if existing.node_id != alloc.node_id:
                self._idx_del(IDX_ALLOCS_NODE, existing.node_id, alloc.id)
            if (existing.namespace, existing.job_id) != (alloc.namespace, alloc.job_id):
                self._idx_del(
                    IDX_ALLOCS_JOB, (existing.namespace, existing.job_id), alloc.id
                )
            if existing.eval_id != alloc.eval_id:
                self._idx_del(IDX_ALLOCS_EVAL, existing.eval_id, alloc.id)
        self._idx_put(IDX_ALLOCS_NODE, alloc.node_id, alloc)
        self._idx_put(IDX_ALLOCS_JOB, (alloc.namespace, alloc.job_id), alloc)
        self._idx_put(IDX_ALLOCS_EVAL, alloc.eval_id, alloc)

    def _del_alloc(self, alloc_id: str) -> None:
        t = self._wtable(TABLE_ALLOCS)
        alloc = t.pop(alloc_id, None)
        if alloc is not None:
            c = usage_contribution(alloc)
            _usage_sub(self._wtable(IDX_NODE_USED), alloc.node_id, c)
            _prio_sub(self._wtable(IDX_PRIO_COUNT), alloc, c)
            self._idx_del(IDX_ALLOCS_NODE, alloc.node_id, alloc_id)
            self._idx_del(IDX_ALLOCS_JOB, (alloc.namespace, alloc.job_id), alloc_id)
            self._idx_del(IDX_ALLOCS_EVAL, alloc.eval_id, alloc_id)

    # -- nodes ---------------------------------------------------------

    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            t = self._wtable(TABLE_NODES)
            existing = t.get(node.id)
            node = node.copy()
            if existing is not None:
                node.create_index = existing.create_index
                # Server-owned lifecycle state survives client
                # re-registration (reference state_store.go UpsertNode:
                # "Retain node events... transfer the drain/eligibility"):
                # a periodic re-fingerprint must not erase an operator's
                # drain or flip a ready node back to initializing.
                node.drain_strategy = existing.drain_strategy
                node.scheduling_eligibility = existing.scheduling_eligibility
                if existing.status:
                    node.status = existing.status
                    node.status_updated_at = existing.status_updated_at
            else:
                node.create_index = index
            node.modify_index = index
            node.canonicalize()
            t[node.id] = node
            self._stamp(index, TABLE_NODES)
            self._publish(index, TABLE_NODES, [node], "NodeRegistration")

    def upsert_nodes(self, index: int, nodes: list) -> None:
        """Bulk ``upsert_node``: one lock hold, one index stamp, one
        published event block for the whole batch — the store half of
        the batched node-register raft entry (a 10k-node reconnect
        storm commits as a bounded number of entries, each landing
        here once)."""
        with self._lock:
            t = self._wtable(TABLE_NODES)
            upserted = []
            for node in nodes:
                existing = t.get(node.id)
                node = node.copy()
                if existing is not None:
                    node.create_index = existing.create_index
                    node.drain_strategy = existing.drain_strategy
                    node.scheduling_eligibility = (
                        existing.scheduling_eligibility
                    )
                    if existing.status:
                        node.status = existing.status
                        node.status_updated_at = existing.status_updated_at
                else:
                    node.create_index = index
                node.modify_index = index
                node.canonicalize()
                t[node.id] = node
                upserted.append(node)
            self._stamp(index, TABLE_NODES)
            self._publish(index, TABLE_NODES, upserted, "NodeRegistration")

    def update_node_statuses(
        self, index: int, node_ids: list, status: str
    ) -> None:
        """Bulk ``update_node_status``: the store half of the batched
        down-mark raft entry a heartbeat-wheel expiry storm commits.
        Unknown ids are skipped (a node purged between expiry and
        apply), not an error — the batch must land for the rest."""
        with self._lock:
            t = self._wtable(TABLE_NODES)
            updated = []
            stamp = now_ns()
            for node_id in node_ids:
                existing = t.get(node_id)
                if existing is None:
                    continue
                node = existing.copy()
                node.status = status
                node.status_updated_at = stamp
                node.modify_index = index
                t[node_id] = node
                updated.append(node)
            if updated:
                self._stamp(index, TABLE_NODES)
                self._publish(
                    index, TABLE_NODES, updated, "NodeStatusUpdate"
                )

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            t = self._wtable(TABLE_NODES)
            node = t.get(node_id)
            if node is not None:
                del t[node_id]
                self._stamp(index, TABLE_NODES)
                self._publish(index, TABLE_NODES, [node], "NodeDeregistration")

    def update_node_status(self, index: int, node_id: str, status: str) -> None:
        with self._lock:
            t = self._wtable(TABLE_NODES)
            existing = t.get(node_id)
            if existing is None:
                raise KeyError(f"node {node_id} not found")
            node = existing.copy()
            node.status = status
            node.status_updated_at = now_ns()
            node.modify_index = index
            t[node_id] = node
            self._stamp(index, TABLE_NODES)
            self._publish(index, TABLE_NODES, [node], "NodeStatusUpdate")

    def update_node_drain(
        self,
        index: int,
        node_id: str,
        drain: Optional[DrainStrategy],
        mark_eligible: bool = False,
    ) -> None:
        with self._lock:
            t = self._wtable(TABLE_NODES)
            existing = t.get(node_id)
            if existing is None:
                raise KeyError(f"node {node_id} not found")
            node = existing.copy()
            node.drain_strategy = drain.copy() if drain is not None else None
            if drain is not None:
                # Stamp the wall-clock force deadline once, at drain time
                # (reference structs.go DrainStrategy.DeadlineTime).
                if drain.deadline_s > 0 and not node.drain_strategy.force_deadline_ns:
                    node.drain_strategy.force_deadline_ns = now_ns() + int(
                        drain.deadline_s * 1e9
                    )
                node.scheduling_eligibility = NODE_SCHEDULING_INELIGIBLE
            elif mark_eligible:
                node.scheduling_eligibility = NODE_SCHEDULING_ELIGIBLE
            node.modify_index = index
            t[node_id] = node
            self._stamp(index, TABLE_NODES)
            self._publish(index, TABLE_NODES, [node], "NodeDrain")

    def update_node_eligibility(
        self, index: int, node_id: str, eligibility: str
    ) -> None:
        with self._lock:
            t = self._wtable(TABLE_NODES)
            existing = t.get(node_id)
            if existing is None:
                raise KeyError(f"node {node_id} not found")
            if existing.drain_strategy is not None and (
                eligibility == NODE_SCHEDULING_ELIGIBLE
            ):
                raise ValueError("can't make draining node eligible")
            node = existing.copy()
            node.scheduling_eligibility = eligibility
            node.modify_index = index
            t[node_id] = node
            self._stamp(index, TABLE_NODES)
            self._publish(index, TABLE_NODES, [node], "NodeEligibilityUpdate")

    # -- jobs ----------------------------------------------------------

    def upsert_job(self, index: int, job: Job, keep_version: bool = False) -> None:
        with self._lock:
            self._upsert_job_txn(index, job, keep_version)
            self._sync_scaling_policies_txn(index, job)
            self._stamp(index, TABLE_JOBS, TABLE_JOB_VERSIONS, TABLE_JOB_SUMMARIES)
            self._publish(
                index,
                TABLE_JOBS,
                [self._tables[TABLE_JOBS][job.ns_id()]],
                "JobRegistered",
            )

    def _upsert_job_txn(self, index: int, job: Job, keep_version: bool = False) -> None:
        t = self._wtable(TABLE_JOBS)
        job = job.copy()
        existing = t.get(job.ns_id())
        if existing is not None:
            job.create_index = existing.create_index
            job.job_modify_index = index
            if keep_version:
                job.version = existing.version
            elif job.specification_changed(existing):
                job.version = existing.version + 1
            else:
                job.version = existing.version
        else:
            job.create_index = index
            job.job_modify_index = index
            job.version = 0
        job.modify_index = index
        if job.status not in (JOB_STATUS_PENDING, JOB_STATUS_RUNNING, JOB_STATUS_DEAD):
            job.status = JOB_STATUS_PENDING
        if job.stop:
            job.status = JOB_STATUS_DEAD
        t[job.ns_id()] = job
        # version history
        vt = self._wtable(TABLE_JOB_VERSIONS)
        vt[(job.namespace, job.id, job.version)] = job
        versions = sorted(
            (k for k in vt if k[0] == job.namespace and k[1] == job.id),
            key=lambda k: k[2],
            reverse=True,
        )
        for stale in versions[JOB_TRACKED_VERSIONS:]:
            del vt[stale]
        # summary
        st = self._wtable(TABLE_JOB_SUMMARIES)
        summary = st.get(job.ns_id())
        summary = summary.copy() if summary else JobSummary(job.id, job.namespace)
        if summary.create_index == 0:
            summary.create_index = index
        for tg in job.task_groups:
            summary.summary.setdefault(
                tg.name,
                {
                    "queued": 0,
                    "complete": 0,
                    "failed": 0,
                    "running": 0,
                    "starting": 0,
                    "lost": 0,
                },
            )
        summary.modify_index = index
        st[job.ns_id()] = summary

    def _sync_scaling_policies_txn(self, index: int, job) -> None:
        """Keep the scaling-policy table in lockstep with the job's
        scaling stanzas (reference: UpsertJob upserts/deletes policies
        for the job's groups, state_store.go updateJobScalingPolicies).
        Deterministic ids (ns/job/group) so re-registration updates in
        place."""
        t = self._wtable(TABLE_SCALING_POLICIES)
        wanted: dict[str, object] = {}
        for tg in job.task_groups:
            if tg.scaling is None:
                continue
            pol = tg.scaling.copy()
            pol.id = f"{job.namespace}/{job.id}/{tg.name}"
            pol.namespace = job.namespace
            pol.job_id = job.id
            pol.group = tg.name
            existing = t.get(pol.id)
            pol.create_index = existing.create_index if existing else index
            pol.modify_index = index
            wanted[pol.id] = pol
        stale = [
            pid
            for pid, p in t.items()
            if p.namespace == job.namespace
            and p.job_id == job.id
            and pid not in wanted
        ]
        changed = bool(wanted) or bool(stale)
        for pid in stale:
            del t[pid]
        t.update(wanted)
        if changed:
            self._stamp(index, TABLE_SCALING_POLICIES)

    def reconcile_job_summaries(self, index: int) -> int:
        """Rebuild every job summary from the alloc table (reference
        state_store.go ReconcileJobSummaries — `system reconcile
        summaries` repairs drifted counters). Returns jobs recomputed."""
        with self._lock:
            st = self._wtable(TABLE_JOB_SUMMARIES)
            jobs = dict(self._tables[TABLE_JOBS])
            per_job: dict[tuple, dict[str, dict[str, int]]] = {}
            for alloc in self._tables[TABLE_ALLOCS].values():
                key = (alloc.namespace, alloc.job_id)
                if key not in jobs:
                    continue
                groups = per_job.setdefault(key, {})
                c = groups.setdefault(
                    alloc.task_group,
                    {
                        "queued": 0,
                        "complete": 0,
                        "failed": 0,
                        "running": 0,
                        "starting": 0,
                        "lost": 0,
                    },
                )
                status = alloc.client_status
                if alloc.server_terminal_status() and status not in (
                    ALLOC_CLIENT_STATUS_COMPLETE,
                    ALLOC_CLIENT_STATUS_FAILED,
                    ALLOC_CLIENT_STATUS_LOST,
                ):
                    continue  # stopping: counted nowhere, like fresh GC
                if status == ALLOC_CLIENT_STATUS_RUNNING:
                    c["running"] += 1
                elif status == ALLOC_CLIENT_STATUS_COMPLETE:
                    c["complete"] += 1
                elif status == ALLOC_CLIENT_STATUS_FAILED:
                    c["failed"] += 1
                elif status == ALLOC_CLIENT_STATUS_LOST:
                    c["lost"] += 1
                else:
                    c["starting"] += 1
            for key, job in jobs.items():
                old = st.get(key)
                summary = JobSummary(job.id, job.namespace)
                summary.create_index = old.create_index if old else index
                summary.modify_index = index
                summary.summary = per_job.get(key, {})
                for tg in job.task_groups:
                    summary.summary.setdefault(
                        tg.name,
                        {
                            "queued": 0,
                            "complete": 0,
                            "failed": 0,
                            "running": 0,
                            "starting": 0,
                            "lost": 0,
                        },
                    )
                if old is not None:
                    summary.children_pending = old.children_pending
                    summary.children_running = old.children_running
                    summary.children_dead = old.children_dead
                st[key] = summary
            self._stamp(index, TABLE_JOB_SUMMARIES)
            return len(jobs)

    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            t = self._wtable(TABLE_JOBS)
            job = t.get((namespace, job_id))
            if job is not None:
                del t[(namespace, job_id)]
            vt = self._wtable(TABLE_JOB_VERSIONS)
            for k in [k for k in vt if k[0] == namespace and k[1] == job_id]:
                del vt[k]
            st = self._wtable(TABLE_JOB_SUMMARIES)
            st.pop((namespace, job_id), None)
            sp = self._wtable(TABLE_SCALING_POLICIES)
            for pid in [
                pid
                for pid, p in sp.items()
                if p.namespace == namespace and p.job_id == job_id
            ]:
                del sp[pid]
            self._wtable(TABLE_SCALING_EVENTS).pop(
                (namespace, job_id), None
            )
            self._stamp(
                index, TABLE_JOBS, TABLE_JOB_VERSIONS,
                TABLE_JOB_SUMMARIES, TABLE_SCALING_POLICIES,
                TABLE_SCALING_EVENTS,
            )
            if job is not None:
                self._publish(index, TABLE_JOBS, [job], "JobDeregistered")

    # -- evals ---------------------------------------------------------

    def upsert_evals(self, index: int, evals: list[Evaluation]) -> None:
        with self._lock:
            stored = self._upsert_evals_txn(index, evals)
            self._stamp(index, TABLE_EVALS)
            self._publish(index, TABLE_EVALS, stored, "EvaluationUpdated")

    def _upsert_evals_txn(self, index: int, evals: list[Evaluation]) -> list[Evaluation]:
        t = self._wtable(TABLE_EVALS)
        jobs_touched: set[tuple[str, str]] = set()
        stored: list[Evaluation] = []
        for ev in evals:
            ev = ev.copy()
            existing = t.get(ev.id)
            ev.create_index = existing.create_index if existing else index
            ev.modify_index = index
            t[ev.id] = ev
            stored.append(ev)
            jobs_touched.add((ev.namespace, ev.job_id))
            # Blocked-eval dedup: cancel older blocked evals for the same job.
            if ev.status == EVAL_STATUS_BLOCKED:
                for other in list(t.values()):
                    if (
                        other.id != ev.id
                        and other.job_id == ev.job_id
                        and other.namespace == ev.namespace
                        and other.status == EVAL_STATUS_BLOCKED
                        and other.modify_index < index
                    ):
                        c = other.copy()
                        c.status = "canceled"
                        c.status_description = (
                            f"evaluation {ev.id} successfully blocked"
                        )
                        c.modify_index = index
                        t[other.id] = c
                        stored.append(c)
        for ns, job_id in jobs_touched:
            self._update_job_status_txn(index, ns, job_id)
        return stored

    # reference structs.go JobTrackedScalingEvents = 20
    SCALING_EVENTS_TRACKED = 20

    def upsert_scaling_event(
        self, index: int, namespace: str, job_id: str, group: str,
        event: dict,
    ) -> None:
        """Append one scale event, bounded per group (reference
        state_store.go UpsertScalingEvent keeps the newest
        JobTrackedScalingEvents = 20)."""
        with self._lock:
            t = self._wtable(TABLE_SCALING_EVENTS)
            key = (namespace, job_id)
            cur = t.get(key) or {}
            fresh = {g: list(evs) for g, evs in cur.items()}
            evs = fresh.setdefault(group, [])
            evs.insert(0, dict(event))
            del evs[self.SCALING_EVENTS_TRACKED:]
            t[key] = fresh
            self._stamp(index, TABLE_SCALING_EVENTS)

    def delete_evals(self, index: int, eval_ids: list[str], alloc_ids: list[str]) -> None:
        with self._lock:
            t = self._wtable(TABLE_EVALS)
            gone_evals = [t.pop(eid) for eid in eval_ids if eid in t]
            gone_allocs = [
                a
                for aid in alloc_ids
                if (a := self._tables[TABLE_ALLOCS].get(aid)) is not None
            ]
            for aid in alloc_ids:
                self._del_alloc(aid)
            self._stamp(index, TABLE_EVALS, TABLE_ALLOCS)
            if gone_evals:
                self._publish(index, TABLE_EVALS, gone_evals, "EvaluationDeleted")
            if gone_allocs:
                self._publish(index, TABLE_ALLOCS, gone_allocs, "AllocationDeleted")

    # -- allocs --------------------------------------------------------

    def upsert_allocs(self, index: int, allocs: list[Allocation]) -> None:
        with self._lock:
            stored = self._upsert_allocs_txn(index, allocs)
            self._stamp(index, TABLE_ALLOCS, TABLE_JOB_SUMMARIES)
            self._publish(index, TABLE_ALLOCS, stored, "AllocationUpdated")

    def _upsert_allocs_txn(
        self,
        index: int,
        allocs: list[Allocation],
        owned: bool = False,
        default_job: Optional[Job] = None,
        default_jobs: Optional[dict] = None,
    ) -> list[Allocation]:
        """owned=True transfers ownership of the alloc objects to the store:
        no defensive copy is made and index/time fields are stamped in
        place. Only valid for allocs the caller minted for this write and
        will not mutate afterwards (the plan-apply path: every alloc in a
        submitted Plan is a plan-owned copy or freshly minted — see
        Plan.append_fresh_alloc). At c2m scale the per-alloc copy is the
        single largest cost of applying a plan (VERDICT r2 weak #2).

        Even when owned, allocs matching an EXISTING row are copied before
        the client-state merge below: with leader-direct raft apply the
        submitted objects are concurrently visible to the plan applier's
        OverlaySnapshot, and while index stamps and job re-attachment are
        invisible to its verification math (it reads statuses and
        resources only), the existing-row merge rewrites client_status /
        task_states — those must never mutate under a concurrent reader.
        Fresh inserts (the ~10^5-alloc bulk of a c2m plan) stay
        zero-copy.

        default_jobs — the merged-plan form of default_job: a
        {(namespace, job_id): Job} map when one bulk upsert carries
        allocs scheduled against SEVERAL plans' job versions (the
        batched plan apply commits N same-snapshot plans in one
        transaction)."""
        t = self._wtable(TABLE_ALLOCS)
        jobs_touched: set[tuple[str, str]] = set()
        # (ns, job) -> {task_group: fresh insert count}: jobs whose touched
        # allocs were ALL fresh non-terminal inserts take an O(1) summary
        # increment instead of the full per-alloc rescan.
        fresh_counts: dict[tuple[str, str], dict[str, int]] = {}
        full_jobs: set[tuple[str, str]] = set()
        stored: list[Allocation] = []
        now = now_ns()
        # Per-txn cache of owned inner index dicts: one ownership check per
        # distinct key instead of three per alloc (bulk plans insert ~10³-10⁵
        # allocs that share one job/eval key and a few thousand node keys).
        # The COW/ownership protocol itself lives in _owned_inner — ONE
        # implementation shared with the batch txn.
        inner_cache: dict[tuple[str, object], dict] = {}

        def _inner(table: str, key) -> dict:
            ck = (table, key)
            inner = inner_cache.get(ck)
            if inner is None:
                inner = inner_cache[ck] = self._owned_inner(table, key)
            return inner

        ut = self._wtable(IDX_NODE_USED)
        pt = self._wtable(IDX_PRIO_COUNT)
        if default_jobs is None:
            default_jobs = (
                {(default_job.namespace, default_job.id): default_job}
                if default_job is not None
                else {}
            )
        # Usage-contribution memo: the batch solver's fast-mint path shares
        # ONE AllocatedResources object across a whole group's fresh allocs
        # (solver._materialize_compact), so the contribution walk runs once
        # per distinct (resources, status) instead of once per alloc.
        contrib_cache: dict[tuple, Optional[tuple]] = {}
        for alloc in allocs:
            existing = t.get(alloc.id)
            if not owned or existing is not None:
                alloc = alloc.copy()
            # Plan payloads are denormalized: allocs scheduled against the
            # plan's job version carry job=None and re-attach to it here —
            # BEFORE the existing-alloc fallback, which holds the OLD
            # version and would revert in-place updates.
            if alloc.job is None and default_jobs:
                alloc.job = default_jobs.get(
                    (alloc.namespace, alloc.job_id)
                )
            if existing is not None:
                alloc.create_index = existing.create_index
                alloc.create_time = existing.create_time
                if alloc.job is None:
                    alloc.job = existing.job
                # Client-reported state survives server-side updates.
                if not alloc.task_states and existing.task_states:
                    alloc.task_states = {
                        k: v.copy() for k, v in existing.task_states.items()
                    }
                if alloc.client_status == "pending" and existing.client_status not in (
                    "",
                    "pending",
                ):
                    alloc.client_status = existing.client_status
                    alloc.client_description = existing.client_description
            else:
                alloc.create_index = index
                if not alloc.create_time:
                    alloc.create_time = now
            alloc.modify_index = index
            alloc.modify_time = now
            if alloc.job is None:
                alloc.job = self._tables[TABLE_JOBS].get(
                    (alloc.namespace, alloc.job_id)
                )
            if existing is not None:
                if existing.node_id != alloc.node_id:
                    self._idx_del(IDX_ALLOCS_NODE, existing.node_id, alloc.id)
                    inner_cache.pop((IDX_ALLOCS_NODE, existing.node_id), None)
                old_key = (existing.namespace, existing.job_id)
                if old_key != (alloc.namespace, alloc.job_id):
                    self._idx_del(IDX_ALLOCS_JOB, old_key, alloc.id)
                    inner_cache.pop((IDX_ALLOCS_JOB, old_key), None)
                if existing.eval_id != alloc.eval_id:
                    self._idx_del(IDX_ALLOCS_EVAL, existing.eval_id, alloc.id)
                    inner_cache.pop((IDX_ALLOCS_EVAL, existing.eval_id), None)
            if existing is not None:
                ce = usage_contribution(existing)
                _usage_sub(ut, existing.node_id, ce)
                _prio_sub(pt, existing, ce)
            ar = alloc.resources
            if ar is not None:
                ck2 = (id(ar), alloc.desired_status, alloc.client_status)
                c = contrib_cache.get(ck2)
                if c is None and ck2 not in contrib_cache:
                    c = contrib_cache[ck2] = usage_contribution(alloc)
            else:
                c = usage_contribution(alloc)
            _usage_add(ut, alloc.node_id, c)
            _prio_add(pt, alloc, c)
            t[alloc.id] = alloc
            _inner(IDX_ALLOCS_NODE, alloc.node_id)[alloc.id] = alloc
            key = (alloc.namespace, alloc.job_id)
            _inner(IDX_ALLOCS_JOB, key)[alloc.id] = alloc
            _inner(IDX_ALLOCS_EVAL, alloc.eval_id)[alloc.id] = alloc
            stored.append(alloc)
            jobs_touched.add(key)
            # inlined: with client_status "pending" (non-terminal),
            # terminal_status() reduces to the desired-status check
            if (
                existing is None
                and alloc.client_status == "pending"
                and alloc.desired_status != ALLOC_DESIRED_STATUS_STOP
                and alloc.desired_status != ALLOC_DESIRED_STATUS_EVICT
            ):
                groups = fresh_counts.setdefault(key, {})
                groups[alloc.task_group] = groups.get(alloc.task_group, 0) + 1
            else:
                full_jobs.add(key)
        self._reconcile_summaries_txn(index, full_jobs)
        inc_jobs = [k for k in fresh_counts if k not in full_jobs]
        if inc_jobs:
            st = self._wtable(TABLE_JOB_SUMMARIES)
            for key in inc_jobs:
                ns, jid = key
                summary = st.get(key)
                summary = summary.copy() if summary else JobSummary(jid, ns)
                for g, delta in fresh_counts[key].items():
                    c = summary.summary.setdefault(
                        g,
                        {
                            "queued": 0,
                            "complete": 0,
                            "failed": 0,
                            "running": 0,
                            "starting": 0,
                            "lost": 0,
                        },
                    )
                    c["starting"] += delta
                summary.modify_index = index
                st[key] = summary
        for ns, job_id in jobs_touched:
            self._update_job_status_txn(index, ns, job_id)
        return stored

    @staticmethod
    def _store_rows_py(
        ids: list,
        handles: list,
        idx_list: list,
        main_t: dict,
        job_inner: dict,
        eval_inner: dict,
        node_inners: dict,
    ) -> None:
        """Pure-Python fallback for fastpack.store_rows: group rows per
        node, preserving row order within a node and first-touch node
        order — the exact insertion sequence the eager txn produces
        from a node_allocation dict, so the two paths build
        byte-identical tables (the identity battery serializes and
        compares)."""
        per_node: dict[int, list] = {}
        for uid, h, ti in zip(ids, handles, idx_list):
            bucket = per_node.get(ti)
            if bucket is None:
                bucket = per_node[ti] = []
            bucket.append((uid, h))
        for ti, bucket in per_node.items():
            node_inner = node_inners[ti]
            for uid, h in bucket:
                main_t[uid] = h
                job_inner[uid] = h
                eval_inner[uid] = h
                node_inner[uid] = h

    def _owned_inner(self, table: str, key) -> dict:
        """Writable (ownership-checked) inner index dict — the method
        form of _upsert_allocs_txn's per-txn _inner resolver."""
        tbl = self._wtable(table)
        inner = tbl.get(key)
        if inner is None:
            inner = {}
            tbl[key] = inner
            self._idx_owned.add((table, key))
        elif (table, key) not in self._idx_owned:
            inner = dict(inner)
            tbl[key] = inner
            self._idx_owned.add((table, key))
        return inner

    def _upsert_batches_txn(
        self,
        index: int,
        batches: list[PlacementBatch],
        default_jobs: Optional[dict] = None,
    ) -> list:
        """Insert SoA placement batches: lazy AllocRow handles into the
        main/secondary tables, per-NODE (not per-row) usage-aggregate
        updates from the columns, one priority-count bump and one
        summary increment per batch. Per-row work is exactly the four
        table inserts the id-keyed indexes require — everything the
        eager path did per row beyond that (defensive copy, stamps,
        contribution walk, terminal checks) happens once per batch.

        Rows are all fresh by construction (new uuids; the applier's
        verification preserved that), so the existing-row merge paths
        never apply."""
        from .. import codec

        # native_module never compiles (codec.warm_native is the one
        # sanctioned build point, outside any lock — NV-lock-blocking),
        # so resolving it under the store lock is a cached attribute
        # read, not a C build.
        fp = codec.native_module()
        t = self._wtable(TABLE_ALLOCS)
        ut = self._wtable(IDX_NODE_USED)
        pt = self._wtable(IDX_PRIO_COUNT)
        st = None
        now = now_ns()
        stored: list = []
        jobs_touched: set[tuple[str, str]] = set()
        for b in batches:
            if not len(b):
                continue
            if b.job is None:
                if default_jobs:
                    b.job = default_jobs.get((b.namespace, b.job_id))
                if b.job is None:
                    b.job = self._tables[TABLE_JOBS].get(
                        (b.namespace, b.job_id)
                    )
            b.stamp(index, now)
            key = (b.namespace, b.job_id)
            job_inner = self._owned_inner(IDX_ALLOCS_JOB, key)
            eval_inner = self._owned_inner(IDX_ALLOCS_EVAL, b.eval_id)
            node_inners: dict[int, dict] = {}
            touched = b.touched_nodes()
            for nid, ti, _cnt in touched:
                node_inners[ti] = self._owned_inner(IDX_ALLOCS_NODE, nid)
            # the four dict inserts per row, node-grouped (first-touch
            # node order, row order within a node): one C call per
            # batch when the extension is live, the identical Python
            # loop when it isn't
            hs = b.handles()
            if fp is not None:
                fp.store_rows(
                    b.ids, hs, b.node_idx_raw,
                    t, job_inner, eval_inner, node_inners,
                )
            else:
                self._store_rows_py(
                    b.ids, hs, b.node_idx.tolist(),
                    t, job_inner, eval_inner, node_inners,
                )
            # aggregates: one update per touched node / one per batch
            c = b.row_contribution()
            for nid, _ti, cnt in touched:
                _usage_add(ut, nid, (c[0] * cnt, c[1] * cnt, c[2] * cnt, 0))
            prio = b.job.priority if b.job is not None else 50
            pt[prio] = pt.get(prio, 0) + len(b)
            # summaries: every row is a fresh non-terminal insert, so the
            # O(1) starting-count increment always applies (the eager
            # txn's fresh-counts fast path)
            if st is None:
                st = self._wtable(TABLE_JOB_SUMMARIES)
            summary = st.get(key)
            summary = summary.copy() if summary else JobSummary(key[1], key[0])
            counts = summary.summary.setdefault(
                b.task_group,
                {
                    "queued": 0,
                    "complete": 0,
                    "failed": 0,
                    "running": 0,
                    "starting": 0,
                    "lost": 0,
                },
            )
            counts["starting"] += len(b)
            summary.modify_index = index
            st[key] = summary
            jobs_touched.add(key)
            stored.extend(hs)
        for ns, job_id in jobs_touched:
            self._update_job_status_txn(index, ns, job_id)
        return stored

    def update_allocs_from_client(self, index: int, allocs: list[Allocation]) -> None:
        """Merge client-reported status into stored allocs.

        Reference: state_store.go UpdateAllocsFromClient / nested
        updateClientAllocUpdateIndex.
        """
        with self._lock:
            t = self._wtable(TABLE_ALLOCS)
            jobs_touched: set[tuple[str, str]] = set()
            stored: list[Allocation] = []
            for update in allocs:
                existing = t.get(update.id)
                if existing is None:
                    continue
                alloc = existing.copy()
                alloc.client_status = update.client_status
                alloc.client_description = update.client_description
                alloc.task_states = {
                    k: v.copy() for k, v in update.task_states.items()
                }
                if update.deployment_status is not None:
                    alloc.deployment_status = update.deployment_status.copy()
                if update.network_status is not None:
                    alloc.network_status = dataclasses.replace(update.network_status)
                    alloc.network_status.dns = dict(update.network_status.dns)
                alloc.modify_index = index
                alloc.modify_time = now_ns()
                self._put_alloc(alloc, existing)
                stored.append(alloc)
                jobs_touched.add((alloc.namespace, alloc.job_id))
            self._reconcile_summaries_txn(index, jobs_touched)
            for ns, job_id in jobs_touched:
                self._update_job_status_txn(index, ns, job_id)
            self._stamp(index, TABLE_ALLOCS, TABLE_JOB_SUMMARIES)
            self._publish(
                index, TABLE_ALLOCS, stored, "AllocationUpdatedFromClient"
            )

    def update_alloc_desired_transition(
        self, index: int, transitions: dict[str, "DesiredTransition"], evals: list[Evaluation]
    ) -> None:
        from ..structs.structs import DesiredTransition  # local to avoid cycle

        with self._lock:
            t = self._wtable(TABLE_ALLOCS)
            changed: list[Allocation] = []
            for alloc_id, transition in transitions.items():
                existing = t.get(alloc_id)
                if existing is None:
                    continue
                alloc = existing.copy()
                dt = alloc.desired_transition
                if transition.migrate is not None:
                    dt.migrate = transition.migrate
                if transition.reschedule is not None:
                    dt.reschedule = transition.reschedule
                if transition.force_reschedule is not None:
                    dt.force_reschedule = transition.force_reschedule
                alloc.modify_index = index
                self._put_alloc(alloc, existing)
                changed.append(alloc)
            if evals:
                stored_evals = self._upsert_evals_txn(index, evals)
                self._stamp(index, TABLE_EVALS)
            self._stamp(index, TABLE_ALLOCS)
            if changed:
                self._publish(
                    index, TABLE_ALLOCS, changed, "AllocationUpdateDesiredStatus"
                )
            if evals:
                self._publish(index, TABLE_EVALS, stored_evals, "EvaluationUpdated")

    # -- namespaces ----------------------------------------------------

    def upsert_namespace(self, index: int, ns) -> None:
        with self._lock:
            t = self._wtable(TABLE_NAMESPACES)
            existing = t.get(ns.name)
            ns = ns.copy()
            ns.create_index = existing.create_index if existing else index
            ns.modify_index = index
            t[ns.name] = ns
            self._stamp(index, TABLE_NAMESPACES)
            self._publish(index, TABLE_NAMESPACES, [ns], "NamespaceUpserted")

    def delete_namespace(self, index: int, name: str) -> None:
        """Refuses while the namespace holds jobs or volumes (reference
        namespace_endpoint.go DeleteNamespaces nonTerminal check)."""
        if name == "default":
            raise ValueError("the default namespace cannot be deleted")
        with self._lock:
            t = self._wtable(TABLE_NAMESPACES)
            ns = t.get(name)
            if ns is None:
                raise KeyError(f"namespace {name} not found")
            # Only NON-TERMINAL jobs block deletion (reference
            # namespace_endpoint.go nonTerminal check): dead jobs pending
            # GC should not wedge the namespace for minutes.
            in_use = sum(
                1
                for (jns, _), j in self._tables[TABLE_JOBS].items()
                if jns == name and not (j.stop or j.status == JOB_STATUS_DEAD)
            ) + sum(
                1 for (vns, _) in self._tables[TABLE_VOLUMES] if vns == name
            )
            if in_use:
                raise ValueError(
                    f"namespace {name} has {in_use} jobs/volumes"
                )
            del t[name]
            self._stamp(index, TABLE_NAMESPACES)
            self._publish(index, TABLE_NAMESPACES, [ns], "NamespaceDeleted")

    # -- volumes -------------------------------------------------------

    def upsert_volume(self, index: int, vol) -> None:
        """Register/update a volume. Claims survive re-registration
        (reference: CSIVolumeRegister keeps claim state)."""
        with self._lock:
            t = self._wtable(TABLE_VOLUMES)
            key = (vol.namespace, vol.id)
            existing = t.get(key)
            vol = vol.copy()
            if existing is not None:
                vol.create_index = existing.create_index
                vol.claims = {
                    k: c for k, c in existing.claims.items()
                }
            else:
                vol.create_index = index
            vol.modify_index = index
            t[key] = vol
            self._stamp(index, TABLE_VOLUMES)
            self._publish(index, TABLE_VOLUMES, [vol], "VolumeRegistered")

    def delete_volume(self, index: int, namespace: str, vol_id: str) -> None:
        with self._lock:
            t = self._wtable(TABLE_VOLUMES)
            vol = t.get((namespace, vol_id))
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            if vol.claims:
                raise ValueError(
                    f"volume {vol_id} has {len(vol.claims)} active claims"
                )
            del t[(namespace, vol_id)]
            self._stamp(index, TABLE_VOLUMES)
            self._publish(index, TABLE_VOLUMES, [vol], "VolumeDeregistered")

    def claim_volume(
        self,
        index: int,
        namespace: str,
        vol_id: str,
        alloc_id: str,
        node_id: str,
        read_only: bool,
    ) -> None:
        """Attach an alloc's claim; raises on access-mode conflict
        (reference: CSIVolumeClaim)."""
        from ..structs.structs import VolumeClaim

        with self._lock:
            t = self._wtable(TABLE_VOLUMES)
            vol = t.get((namespace, vol_id))
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            if alloc_id in vol.claims:
                return
            ok, why = vol.claimable(read_only)
            if not ok:
                raise ValueError(f"volume {vol_id}: {why}")
            vol = vol.copy()
            vol.claims[alloc_id] = VolumeClaim(
                alloc_id=alloc_id,
                node_id=node_id,
                read_only=read_only,
                create_index=index,
            )
            vol.modify_index = index
            t[(namespace, vol_id)] = vol
            self._stamp(index, TABLE_VOLUMES)
            self._publish(index, TABLE_VOLUMES, [vol], "VolumeClaimed")

    def _claim_volumes_txn(self, index: int, allocs: list[Allocation]) -> None:
        """Best-effort claims for freshly placed allocs whose group asks
        for volumes that are REGISTERED (unregistered host volumes keep
        the config-only semantics). Conflicts are logged, not fatal —
        feasibility screened them; a race loses gracefully."""
        vt = self._tables[TABLE_VOLUMES]
        if not vt:
            return
        import logging

        log = logging.getLogger("nomad_tpu.state")
        for alloc in allocs:
            if alloc.terminal_status() or alloc.job is None:
                continue
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg is None or not tg.volumes:
                continue
            for req in tg.volumes.values():
                # A node-pinned volume only serves allocs on its node;
                # prefer the pinned match over an unpinned (any-node) one.
                matches = [
                    vol
                    for vol in vt.values()
                    if vol.namespace == alloc.namespace
                    and vol.name == req.source
                    and vol.node_id in ("", alloc.node_id)
                ]
                matches.sort(key=lambda v: v.node_id == "", )
                if not matches:
                    continue
                vol = matches[0]
                try:
                    self.claim_volume(
                        index,
                        vol.namespace,
                        vol.id,
                        alloc.id,
                        alloc.node_id,
                        req.read_only,
                    )
                except (KeyError, ValueError) as e:
                    log.warning(
                        "volume claim for alloc %s: %s", alloc.id, e
                    )

    # -- operator config -----------------------------------------------

    def upsert_operator_config(self, index: int, key: str, value: dict) -> None:
        """Raft-replicated operator knobs (reference: autopilot config
        lives in raft state, operator_endpoint.go)."""
        with self._lock:
            t = self._wtable(TABLE_OPERATOR)
            t[key] = dict(value)
            self._stamp(index, TABLE_OPERATOR)

    # -- secrets -------------------------------------------------------

    def upsert_secret(self, index: int, entry) -> None:
        with self._lock:
            t = self._wtable(TABLE_SECRETS)
            key = (entry.namespace, entry.path)
            entry = entry.copy()
            existing = t.get(key)
            entry.create_index = existing.create_index if existing else index
            entry.modify_index = index
            t[key] = entry
            self._stamp(index, TABLE_SECRETS)
            # event subscribers must never see secret VALUES — publish a
            # redacted row (path/namespace only)
            self._publish(
                index,
                TABLE_SECRETS,
                [dataclasses.replace(entry, items={})],
                "SecretUpserted",
            )

    def delete_secret(self, index: int, namespace: str, path: str) -> None:
        with self._lock:
            t = self._wtable(TABLE_SECRETS)
            entry = t.pop((namespace, path), None)
            if entry is None:
                raise KeyError(f"secret {path} not found")
            self._stamp(index, TABLE_SECRETS)
            self._publish(
                index,
                TABLE_SECRETS,
                [dataclasses.replace(entry, items={})],
                "SecretDeleted",
            )

    # -- services ------------------------------------------------------

    def upsert_service_registrations(self, index: int, regs: list) -> None:
        """Register/update service instances (reference:
        state_store_service_registration.go UpsertServiceRegistrations)."""
        with self._lock:
            t = self._wtable(TABLE_SERVICES)
            stored = []
            for reg in regs:
                reg = reg.copy()
                existing = t.get(reg.id)
                reg.create_index = (
                    existing.create_index if existing else index
                )
                reg.modify_index = index
                t[reg.id] = reg
                stored.append(reg)
            if stored:
                self._stamp(index, TABLE_SERVICES)
                self._publish(
                    index, TABLE_SERVICES, stored, "ServiceRegistration"
                )

    def delete_service_registrations(self, index: int, ids: list[str]) -> int:
        with self._lock:
            t = self._wtable(TABLE_SERVICES)
            gone = [t.pop(i) for i in ids if i in t]
            if gone:
                self._stamp(index, TABLE_SERVICES)
                self._publish(
                    index, TABLE_SERVICES, gone, "ServiceDeregistration"
                )
            return len(gone)

    def delete_services_by_alloc(self, index: int, alloc_ids) -> int:
        """Drop every registration owned by the given allocs (client
        deregister on task stop + the GC sweep for lost clients)."""
        drop = set(alloc_ids)
        with self._lock:
            t = self._wtable(TABLE_SERVICES)
            gone = [r for r in t.values() if r.alloc_id in drop]
            for r in gone:
                del t[r.id]
            if gone:
                self._stamp(index, TABLE_SERVICES)
                self._publish(
                    index, TABLE_SERVICES, gone, "ServiceDeregistration"
                )
            return len(gone)

    def release_volume_claims_scoped(
        self, index: int, namespace: str, vol_id: str,
        alloc_ids: list[str],
    ) -> int:
        """Drop the given allocs' claims on ONE volume (the detach
        escape hatch — releasing them everywhere would free claims the
        same allocs legitimately hold on other volumes)."""
        drop = set(alloc_ids)
        released = 0
        with self._lock:
            t = self._wtable(TABLE_VOLUMES)
            vol = t.get((namespace, vol_id))
            if vol is None:
                return 0
            hits = drop & vol.claims.keys()
            if not hits:
                return 0
            vol = vol.copy()
            for aid in hits:
                del vol.claims[aid]
                released += 1
            vol.modify_index = index
            t[(namespace, vol_id)] = vol
            self._stamp(index, TABLE_VOLUMES)
            self._publish(
                index, TABLE_VOLUMES, [vol], "VolumeClaimReleased"
            )
        return released

    def release_volume_claims(self, index: int, alloc_ids: list[str]) -> int:
        """Drop the given allocs' claims everywhere; returns how many
        claims were released (the volume watcher's write)."""
        drop = set(alloc_ids)
        released = 0
        with self._lock:
            t = self._wtable(TABLE_VOLUMES)
            changed: list = []
            for key, vol in list(t.items()):
                hits = drop & vol.claims.keys()
                if not hits:
                    continue
                vol = vol.copy()
                for aid in hits:
                    del vol.claims[aid]
                    released += 1
                vol.modify_index = index
                t[key] = vol
                changed.append(vol)
            if changed:
                self._stamp(index, TABLE_VOLUMES)
                self._publish(
                    index, TABLE_VOLUMES, changed, "VolumeClaimReleased"
                )
        return released

    # -- plan results (the serialization point) ------------------------

    def upsert_plan_results(self, index: int, result: PlanResult) -> None:
        """Apply a committed plan atomically (reference state_store.go:318)."""
        self.upsert_plan_results_batch(index, [result])

    def upsert_plan_results_batch(
        self, index: int, results: list[PlanResult]
    ) -> None:
        """Apply N verified plan results as ONE store transaction.

        The batched plan applier commits a whole TPU batch's worth of
        same-snapshot, node-disjoint plans in a single raft entry; here
        they land under one lock acquisition with one bulk alloc upsert
        (one COW table fork, one summaries/status pass, one publish)
        instead of N serial upsert_plan_results calls. Semantics per
        result are identical to the single-plan form — the differential
        state-identity test (tests/test_plan_apply_batch.py) pins that.
        """
        with self._lock, paused_gc():
            allocs_to_upsert: list[Allocation] = []
            batches: list[PlacementBatch] = []
            stopped: list[Allocation] = []
            preempted: list[Allocation] = []
            deployment_events: list = []
            default_jobs: dict[tuple[str, str], Job] = {}
            preemption_evals: list[Evaluation] = []
            for result in results:
                for allocs in result.node_allocation.values():
                    allocs_to_upsert.extend(allocs)
                batches.extend(result.alloc_batches)
                for allocs in result.node_update.values():
                    stopped.extend(allocs)
                for allocs in result.node_preemptions.values():
                    preempted.extend(allocs)
                if result.job is not None:
                    default_jobs[
                        (result.job.namespace, result.job.id)
                    ] = result.job
                if result.deployment is not None:
                    self._upsert_deployment_txn(index, result.deployment)
                    deployment_events.append(
                        self._tables[TABLE_DEPLOYMENTS][result.deployment.id]
                    )
                for du in result.deployment_updates:
                    self._update_deployment_status_txn(index, du)
                    d = self._tables[TABLE_DEPLOYMENTS].get(du.deployment_id)
                    if d is not None:
                        deployment_events.append(d)
                preemption_evals.extend(result.preemption_evals)
            any_deployment = any(
                r.deployment is not None or r.deployment_updates
                for r in results
            )

            t = self._wtable(TABLE_ALLOCS)
            # Stops and preemptions merge desired-status changes onto the
            # existing alloc rather than replacing client state.
            committed: list[Allocation] = []
            for alloc in stopped + preempted:
                existing = t.get(alloc.id)
                merged = alloc.copy()
                if existing is not None:
                    merged = existing.copy()
                    merged.desired_status = alloc.desired_status
                    merged.desired_description = alloc.desired_description
                    merged.preempted_by_allocation = alloc.preempted_by_allocation
                    if alloc.client_status:
                        merged.client_status = alloc.client_status
                else:
                    # Plan raced a GC: recreate a fully-stamped tombstone row.
                    merged.create_index = index
                    merged.job = self._tables[TABLE_JOBS].get(
                        (merged.namespace, merged.job_id)
                    )
                merged.modify_index = index
                merged.modify_time = now_ns()
                self._put_alloc(merged, existing)
                committed.append(merged)
            # Ownership transfer: every alloc in a committed plan is either
            # freshly minted by the scheduler or a plan-owned copy (Plan's
            # append_* methods copy), so the store takes them without the
            # per-alloc defensive copy. The fresh-alloc scan only exists
            # for volume claims — skip it (and its 10^5 membership probes)
            # when no volumes are registered at all.
            fresh_allocs = (
                [a for a in allocs_to_upsert if a.id not in t]
                if self._tables[TABLE_VOLUMES]
                else []
            )
            committed.extend(
                self._upsert_allocs_txn(
                    index, allocs_to_upsert, owned=True,
                    default_jobs=default_jobs,
                )
            )
            # SoA batches: one bulk column transaction per batch — lazy
            # row handles into the tables, vectorized aggregate updates,
            # incremental summaries. The store takes ownership (stamps
            # the batch in place), the same owned-payload contract the
            # eager path has.
            if batches:
                committed.extend(
                    self._upsert_batches_txn(index, batches, default_jobs)
                )
                # volume-bearing batches materialize for the claim walk
                # (rare: volumes gate the plan onto the serial path)
                if self._tables[TABLE_VOLUMES]:
                    for b in batches:
                        job = b.job
                        tg = (
                            job.lookup_task_group(b.task_group)
                            if job is not None
                            else None
                        )
                        if tg is not None and tg.volumes:
                            fresh_allocs.extend(b.materialize())
            # Volume claims attach atomically with the placements that
            # need them (reference: the CSI claim RPC; here the plan
            # apply IS the claim point for registered volumes).
            if fresh_allocs:
                self._claim_volumes_txn(index, fresh_allocs)
            # Record placed canaries on their deployment's group state
            # (reference state_store.go:4888 "Ensure PlacedCanaries
            # accurately reflects the alloc canary status"): the
            # reconciler and promotion read dstate.placed_canaries.
            # Canary markers only exist on deployment-bearing plans, so
            # the per-alloc scan is gated on that.
            canary_by_deploy: dict[str, list[Allocation]] = {}
            if any_deployment or self._tables[TABLE_DEPLOYMENTS]:
                for a in allocs_to_upsert:
                    if (
                        a.deployment_id
                        and a.deployment_status is not None
                        and a.deployment_status.canary
                    ):
                        canary_by_deploy.setdefault(a.deployment_id, []).append(a)
            if canary_by_deploy:
                dt = self._wtable(TABLE_DEPLOYMENTS)
                for dep_id, callocs in canary_by_deploy.items():
                    existing_d = dt.get(dep_id)
                    if existing_d is None:
                        continue
                    d = existing_d.copy()
                    for a in callocs:
                        ds = d.task_groups.get(a.task_group)
                        if ds is not None and a.id not in ds.placed_canaries:
                            ds.placed_canaries.append(a.id)
                    d.modify_index = index
                    dt[dep_id] = d
                    deployment_events.append(d)
            if preemption_evals:
                self._upsert_evals_txn(index, preemption_evals)
                self._stamp(index, TABLE_EVALS)
            tables = [TABLE_ALLOCS, TABLE_JOB_SUMMARIES]
            if any_deployment or canary_by_deploy:
                tables.append(TABLE_DEPLOYMENTS)
            self._stamp(index, *tables)
            jobs_touched = {
                (a.namespace, a.job_id) for a in stopped + preempted
            }
            self._reconcile_summaries_txn(index, jobs_touched)
            for ns, job_id in jobs_touched:
                self._update_job_status_txn(index, ns, job_id)
            self._publish(index, TABLE_ALLOCS, committed, "PlanResult")
            if deployment_events:
                self._publish(
                    index,
                    TABLE_DEPLOYMENTS,
                    deployment_events,
                    "DeploymentStatusUpdate",
                )

    # -- deployments ---------------------------------------------------

    def upsert_deployment(self, index: int, deployment: Deployment) -> None:
        with self._lock:
            self._upsert_deployment_txn(index, deployment)
            self._stamp(index, TABLE_DEPLOYMENTS)
            self._publish(
                index, TABLE_DEPLOYMENTS, [deployment], "DeploymentStatusUpdate"
            )

    def _upsert_deployment_txn(self, index: int, deployment: Deployment) -> None:
        t = self._wtable(TABLE_DEPLOYMENTS)
        deployment = deployment.copy()
        existing = t.get(deployment.id)
        deployment.create_index = existing.create_index if existing else index
        deployment.modify_index = index
        deployment.modify_time = now_ns()
        t[deployment.id] = deployment

    def _update_deployment_status_txn(self, index: int, update) -> None:
        t = self._wtable(TABLE_DEPLOYMENTS)
        existing = t.get(update.deployment_id)
        if existing is None:
            return
        d = existing.copy()
        d.status = update.status
        d.status_description = update.status_description
        d.modify_index = index
        d.modify_time = now_ns()
        t[d.id] = d

    def update_deployment_status(self, index: int, update) -> None:
        with self._lock:
            self._update_deployment_status_txn(index, update)
            self._stamp(index, TABLE_DEPLOYMENTS)
            d = self._tables[TABLE_DEPLOYMENTS].get(update.deployment_id)
            if d is not None:
                self._publish(
                    index, TABLE_DEPLOYMENTS, [d], "DeploymentStatusUpdate"
                )

    def delete_deployment(self, index: int, deployment_ids: list[str]) -> None:
        with self._lock:
            t = self._wtable(TABLE_DEPLOYMENTS)
            gone = [t.pop(did) for did in deployment_ids if did in t]
            self._stamp(index, TABLE_DEPLOYMENTS)
            if gone:
                self._publish(
                    index, TABLE_DEPLOYMENTS, gone, "DeploymentDeleted"
                )

    def update_deployment_promotion(
        self,
        index: int,
        deployment_id: str,
        groups: Optional[list[str]] = None,
        eval_obj: Optional[Evaluation] = None,
    ) -> None:
        """Promote canaries (reference state_store.go UpdateDeploymentPromotion).

        Marks the given groups (all canary groups when None) promoted and
        flips the promoted allocs' canary flag off. Raises when a group has
        fewer healthy canaries than desired.
        """
        with self._lock:
            t = self._wtable(TABLE_DEPLOYMENTS)
            existing = t.get(deployment_id)
            if existing is None:
                raise KeyError(f"unknown deployment {deployment_id}")
            d = existing.copy()
            targets = groups if groups else [
                g for g, s in d.task_groups.items() if s.desired_canaries > 0
            ]
            canary_ids: set[str] = set()
            # Validation (healthy canary counts) happens in the endpoint
            # BEFORE the raft commit (check_promotion_ready) — an FSM apply
            # must never raise, or replay of the log would poison followers.
            for g in targets:
                dstate = d.task_groups.get(g)
                if dstate is None:
                    continue
                dstate.promoted = True
                canary_ids.update(dstate.placed_canaries)
            if not any(
                s.desired_canaries > 0 and not s.promoted
                for s in d.task_groups.values()
            ):
                d.status_description = "Deployment is running"
            d.modify_index = index
            d.modify_time = now_ns()
            t[d.id] = d
            # clear the canary flag on promoted allocs
            at = self._wtable(TABLE_ALLOCS)
            for cid in canary_ids:
                a = at.get(cid)
                if a is None or a.deployment_status is None:
                    continue
                na = a.copy()
                na.deployment_status.canary = False
                na.modify_index = index
                na.modify_time = now_ns()
                self._put_alloc(na, a)
            if eval_obj is not None:
                self._upsert_evals_txn(index, [eval_obj])
                self._stamp(index, TABLE_EVALS)
            self._stamp(index, TABLE_DEPLOYMENTS, TABLE_ALLOCS)
            self._publish(
                index, TABLE_DEPLOYMENTS, [d], "DeploymentPromotion"
            )

    def update_alloc_deployment_health(
        self,
        index: int,
        deployment_id: str,
        healthy_ids: list[str],
        unhealthy_ids: list[str],
        status_update=None,
        eval_obj: Optional[Evaluation] = None,
        revert_job: Optional[Job] = None,
    ) -> None:
        """Set alloc deployment health and resync the deployment's
        healthy/unhealthy counters (reference state_store.go
        UpdateDeploymentAllocHealth / upsertDeploymentUpdate). The optional
        revert_job is upserted atomically (auto-revert)."""
        with self._lock:
            at = self._wtable(TABLE_ALLOCS)
            ts = now_ns()
            for aid, healthy in [(i, True) for i in healthy_ids] + [
                (i, False) for i in unhealthy_ids
            ]:
                a = at.get(aid)
                if a is None:
                    continue
                na = a.copy()
                if na.deployment_status is None:
                    from ..structs.structs import AllocDeploymentStatus

                    na.deployment_status = AllocDeploymentStatus()
                na.deployment_status.healthy = healthy
                na.deployment_status.timestamp_ns = ts
                na.modify_index = index
                na.modify_time = ts
                self._put_alloc(na, a)
            # resync counters from the alloc table (single source of truth)
            dt = self._wtable(TABLE_DEPLOYMENTS)
            existing = dt.get(deployment_id)
            if existing is not None:
                d = existing.copy()
                counts: dict[str, list[int]] = {g: [0, 0] for g in d.task_groups}
                for a in self.allocs_by_deployment(deployment_id):
                    if (
                        a.deployment_status is None
                        or a.task_group not in counts
                        or a.terminal_status()
                    ):
                        continue
                    if a.deployment_status.is_healthy():
                        counts[a.task_group][0] += 1
                    elif a.deployment_status.is_unhealthy():
                        counts[a.task_group][1] += 1
                for g, (h, u) in counts.items():
                    d.task_groups[g].healthy_allocs = h
                    d.task_groups[g].unhealthy_allocs = u
                d.modify_index = index
                d.modify_time = now_ns()
                dt[d.id] = d
            if status_update is not None:
                self._update_deployment_status_txn(index, status_update)
            if revert_job is not None:
                self._upsert_job_txn(index, revert_job)
                self._stamp(index, TABLE_JOBS)
            if eval_obj is not None:
                self._upsert_evals_txn(index, [eval_obj])
                self._stamp(index, TABLE_EVALS)
            self._stamp(index, TABLE_DEPLOYMENTS, TABLE_ALLOCS)
            d2 = self._tables[TABLE_DEPLOYMENTS].get(deployment_id)
            if d2 is not None:
                self._publish(
                    index, TABLE_DEPLOYMENTS, [d2], "DeploymentAllocHealth"
                )

    # -- derived state -------------------------------------------------

    def _reconcile_summaries_txn(
        self, index: int, jobs_touched: set[tuple[str, str]]
    ) -> None:
        if not jobs_touched:
            return
        st = self._wtable(TABLE_JOB_SUMMARIES)
        for ns, job_id in jobs_touched:
            job = self._tables[TABLE_JOBS].get((ns, job_id))
            summary = st.get((ns, job_id))
            summary = summary.copy() if summary else JobSummary(job_id, ns)
            groups = (
                {tg.name for tg in job.task_groups}
                if job
                else set(summary.summary.keys())
            )
            counts = {
                g: {
                    "queued": summary.summary.get(g, {}).get("queued", 0),
                    "complete": 0,
                    "failed": 0,
                    "running": 0,
                    "starting": 0,
                    "lost": 0,
                }
                for g in groups
            }
            for a in self.allocs_by_job(ns, job_id):
                c = counts.setdefault(
                    a.task_group,
                    {
                        "queued": 0,
                        "complete": 0,
                        "failed": 0,
                        "running": 0,
                        "starting": 0,
                        "lost": 0,
                    },
                )
                if a.client_status == ALLOC_CLIENT_STATUS_RUNNING:
                    c["running"] += 1
                elif a.client_status == ALLOC_CLIENT_STATUS_COMPLETE:
                    c["complete"] += 1
                elif a.client_status == ALLOC_CLIENT_STATUS_FAILED:
                    c["failed"] += 1
                elif a.client_status == ALLOC_CLIENT_STATUS_LOST:
                    c["lost"] += 1
                elif not a.terminal_status():
                    c["starting"] += 1
            summary.summary = counts
            summary.modify_index = index
            st[(ns, job_id)] = summary

    def update_job_queued_allocs(
        self, index: int, namespace: str, job_id: str, queued: dict[str, int]
    ) -> None:
        with self._lock:
            st = self._wtable(TABLE_JOB_SUMMARIES)
            summary = st.get((namespace, job_id))
            if summary is None:
                return
            summary = summary.copy()
            for group, count in queued.items():
                summary.summary.setdefault(
                    group,
                    {
                        "queued": 0,
                        "complete": 0,
                        "failed": 0,
                        "running": 0,
                        "starting": 0,
                        "lost": 0,
                    },
                )["queued"] = count
            summary.modify_index = index
            st[(namespace, job_id)] = summary
            self._stamp(index, TABLE_JOB_SUMMARIES)

    def _update_job_status_txn(self, index: int, namespace: str, job_id: str) -> None:
        """Derive job status from its allocs and evals (reference
        state_store.go getJobStatus/setJobStatus)."""
        jt = self._tables[TABLE_JOBS]
        job = jt.get((namespace, job_id))
        if job is None:
            return
        if job.stop:
            new_status = JOB_STATUS_DEAD
        else:
            # raw index rows, not the materializing reader: the only
            # question is "any live alloc?", which lazy AllocRow handles
            # answer straight from their batch columns
            job_allocs = self._tables[IDX_ALLOCS_JOB].get(
                (namespace, job_id), {}
            )
            has_live_alloc = any(
                not a.terminal_status() for a in job_allocs.values()
            )
            has_open_eval = False
            for e in self._tables[TABLE_EVALS].values():
                if (
                    e.namespace == namespace
                    and e.job_id == job_id
                    and e.status in (EVAL_STATUS_PENDING, EVAL_STATUS_BLOCKED)
                ):
                    has_open_eval = True
                    break
            if has_live_alloc or has_open_eval:
                new_status = JOB_STATUS_RUNNING if has_live_alloc else JOB_STATUS_PENDING
            else:
                # Periodic/parameterized parents idle at running.
                if job.is_periodic() or job.is_parameterized():
                    new_status = JOB_STATUS_RUNNING
                elif job.type in (JOB_TYPE_SERVICE, JOB_TYPE_SYSTEM):
                    # Service/system jobs with no allocs yet are pending.
                    new_status = (
                        JOB_STATUS_PENDING if job.status == JOB_STATUS_PENDING else JOB_STATUS_DEAD
                    )
                else:
                    new_status = JOB_STATUS_DEAD if job_allocs else job.status
        if new_status != job.status:
            jt2 = self._wtable(TABLE_JOBS)
            # shallow clone: only status/modify_index change, so the
            # nested spec (task_groups, constraints, meta) is SHARED
            # with the replaced row — safe under the store's
            # copy-on-write discipline (every writer that mutates spec
            # internals goes through Job.copy first, which deep-copies
            # them; the same sub-object sharing the solver's fast-mint
            # templates rely on). The deep copy here was the single
            # largest cost of committing a fresh job's first placement
            # (~0.2ms of a ~1ms interactive eval).
            import copy as _copy

            j = _copy.copy(job)
            j.status = new_status
            j.modify_index = index
            jt2[(namespace, job_id)] = j
            self._stamp(index, TABLE_JOBS)


