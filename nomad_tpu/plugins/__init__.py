"""Plugin interfaces beyond task drivers.

Reference: plugins/ — base handshake (plugins/base), driver wrappers
(plugins/drivers, implemented in nomad_tpu/drivers/plugin.py), device
plugins (plugins/device, implemented in nomad_tpu/client/devicemanager.py),
and the CSI client (plugins/csi) implemented here in csi.py.
"""

from .csi import (
    CSIError,
    CSIPlugin,
    ExternalCSIPlugin,
    FakeCSIPlugin,
    serve_csi_plugin,
)

__all__ = [
    "CSIError",
    "CSIPlugin",
    "ExternalCSIPlugin",
    "FakeCSIPlugin",
    "serve_csi_plugin",
]
