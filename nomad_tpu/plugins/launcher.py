"""Shared plugin-process launcher for the driver and device fabrics.

Reference: helper/pluginutils + go-plugin's client lifecycle — launch
the plugin binary, read one handshake line from stdout, talk RPC, and
let the child die with the parent (stdin EOF). Both ExternalDriver
(drivers/plugin.py) and ExternalDevicePlugin (devices/plugin.py) wrap
this; fixes to the lifecycle land once.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Optional

from ..rpc import ConnPool, RPCError


class PluginProcess:
    """One plugin subprocess: lazy launch, handshake, RPC calls,
    die-with-parent shutdown."""

    def __init__(
        self,
        argv: list[str],
        handshake_prefix: str,
        error_cls: type[Exception] = RuntimeError,
    ) -> None:
        self.argv = argv
        self.handshake_prefix = handshake_prefix
        self.error_cls = error_cls
        self._proc: Optional[subprocess.Popen] = None
        self._addr: Optional[tuple[str, int]] = None
        self._pool = ConnPool()
        self._lock = threading.Lock()

    def ensure_running(self) -> tuple[str, int]:
        with self._lock:
            if (
                self._proc is not None
                and self._proc.poll() is None
                and self._addr is not None
            ):
                return self._addr
            self._addr = None
            proc = subprocess.Popen(
                self.argv,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                text=True,
            )
            line = (proc.stdout.readline() or "").strip()  # type: ignore[union-attr]
            if not line.startswith(self.handshake_prefix):
                # A bad handshake must not leave a zombie child behind or
                # poison later calls with half-initialized state.
                try:
                    proc.kill()
                    proc.wait(timeout=5)
                except Exception:
                    pass
                raise self.error_cls(f"bad plugin handshake: {line!r}")
            self._proc = proc
            host, _, port = line[len(self.handshake_prefix):].partition(":")
            self._addr = (host, int(port))
            return self._addr

    def shutdown(self) -> None:
        with self._lock:
            if self._proc is not None:
                try:
                    self._proc.stdin.close()  # type: ignore[union-attr]
                    self._proc.wait(timeout=5)
                except Exception:
                    try:
                        self._proc.kill()
                        self._proc.wait(timeout=5)
                    except Exception:
                        pass
                self._proc = None
                self._addr = None

    def call(self, method: str, args=None, timeout_s: float = 30.0):
        addr = self.ensure_running()
        try:
            return self._pool.call(addr, method, args, timeout_s=timeout_s)
        except RPCError as e:
            raise self.error_cls(str(e)) from None


def instantiate_plugin(cls: type, config: Optional[dict]):
    """Build the plugin object, passing config only when the constructor
    takes it — inspected, not duck-typed, so a TypeError raised INSIDE a
    config-accepting __init__ propagates instead of silently dropping
    the operator's config."""
    import inspect

    try:
        params = inspect.signature(cls).parameters
    except (TypeError, ValueError):
        params = {}
    takes_arg = any(
        p.kind
        in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
            inspect.Parameter.VAR_POSITIONAL,
            inspect.Parameter.VAR_KEYWORD,
        )
        or p.name == "config"
        for p in params.values()
    )
    return cls(config or {}) if takes_arg else cls()
