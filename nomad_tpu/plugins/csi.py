"""CSI plugin client interface.

Reference: plugins/csi/ — Nomad talks the CSI spec's gRPC services
(Identity/Controller/Node) to external storage plugins and ships a fake
client for tests (plugins/csi/fake/). The TPU-native build keeps the same
three-service verb set but carries it over the repo's framed-msgpack RPC
fabric instead of gRPC (see nomad_tpu/drivers/plugin.py for the matching
driver-plugin transport): an external CSI plugin process hosts a
``CSIPlugin`` implementation and prints the same
``NOMAD_TPU_PLUGIN|1|host:port`` handshake.

The verb set mirrors the CSI spec methods Nomad actually calls
(plugins/csi/client.go):

  identity:   plugin_info, probe
  controller: controller_publish, controller_unpublish, validate_volume
  node:       node_get_info, node_stage, node_unstage,
              node_publish, node_unpublish

Staging/publishing are filesystem operations under the client's data dir;
on hosts where bind mounts need privileges the fake (and any in-process
plugin) uses symlinks — the lifecycle contract, refcounts and claim
interaction are what parity requires, not mount(2).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


class CSIError(Exception):
    pass


@dataclass
class CSIPluginInfo:
    name: str = ""
    version: str = "0.0.0"
    # which services this instance provides (a plugin job may run
    # controller-only and node-only instances; reference: TaskCSIPluginConfig)
    controller: bool = True
    node: bool = True


@dataclass
class StageContext:
    """Everything a node-stage/publish call needs (reference:
    plugins/csi/client.go NodeStageVolume params)."""

    volume_id: str = ""
    external_id: str = ""
    staging_path: str = ""
    target_path: str = ""
    read_only: bool = False
    access_mode: str = ""
    attachment_mode: str = "file-system"
    context: dict[str, str] = field(default_factory=dict)


class CSIPlugin:
    """One CSI plugin (in-process base class; external plugins subclass
    this in their own process behind ``serve_csi_plugin``)."""

    def plugin_info(self) -> CSIPluginInfo:
        raise NotImplementedError

    def probe(self) -> bool:
        """Health check (CSI Identity.Probe)."""
        return True

    # -- controller service -------------------------------------------

    def controller_publish(
        self, volume_id: str, external_id: str, node_id: str, read_only: bool
    ) -> dict[str, str]:
        """Attach the volume to the node; returns publish context the node
        verbs receive (CSI ControllerPublishVolume)."""
        return {}

    def controller_unpublish(
        self, volume_id: str, external_id: str, node_id: str
    ) -> None:
        """CSI ControllerUnpublishVolume."""

    def validate_volume(
        self, volume_id: str, external_id: str, access_mode: str,
        attachment_mode: str,
    ) -> None:
        """Raise CSIError if the volume can't satisfy the requested modes
        (CSI ValidateVolumeCapabilities)."""

    def create_volume(self, name: str, params: dict) -> dict:
        """Provision storage; returns {"external_id": ..., "context":
        {...}} (CSI CreateVolume)."""
        raise CSIError("plugin does not support volume creation")

    def delete_volume(self, external_id: str) -> None:
        """CSI DeleteVolume."""
        raise CSIError("plugin does not support volume deletion")

    def create_snapshot(
        self, external_id: str, name: str, params: dict
    ) -> dict:
        """Point-in-time copy of a volume; returns {"snapshot_id",
        "source_external_id", "size_mb", "create_time_ns", "ready"}
        (CSI CreateSnapshot)."""
        raise CSIError("plugin does not support snapshots")

    def delete_snapshot(self, snapshot_id: str) -> None:
        """CSI DeleteSnapshot."""
        raise CSIError("plugin does not support snapshots")

    def list_snapshots(self) -> list[dict]:
        """CSI ListSnapshots — every snapshot this plugin holds."""
        raise CSIError("plugin does not support snapshots")

    # -- node service --------------------------------------------------

    def node_get_info(self) -> dict[str, str]:
        """CSI NodeGetInfo — the storage provider's id for this host."""
        return {"node_id": ""}

    def node_stage(self, ctx: StageContext) -> None:
        """Make the volume available at ctx.staging_path (once per volume
        per node; CSI NodeStageVolume)."""
        raise NotImplementedError

    def node_unstage(self, volume_id: str, staging_path: str) -> None:
        raise NotImplementedError

    def node_publish(self, ctx: StageContext) -> None:
        """Expose the staged volume at ctx.target_path (once per alloc;
        CSI NodePublishVolume)."""
        raise NotImplementedError

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        raise NotImplementedError


class FakeCSIPlugin(CSIPlugin):
    """Directory-backed plugin (reference: plugins/csi/fake/client.go).

    The "storage cloud" is ``backing_dir``: each external volume id is a
    subdirectory; stage links it into the staging path and publish links
    the staging path to the per-alloc target. Tests and the builtin
    ``hostpath`` catalog entry both use it.
    """

    def __init__(self, name: str = "hostpath", backing_dir: str = "",
                 controller: bool = True) -> None:
        self.name = name
        self.backing_dir = backing_dir or os.path.join(
            os.path.expanduser("~"), ".nomad-tpu-csi", name
        )
        self._controller = controller
        self._lock = threading.Lock()
        self.published: dict[str, str] = {}  # target_path -> volume_id
        self.staged: dict[str, str] = {}  # staging_path -> volume_id
        self.attached: dict[str, set[str]] = {}  # external_id -> node ids
        self.healthy = True

    def plugin_info(self) -> CSIPluginInfo:
        return CSIPluginInfo(
            name=self.name, version="1.0.0",
            controller=self._controller, node=True,
        )

    def probe(self) -> bool:
        return self.healthy

    def _backing(self, external_id: str) -> str:
        path = os.path.join(self.backing_dir, external_id or "default")
        os.makedirs(path, exist_ok=True)
        return path

    def controller_publish(self, volume_id, external_id, node_id, read_only):
        with self._lock:
            self.attached.setdefault(external_id, set()).add(node_id)
        return {"attached_on": node_id}

    def controller_unpublish(self, volume_id, external_id, node_id):
        with self._lock:
            self.attached.get(external_id, set()).discard(node_id)

    def validate_volume(self, volume_id, external_id, access_mode,
                        attachment_mode):
        if attachment_mode not in ("", "file-system"):
            raise CSIError(
                f"fake plugin only supports file-system attachment, "
                f"got {attachment_mode!r}"
            )

    def create_volume(self, name: str, params: dict) -> dict:
        external_id = f"vol-{name}"
        os.makedirs(os.path.join(self.backing_dir, external_id),
                    exist_ok=True)
        return {"external_id": external_id, "context": dict(params or {})}

    def delete_volume(self, external_id: str) -> None:
        path = os.path.join(self.backing_dir, external_id)
        if os.path.isdir(path):
            shutil.rmtree(path)

    def _snap_dir(self) -> str:
        path = os.path.join(self.backing_dir, "_snapshots")
        os.makedirs(path, exist_ok=True)
        return path

    @staticmethod
    def _safe_component(value: str, what: str) -> str:
        """Snapshot ids/names become filesystem path components: reject
        anything that could escape the snapshot directory (these arrive
        straight off the HTTP query string)."""
        if (
            not value
            or value != os.path.basename(value)
            or value in (".", "..")
            or "/" in value
            or "\\" in value
        ):
            raise CSIError(f"invalid {what} {value!r}")
        return value

    def create_snapshot(self, external_id: str, name: str,
                        params: dict) -> dict:
        self._safe_component(external_id, "volume id")
        if name:
            self._safe_component(name, "snapshot name")
        src = os.path.join(self.backing_dir, external_id)
        if not os.path.isdir(src):
            raise CSIError(f"volume {external_id!r} not found")
        snap_id = f"snap-{name or external_id}-{int(time.time_ns())}"
        dst = os.path.join(self._snap_dir(), snap_id)
        shutil.copytree(src, dst)
        meta = {
            "snapshot_id": snap_id,
            "source_external_id": external_id,
            "size_mb": sum(
                os.path.getsize(os.path.join(r, f))
                for r, _, fs in os.walk(dst)
                for f in fs
            ) // (1024 * 1024),
            "create_time_ns": time.time_ns(),
            "ready": True,
        }
        # metadata rides BESIDE the copy, never inside it — the snapshot
        # must stay a faithful point-in-time image of the volume
        with open(os.path.join(self._snap_dir(),
                               f"{snap_id}.meta.json"), "w") as f:
            json.dump(meta, f)
        return meta

    def delete_snapshot(self, snapshot_id: str) -> None:
        self._safe_component(snapshot_id, "snapshot id")
        path = os.path.join(self._snap_dir(), snapshot_id)
        if not os.path.isdir(path):
            raise CSIError(f"snapshot {snapshot_id!r} not found")
        shutil.rmtree(path)
        meta = os.path.join(self._snap_dir(), f"{snapshot_id}.meta.json")
        if os.path.exists(meta):
            os.unlink(meta)

    def list_snapshots(self) -> list[dict]:
        out = []
        snap_root = self._snap_dir()
        for snap_id in sorted(os.listdir(snap_root)):
            if not os.path.isdir(os.path.join(snap_root, snap_id)):
                continue  # sibling .meta.json files
            try:
                with open(os.path.join(
                    snap_root, f"{snap_id}.meta.json"
                )) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                # missing/corrupt metadata must not break listing the
                # rest; the snapshot itself is still intact
                out.append({"snapshot_id": snap_id, "ready": True})
        return out

    def node_get_info(self):
        return {"node_id": f"fake-{os.uname().nodename}"}

    def node_stage(self, ctx: StageContext) -> None:
        backing = self._backing(ctx.external_id or ctx.volume_id)
        os.makedirs(os.path.dirname(ctx.staging_path), exist_ok=True)
        with self._lock:
            if not os.path.lexists(ctx.staging_path):
                os.symlink(backing, ctx.staging_path)
            self.staged[ctx.staging_path] = ctx.volume_id

    def node_unstage(self, volume_id: str, staging_path: str) -> None:
        with self._lock:
            if os.path.islink(staging_path):
                os.unlink(staging_path)
            self.staged.pop(staging_path, None)

    def node_publish(self, ctx: StageContext) -> None:
        if ctx.staging_path not in self.staged:
            raise CSIError(f"volume {ctx.volume_id} not staged")
        os.makedirs(os.path.dirname(ctx.target_path), exist_ok=True)
        with self._lock:
            if not os.path.lexists(ctx.target_path):
                os.symlink(os.path.realpath(ctx.staging_path), ctx.target_path)
            self.published[ctx.target_path] = ctx.volume_id

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        with self._lock:
            if os.path.islink(target_path):
                os.unlink(target_path)
            elif os.path.isdir(target_path):
                shutil.rmtree(target_path, ignore_errors=True)
            self.published.pop(target_path, None)


# -- external plugin transport (mirrors drivers/plugin.py) -------------

HANDSHAKE_PREFIX = "NOMAD_TPU_PLUGIN|1|"


class _CSIEndpoint:
    """RPC surface wrapping a concrete CSIPlugin (plugin side)."""

    def __init__(self, plugin: CSIPlugin) -> None:
        self.plugin = plugin

    def plugin_info(self, args):
        info = self.plugin.plugin_info()
        return {
            "name": info.name, "version": info.version,
            "controller": info.controller, "node": info.node,
        }

    def probe(self, args):
        return self.plugin.probe()

    def controller_publish(self, args):
        return self.plugin.controller_publish(
            args["volume_id"], args["external_id"], args["node_id"],
            args["read_only"],
        )

    def controller_unpublish(self, args):
        self.plugin.controller_unpublish(
            args["volume_id"], args["external_id"], args["node_id"]
        )

    def validate_volume(self, args):
        self.plugin.validate_volume(
            args["volume_id"], args["external_id"], args["access_mode"],
            args["attachment_mode"],
        )

    def node_get_info(self, args):
        return self.plugin.node_get_info()

    def create_volume(self, args):
        return self.plugin.create_volume(args["name"], args.get("params") or {})

    def delete_volume(self, args):
        self.plugin.delete_volume(args["external_id"])

    def create_snapshot(self, args):
        return self.plugin.create_snapshot(
            args["external_id"], args.get("name", ""),
            args.get("params") or {},
        )

    def delete_snapshot(self, args):
        self.plugin.delete_snapshot(args["snapshot_id"])

    def list_snapshots(self, args):
        return self.plugin.list_snapshots()

    def _ctx(self, args) -> StageContext:
        return StageContext(**args["ctx"])

    def node_stage(self, args):
        self.plugin.node_stage(self._ctx(args))

    def node_unstage(self, args):
        self.plugin.node_unstage(args["volume_id"], args["staging_path"])

    def node_publish(self, args):
        self.plugin.node_publish(self._ctx(args))

    def node_unpublish(self, args):
        self.plugin.node_unpublish(args["volume_id"], args["target_path"])


def serve_csi_plugin(plugin: CSIPlugin) -> None:
    """CSI-plugin-process main (same contract as drivers.plugin.serve_plugin:
    handshake on stdout, die on stdin EOF)."""
    from ..rpc import RPCServer

    server = RPCServer(host="127.0.0.1", port=0)
    server.register("CSI", _CSIEndpoint(plugin))
    server.start()
    host, port = server.addr
    sys.stdout.write(f"{HANDSHAKE_PREFIX}{host}:{port}\n")
    sys.stdout.flush()
    try:
        while sys.stdin.readline():
            pass
    except (KeyboardInterrupt, OSError):
        pass
    server.shutdown()


class ExternalCSIPlugin(CSIPlugin):
    """Parent-side proxy to a CSI plugin process (reference:
    plugins/csi/client.go over gRPC; here the repo's RPC fabric)."""

    def __init__(self, name: str, factory_ref: str) -> None:
        from ..rpc import ConnPool

        self.name = name
        self.factory_ref = factory_ref
        self._proc: Optional[subprocess.Popen] = None
        self._addr: Optional[tuple[str, int]] = None
        self._pool = ConnPool()
        self._lock = threading.Lock()

    def _ensure_running(self) -> tuple[str, int]:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return self._addr  # type: ignore[return-value]
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "nomad_tpu.plugins.csi",
                 self.factory_ref],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                text=True,
            )
            line = self._proc.stdout.readline().strip()  # type: ignore[union-attr]
            if not line.startswith(HANDSHAKE_PREFIX):
                raise CSIError(f"bad CSI plugin handshake: {line!r}")
            host, _, port = line[len(HANDSHAKE_PREFIX):].partition(":")
            self._addr = (host, int(port))
            return self._addr

    def shutdown_plugin(self) -> None:
        with self._lock:
            if self._proc is not None:
                try:
                    self._proc.stdin.close()  # type: ignore[union-attr]
                    self._proc.wait(timeout=5)
                except Exception:
                    self._proc.kill()
                self._proc = None

    def _call(self, method: str, args=None, timeout_s: float = 30.0):
        from ..rpc import RPCError

        addr = self._ensure_running()
        try:
            return self._pool.call(addr, method, args, timeout_s=timeout_s)
        except RPCError as e:
            raise CSIError(str(e)) from None

    def plugin_info(self) -> CSIPluginInfo:
        d = self._call("CSI.plugin_info")
        return CSIPluginInfo(**d)

    def probe(self) -> bool:
        try:
            return bool(self._call("CSI.probe", timeout_s=5.0))
        except CSIError:
            return False

    def controller_publish(self, volume_id, external_id, node_id, read_only):
        return self._call("CSI.controller_publish", {
            "volume_id": volume_id, "external_id": external_id,
            "node_id": node_id, "read_only": read_only,
        })

    def controller_unpublish(self, volume_id, external_id, node_id):
        self._call("CSI.controller_unpublish", {
            "volume_id": volume_id, "external_id": external_id,
            "node_id": node_id,
        })

    def validate_volume(self, volume_id, external_id, access_mode,
                        attachment_mode):
        self._call("CSI.validate_volume", {
            "volume_id": volume_id, "external_id": external_id,
            "access_mode": access_mode, "attachment_mode": attachment_mode,
        })

    def node_get_info(self):
        return self._call("CSI.node_get_info")

    def create_volume(self, name, params):
        return self._call(
            "CSI.create_volume", {"name": name, "params": params}
        )

    def delete_volume(self, external_id):
        self._call("CSI.delete_volume", {"external_id": external_id})

    def create_snapshot(self, external_id, name, params):
        return self._call("CSI.create_snapshot", {
            "external_id": external_id, "name": name, "params": params,
        })

    def delete_snapshot(self, snapshot_id):
        self._call("CSI.delete_snapshot", {"snapshot_id": snapshot_id})

    def list_snapshots(self):
        return self._call("CSI.list_snapshots")

    def _wire_ctx(self, ctx: StageContext) -> dict:
        return {"ctx": {
            "volume_id": ctx.volume_id, "external_id": ctx.external_id,
            "staging_path": ctx.staging_path, "target_path": ctx.target_path,
            "read_only": ctx.read_only, "access_mode": ctx.access_mode,
            "attachment_mode": ctx.attachment_mode, "context": ctx.context,
        }}

    def node_stage(self, ctx: StageContext) -> None:
        self._call("CSI.node_stage", self._wire_ctx(ctx))

    def node_unstage(self, volume_id: str, staging_path: str) -> None:
        self._call("CSI.node_unstage", {
            "volume_id": volume_id, "staging_path": staging_path,
        })

    def node_publish(self, ctx: StageContext) -> None:
        self._call("CSI.node_publish", self._wire_ctx(ctx))

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        self._call("CSI.node_unpublish", {
            "volume_id": volume_id, "target_path": target_path,
        })


def _main() -> None:
    import importlib

    if len(sys.argv) != 2 or ":" not in sys.argv[1]:
        sys.stderr.write(
            "usage: python -m nomad_tpu.plugins.csi module:Class\n"
        )
        sys.exit(2)
    mod_name, _, cls_name = sys.argv[1].partition(":")
    mod = importlib.import_module(mod_name)
    serve_csi_plugin(getattr(mod, cls_name)())


if __name__ == "__main__":
    _main()
