"""Embedded web UI.

Reference: ui/ (a 389-file Ember app). The tpu-native build ships a
dependency-free single-page app embedded in the agent binary-equivalent:
hash-routed views over the same JSON API the SDK uses (jobs, job detail
with allocs/evals/deployments, nodes + node detail, allocations, evals,
services, CSI plugins, topology, servers), 5s auto-refresh, ACL token
entry stored in localStorage and sent as X-Nomad-Token. Served at /ui by
agent/http.py (GET / redirects there, like the reference).
"""

INDEX_HTML = r"""<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root {
  --bg: #0f1419; --panel: #161d26; --line: #233041; --fg: #d8e1ea;
  --dim: #8496a8; --accent: #25ba81; --warn: #e0a458; --bad: #e06c75;
  --info: #61afef;
}
* { box-sizing: border-box; }
body { margin: 0; background: var(--bg); color: var(--fg);
  font: 14px/1.5 -apple-system, "Segoe UI", Roboto, sans-serif; }
header { display: flex; align-items: center; gap: 18px;
  padding: 10px 20px; background: var(--panel);
  border-bottom: 1px solid var(--line); position: sticky; top: 0; }
header .logo { font-weight: 700; color: var(--accent);
  letter-spacing: .5px; }
nav a { color: var(--dim); text-decoration: none; margin-right: 14px; }
nav a.active, nav a:hover { color: var(--fg); }
#token { background: var(--bg);
  border: 1px solid var(--line); color: var(--fg); padding: 4px 8px;
  border-radius: 4px; width: 180px; }
#search { margin-left: auto; background: var(--bg);
  border: 1px solid var(--line); color: var(--fg); padding: 4px 8px;
  border-radius: 4px; width: 240px; }
#searchresults { position: fixed; right: 210px; top: 44px; z-index: 10;
  background: var(--panel); border: 1px solid var(--line);
  border-radius: 0 0 6px 6px; max-width: 420px; max-height: 70vh;
  overflow-y: auto; }
#searchresults a { display: block; padding: 5px 12px; }
#searchresults .ctx { color: var(--dim); font-size: 11px;
  text-transform: uppercase; padding: 5px 12px 0; }
main { padding: 20px; max-width: 1200px; margin: 0 auto; }
h1 { font-size: 18px; margin: 0 0 14px; }
h2 { font-size: 15px; margin: 22px 0 8px; color: var(--dim); }
table { width: 100%; border-collapse: collapse; background: var(--panel);
  border: 1px solid var(--line); border-radius: 6px; overflow: hidden; }
th, td { text-align: left; padding: 7px 12px;
  border-bottom: 1px solid var(--line); }
th { color: var(--dim); font-weight: 600; font-size: 12px;
  text-transform: uppercase; letter-spacing: .4px; }
tr:last-child td { border-bottom: none; }
tbody tr:hover { background: #1b2430; }
a { color: var(--info); text-decoration: none; }
a:hover { text-decoration: underline; }
.pill { display: inline-block; padding: 1px 9px; border-radius: 10px;
  font-size: 12px; border: 1px solid var(--line); }
.ok { color: var(--accent); border-color: var(--accent); }
.warn { color: var(--warn); border-color: var(--warn); }
.bad { color: var(--bad); border-color: var(--bad); }
.dim { color: var(--dim); }
.kv { display: grid; grid-template-columns: 220px 1fr; gap: 4px 16px;
  background: var(--panel); border: 1px solid var(--line);
  border-radius: 6px; padding: 12px 16px; }
.kv dt { color: var(--dim); } .kv dd { margin: 0; word-break: break-all; }
#err { color: var(--bad); margin-bottom: 10px; white-space: pre-wrap; }
.bar { display: inline-block; height: 10px; background: var(--accent);
  border-radius: 2px; vertical-align: middle; }
.barbg { display: inline-block; width: 120px; height: 10px;
  background: var(--line); border-radius: 2px; vertical-align: middle; }
code { background: var(--bg); padding: 1px 5px; border-radius: 3px; }
textarea, #xin { width: 100%; background: var(--bg); color: var(--fg);
  border: 1px solid var(--line); border-radius: 6px; padding: 10px;
  font: 13px/1.45 ui-monospace, "SF Mono", Menlo, monospace; }
textarea { min-height: 320px; resize: vertical; }
button { background: var(--accent); color: #08110d; border: none;
  padding: 7px 16px; border-radius: 5px; font-weight: 600;
  cursor: pointer; margin-right: 8px; }
button.alt { background: var(--panel); color: var(--fg);
  border: 1px solid var(--line); }
button.danger { background: var(--bad); color: #140a0b; }
#term { background: #06090d; border: 1px solid var(--line);
  border-radius: 6px; padding: 12px; min-height: 260px; max-height: 420px;
  overflow-y: auto; white-space: pre-wrap; word-break: break-all;
  font: 13px/1.4 ui-monospace, "SF Mono", Menlo, monospace; }
#runout { white-space: pre-wrap; background: var(--panel);
  border: 1px solid var(--line); border-radius: 6px; padding: 12px;
  font: 13px/1.45 ui-monospace, Menlo, monospace; }
</style>
</head>
<body>
<header>
  <span class="logo">nomad-tpu</span>
  <nav id="nav"></nav>
  <input id="search" placeholder="search (jobs, nodes, allocs…)"
    title="prefix search across the cluster">
  <input id="token" placeholder="ACL token" title="X-Nomad-Token">
</header>
<div id="searchresults"></div>
<main>
  <div id="err"></div>
  <div id="view">loading…</div>
</main>
<script>
"use strict";
const $ = (s) => document.querySelector(s);
const NAV = [
  ["jobs", "Jobs"], ["run", "Run"], ["deployments", "Deployments"],
  ["nodes", "Clients"], ["allocs", "Allocations"],
  ["evals", "Evaluations"], ["services", "Services"],
  ["storage", "Storage"], ["topology", "Topology"],
  ["servers", "Servers"],
];
$("#nav").innerHTML = NAV.map(([r, t]) =>
  `<a href="#/${r}" data-route="${r}">${t}</a>`).join("");
const searchInput = $("#search");
const searchBox = $("#searchresults");
let searchTimer = null;
let searchGen = 0;
searchInput.addEventListener("input", () => {
  clearTimeout(searchTimer);
  const prefix = searchInput.value.trim();
  const gen = ++searchGen;  // invalidates any in-flight response
  if (!prefix) { searchBox.innerHTML = ""; return; }
  searchTimer = setTimeout(async () => {
    let out;
    try {
      // namespace-scoped like the reference UI's search (matches carry
      // no namespace, so cross-namespace hits couldn't be routed);
      // list pages remain the cross-namespace view
      out = await api("/v1/search", {
        method: "POST", body: { Prefix: prefix, Context: "all" } });
    } catch (_) { return; }
    // a newer keystroke (or a clear) won while this was in flight
    if (gen !== searchGen) return;
    const routeOf = {
      jobs: (id) => `#/jobs/default/${id}`,
      nodes: (id) => `#/nodes/${id}`,
      allocs: (id) => `#/allocs/${id}`,
      deployments: () => `#/deployments`,
      evals: () => `#/evals`,
      volumes: () => `#/storage`,
      namespaces: null,  // list-only context, no page to land on
    };
    let html = "";
    for (const [ctx, ids] of Object.entries(out.Matches || {})) {
      if (!ids || !ids.length) continue;
      html += `<div class="ctx">${esc(ctx)}</div>`;
      for (const id of ids.slice(0, 8)) {
        const fn = routeOf[ctx];
        html += fn
          ? `<a href="${fn(encodeURIComponent(id))}">${esc(id)}</a>`
          : `<span class="dim" style="display:block;padding:5px 12px">`
            + `${esc(id)}</span>`;
      }
    }
    searchBox.innerHTML = html;
  }, 200);
});
function clearSearch() {
  searchGen++;
  searchBox.innerHTML = "";
}
searchBox.addEventListener("click", () => {
  clearSearch(); searchInput.value = "";
});
// dismiss on navigation, Escape, or clicking anywhere else
window.addEventListener("hashchange", clearSearch);
searchInput.addEventListener("keydown", (ev) => {
  if (ev.key === "Escape") { searchInput.value = ""; clearSearch(); }
});
document.addEventListener("click", (ev) => {
  if (ev.target !== searchInput && !searchBox.contains(ev.target))
    clearSearch();
});
const tokenInput = $("#token");
tokenInput.value = localStorage.getItem("nomad_token") || "";
tokenInput.addEventListener("change", () => {
  localStorage.setItem("nomad_token", tokenInput.value);
  render();
});

async function api(path, opts) {
  const headers = {};
  const tok = localStorage.getItem("nomad_token") || "";
  if (tok) headers["X-Nomad-Token"] = tok;
  let init = { headers };
  if (opts && opts.body !== undefined) {
    headers["Content-Type"] = "application/json";
    init = { method: opts.method || "POST", headers,
             body: JSON.stringify(opts.body) };
  } else if (opts && opts.method) {
    init = { method: opts.method, headers };
  }
  const resp = await fetch(path, init);
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok) throw new Error(`${path}: ${body.error || resp.status}`);
  return body;
}

const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;",
    "'":"&#39;"}[c]));
const short = (id) => esc(String(id || "").slice(0, 8));
function pill(status) {
  const cls = {
    running: "ok", ready: "ok", passing: "ok", complete: "ok",
    successful: "ok", healthy: "ok", true: "ok",
    pending: "warn", initializing: "warn", blocked: "warn",
    paused: "warn", critical: "bad", failed: "bad", dead: "bad",
    down: "bad", lost: "bad", false: "bad",
  }[String(status)] || "dim";
  return `<span class="pill ${cls}">${esc(status)}</span>`;
}
function table(headers, rows) {
  if (!rows.length) return `<p class="dim">none</p>`;
  return `<table><thead><tr>${headers.map((h) =>
    `<th>${h}</th>`).join("")}</tr></thead><tbody>${rows.map((r) =>
    `<tr>${r.map((c) => `<td>${c}</td>`).join("")}</tr>`).join("")
  }</tbody></table>`;
}
function pluginsTable(plugins) {
  return table(
    ["ID", "Controllers Healthy", "Nodes Healthy"],
    plugins.map((p) => [
      esc(p.id),
      `${p.controllers_healthy}/${p.controllers_expected}`,
      `${p.nodes_healthy}/${p.nodes_expected}`,
    ]));
}
function kv(pairs) {
  return `<dl class="kv">${pairs.map(([k, v]) =>
    `<dt>${esc(k)}</dt><dd>${v}</dd>`).join("")}</dl>`;
}

const views = {
  async jobs() {
    const jobs = await api("/v1/jobs?namespace=*");
    return `<h1>Jobs</h1>` + table(
      ["ID", "Namespace", "Type", "Priority", "Status"],
      jobs.map((j) => [
        `<a href="#/jobs/${esc(j.namespace)}/${esc(j.id)}">${esc(j.id)}</a>`,
        esc(j.namespace), esc(j.type), j.priority, pill(j.status),
      ]));
  },

  async job(ns, id) {
    const [job, allocs, evals] = await Promise.all([
      api(`/v1/job/${id}?namespace=${ns}`),
      api(`/v1/job/${id}/allocations?namespace=${ns}`),
      api(`/v1/job/${id}/evaluations?namespace=${ns}`),
    ]);
    setTimeout(() => {
      const b = $("#stopbtn");
      if (b) b.onclick = () => stopJob(job.namespace, job.id);
    }, 0);
    let html = `<h1>${esc(job.name || job.id)} ${pill(job.status)}
      <button class="danger" style="float:right" id="stopbtn">
        Stop</button></h1>`;
    html += kv([
      ["ID", esc(job.id)], ["Namespace", esc(job.namespace)],
      ["Type", esc(job.type)], ["Priority", job.priority],
      ["Datacenters", esc((job.datacenters || []).join(", "))],
      ["Version", job.version],
    ]);
    html += `<h2>Task Groups</h2>` + table(
      ["Name", "Count", "Tasks", "Volumes"],
      (job.task_groups || []).map((g) => [
        esc(g.name), g.count,
        esc((g.tasks || []).map((t) => `${t.name} (${t.driver})`)
          .join(", ")),
        esc(Object.keys(g.volumes || {}).join(", ") || "-"),
      ]));
    html += `<h2>Allocations</h2>` + table(
      ["ID", "Group", "Node", "Desired", "Status"],
      allocs.map((a) => [
        `<a href="#/allocs/${esc(a.id)}">${short(a.id)}</a>`,
        esc(a.task_group), short(a.node_id),
        esc(a.desired_status), pill(a.client_status),
      ]));
    html += `<h2>Evaluations</h2>` + table(
      ["ID", "Triggered By", "Status"],
      evals.map((e) => [short(e.id), esc(e.triggered_by),
        pill(e.status)]));
    return html;
  },

  async run() {
    const saved = sessionStorage.getItem("nomad_jobspec") ||
      `job "example" {\n  group "web" {\n    count = 1\n    task "app" {\n      driver = "mock"\n      config {}\n    }\n  }\n}\n`;
    setTimeout(() => {
      const ta = $("#jobsrc");
      if (!ta) return;
      ta.value = saved;
      ta.addEventListener("input", () =>
        sessionStorage.setItem("nomad_jobspec", ta.value));
      $("#btnplan").addEventListener("click", () => planJob());
      $("#btnrun").addEventListener("click", () => runJob());
    }, 0);
    return `<h1>Run Job</h1>
      <p class="dim">Paste an HCL jobspec; Plan dry-runs the scheduler
      against live state, Run submits it.</p>
      <textarea id="jobsrc" spellcheck="false"></textarea>
      <p style="margin:12px 0">
        <button id="btnrun">Run</button>
        <button id="btnplan" class="alt">Plan</button>
      </p>
      <div id="runout" class="dim">no output yet</div>`;
  },

  async deployments() {
    const deps = await api("/v1/deployments");
    setTimeout(() => {
      document.querySelectorAll("[data-dep-act]").forEach((b) => {
        b.onclick = async () => {
          const [act, id] = b.dataset.depAct.split("|");
          try {
            await api(`/v1/deployment/${act.replace("resume", "pause")
              }/${id}`, { method: "PUT",
              body: act === "pause" ? { Pause: true }
                : act === "resume" ? { Pause: false } : {} });
          } catch (e) {
            $("#err").textContent = `${act} failed: ${e.message || e}`;
            return;
          }
          render();
        };
      });
    }, 0);
    return `<h1>Deployments</h1>` + table(
      ["ID", "Job", "Status", "Description", "Groups", "Actions"],
      deps.map((d) => {
        const groups = Object.entries(d.task_groups || {}).map(
          ([g, st]) =>
            `${esc(g)}: ${st.healthy_allocs ?? 0}/${st.desired_total
            } healthy` + (st.desired_canaries
              ? ` (${(st.placed_canaries || []).length}/${
                st.desired_canaries} canaries${st.promoted
                ? ", promoted" : ""})` : "")
        ).join("<br>");
        const active = ["running", "paused", "pending"].includes(
          d.status);
        const pauseAct = d.status === "paused"
          ? `resume|${esc(d.id)}` : `pause|${esc(d.id)}`;
        const pauseLabel = d.status === "paused" ? "Resume" : "Pause";
        const acts = active
          ? `<button data-dep-act="promote|${esc(d.id)}">Promote`
            + `</button> <button class="alt" data-dep-act=`
            + `"${pauseAct}">${pauseLabel}</button> <button `
            + `class="danger" data-dep-act="fail|${esc(d.id)}">`
            + `Fail</button>`
          : `<span class="dim">—</span>`;
        return [
          short(d.id),
          `<a href="#/jobs/${esc(d.namespace)}/${esc(d.job_id)}">`
            + `${esc(d.job_id)}</a>`,
          pill(d.status), esc(d.status_description || "-"),
          groups, acts,
        ];
      }));
  },

  async nodes() {
    const nodes = await api("/v1/nodes");
    return `<h1>Clients</h1>` + table(
      ["ID", "Name", "DC", "Class", "Drivers", "Eligibility", "Status"],
      nodes.map((n) => [
        `<a href="#/nodes/${esc(n.id)}">${short(n.id)}</a>`,
        esc(n.name), esc(n.datacenter), esc(n.node_class || "-"),
        esc(Object.keys(n.drivers || {}).join(", ")),
        esc(n.scheduling_eligibility), pill(n.status),
      ]));
  },

  async node(id) {
    const [node, allocs] = await Promise.all([
      api(`/v1/node/${id}`), api(`/v1/node/${id}/allocations`),
    ]);
    const res = node.resources || {};
    setTimeout(() => {
      const d = $("#ndrain"), e = $("#nelig");
      if (d) d.onclick = async () => {
        const enable = !node.drain_strategy;
        try {
          await api(`/v1/node/${node.id}/drain`, { method: "PUT",
            body: { DrainSpec: enable ? { Deadline: 3600e9 } : null } });
        } catch (err) {
          $("#err").textContent = `drain failed: ${err.message || err}`;
          return;
        }
        render();
      };
      if (e) e.onclick = async () => {
        const elig = node.scheduling_eligibility === "eligible"
          ? "ineligible" : "eligible";
        try {
          await api(`/v1/node/${node.id}/eligibility`, { method: "PUT",
            body: { Eligibility: elig } });
        } catch (err) {
          $("#err").textContent =
            `eligibility failed: ${err.message || err}`;
          return;
        }
        render();
      };
    }, 0);
    let html = `<h1>${esc(node.name)} ${pill(node.status)}
      <span style="float:right">
        <button class="alt" id="nelig">${
          node.scheduling_eligibility === "eligible"
            ? "Mark ineligible" : "Mark eligible"}</button>
        <button class="danger" id="ndrain">${
          node.drain_strategy ? "Stop drain" : "Drain"}</button>
      </span></h1>`;
    html += kv([
      ["ID", esc(node.id)], ["Datacenter", esc(node.datacenter)],
      ["Class", esc(node.node_class || "-")],
      ["CPU", `${res.cpu ?? "?"} MHz`],
      ["Memory", `${res.memory_mb ?? "?"} MB`],
      ["Disk", `${res.disk_mb ?? "?"} MB`],
      ["Eligibility", esc(node.scheduling_eligibility)],
      ["CSI plugins",
        esc(Object.keys(node.csi_plugins || {}).join(", ") || "-")],
    ]);
    html += `<h2>Allocations</h2>` + table(
      ["ID", "Job", "Group", "Desired", "Status"],
      allocs.map((a) => [
        `<a href="#/allocs/${esc(a.id)}">${short(a.id)}</a>`,
        esc(a.job_id), esc(a.task_group), esc(a.desired_status),
        pill(a.client_status),
      ]));
    html += `<h2>Attributes</h2>` + table(
      ["Key", "Value"],
      Object.entries(node.attributes || {}).sort()
        .map(([k, v]) => [esc(k), esc(v)]));
    return html;
  },

  async allocs() {
    const allocs = await api("/v1/allocations?namespace=*");
    return `<h1>Allocations</h1>` + table(
      ["ID", "Job", "Group", "Node", "Desired", "Status"],
      allocs.map((a) => [
        `<a href="#/allocs/${esc(a.id)}">${short(a.id)}</a>`,
        esc(a.job_id), esc(a.task_group), short(a.node_id),
        esc(a.desired_status), pill(a.client_status),
      ]));
  },

  async alloc(id) {
    const a = await api(`/v1/allocation/${id}`);
    let html = `<h1>Allocation ${short(a.id)} `
      + `${pill(a.client_status)}</h1>`;
    html += kv([
      ["ID", esc(a.id)], ["Job", esc(a.job_id)],
      ["Task Group", esc(a.task_group)], ["Node", esc(a.node_id)],
      ["Desired", esc(a.desired_status)],
      ["Eval", esc(a.eval_id)],
    ]);
    const states = a.task_states || {};
    html += `<h2>Tasks</h2>` + table(
      ["Task", "State", "Failed", "Restarts", "Last Event"],
      Object.entries(states).map(([name, st]) => {
        const ev = (st.events || []).slice(-1)[0] || {};
        return [esc(name), pill(st.state), pill(!!st.failed),
          st.restarts || 0,
          esc(ev.type ? `${ev.type} ${ev.details || ""}` : "-")];
      }));
    if (a.client_status === "running") {
      const tasks = Object.keys(states);
      setTimeout(() => {
        const b = $("#xconnect");
        if (b) b.onclick = () => execConnect(a.id);
      }, 0);
      html += `<h2>Exec</h2>
        <p>
          <select id="xtask">${tasks.map((t) =>
            `<option value="${esc(t)}">${esc(t)}</option>`).join("")}
          </select>
          <input id="xcmd" value="/bin/sh" style="width:220px;
            background:var(--bg);color:var(--fg);
            border:1px solid var(--line);border-radius:4px;
            padding:5px 8px">
          <button id="xconnect">Connect</button>
        </p>
        <div id="term" class="dim">not connected</div>
        <input id="xin" placeholder="stdin — Enter sends a line"
          style="margin-top:8px">`;
    }
    return html;
  },

  async evals() {
    const evals = await api("/v1/evaluations");
    return `<h1>Evaluations</h1>` + table(
      ["ID", "Job", "Type", "Triggered By", "Priority", "Status"],
      evals.slice(0, 200).map((e) => [
        short(e.id), esc(e.job_id), esc(e.type), esc(e.triggered_by),
        e.priority, pill(e.status),
      ]));
  },

  async services() {
    const svcs = await api("/v1/services?namespace=*");
    return `<h1>Services</h1>` + table(
      ["Name", "Namespace", "Tags", "Instances"],
      svcs.map((s) => [
        esc(s.service_name), esc(s.namespace),
        esc((s.tags || []).join(", ")), s.instances,
      ]));
  },

  async storage() {
    const [vols, plugins, namespaces] = await Promise.all([
      api("/v1/volumes?namespace=*").catch(() => []),
      api("/v1/plugins"),
      api("/v1/namespaces"),
    ]);
    let html = `<h1>Storage</h1><h2>Volumes</h2>` + table(
      ["ID", "Namespace", "Type", "Plugin", "Access Mode", "Claims"],
      (vols || []).map((v) => [
        esc(v.id), esc(v.namespace), esc(v.type),
        esc(v.plugin_id || "-"), esc(v.access_mode),
        Object.keys(v.claims || {}).length,
      ]));
    html += `<h2>CSI Plugins</h2>` + pluginsTable(plugins);
    html += `<h2>Namespaces</h2>` + table(
      ["Name", "Description"],
      namespaces.map((n) => [esc(n.name), esc(n.description || "-")]));
    return html;
  },

  async topology() {
    const [nodes, allocs] = await Promise.all([
      api("/v1/nodes"), api("/v1/allocations?namespace=*"),
    ]);
    const byNode = {};
    for (const a of allocs) {
      if (a.client_status !== "running") continue;
      (byNode[a.node_id] = byNode[a.node_id] || []).push(a);
    }
    const rows = nodes.map((n) => {
      const na = byNode[n.id] || [];
      const res = n.resources || {};
      const used = na.reduce((s, a) => {
        const tasks = (a.resources || {}).tasks || {};
        return s + Object.values(tasks)
          .reduce((t, r) => t + (r.cpu || 0), 0);
      }, 0);
      const cap = res.cpu || 1;
      const pct = Math.min(100, Math.round(100 * used / cap));
      return [
        `<a href="#/nodes/${esc(n.id)}">${short(n.id)}</a>`,
        esc(n.datacenter), pill(n.status), na.length,
        `<span class="barbg"><span class="bar" style="width:${pct}%">` +
        `</span></span> <span class="dim">${pct}% cpu</span>`,
      ];
    });
    return `<h1>Topology</h1>` +
      table(["Node", "DC", "Status", "Running Allocs", "CPU"], rows);
  },

  async servers() {
    const [peers, leader] = await Promise.all([
      api("/v1/operator/raft/configuration"),
      api("/v1/status/leader").catch(() => "?"),
    ]);
    return `<h1>Servers</h1>` +
      `<p class="dim">leader: <code>${esc(leader)}</code></p>` +
      table(["ID", "Address", "Leader"],
        peers.map((p) => [
          esc(p.id), esc((p.address || []).join(":")),
          pill(!!p.leader),
        ]));
  },
};

async function parseJob() {
  const src = $("#jobsrc").value;
  const out = await api("/v1/jobs/parse", { body: { JobHCL: src } });
  return out.Job;
}
async function planJob() {
  const el = $("#runout");
  el.textContent = "planning…";
  try {
    const job = await parseJob();
    const plan = await api(
      `/v1/job/${encodeURIComponent(job.id)}/plan`,
      { method: "PUT", body: { Job: job, Diff: true } });
    const ann = plan.Annotations || plan.annotations || {};
    const tg = ann.DesiredTGUpdates || ann.desired_tg_updates || {};
    let lines = [`plan for ${job.id}:`];
    for (const [g, u] of Object.entries(tg)) {
      lines.push(
        `  group ${g}: +${u.Place ?? u.place ?? 0} place, ` +
        `${u.DestructiveUpdate ?? u.destructive ?? 0} destructive, ` +
        `${u.InPlaceUpdate ?? u.in_place_update ?? 0} in-place, ` +
        `${u.Stop ?? u.stop ?? 0} stop, ` +
        `${u.Ignore ?? u.ignore ?? 0} ignore`);
    }
    if (plan.FailedTGAllocs && Object.keys(plan.FailedTGAllocs).length)
      lines.push(`  FAILED groups: ` +
        Object.keys(plan.FailedTGAllocs).join(", "));
    el.textContent = lines.join("\n");
  } catch (e) { el.textContent = String(e.message || e); }
}
async function runJob() {
  const el = $("#runout");
  el.textContent = "submitting…";
  try {
    const job = await parseJob();
    const out = await api("/v1/jobs", { method: "PUT",
      body: { Job: job } });
    const evalId = typeof out === "string" ? out :
      (out.EvalID || out.eval_id || "");
    el.textContent = `submitted: eval ${evalId}`;
    location.hash = `#/jobs/${job.namespace || "default"}/${job.id}`;
  } catch (e) { el.textContent = String(e.message || e); }
}
async function stopJob(ns, id) {
  if (!confirm(`Stop job ${id}?`)) return;
  try {
    await api(
      `/v1/job/${encodeURIComponent(id)}?namespace=` +
      encodeURIComponent(ns), { method: "DELETE" });
  } catch (e) {
    $("#err").textContent = `stop failed: ${e.message || e}`;
    return;
  }
  render();
}

// -- browser exec terminal (WebSocket to the agent's exec bridge) ------
let execWs = null;
function execConnect(allocId) {
  const term = $("#term");
  const task = $("#xtask").value;
  const cmd = $("#xcmd").value || "/bin/sh";
  term.textContent = "";
  // a pending auto-refresh would re-render and detach this terminal
  clearTimeout(refreshTimer);
  if (execWs) { try { execWs.close(); } catch (_) {} }
  const tok = localStorage.getItem("nomad_token") || "";
  const proto = location.protocol === "https:" ? "wss" : "ws";
  const params = new URLSearchParams();
  for (const part of cmd.split(" ").filter(Boolean))
    params.append("command", part);
  if (task) params.set("task", task);
  if (tok) params.set("token", tok);
  const ws = new WebSocket(
    `${proto}://${location.host}/v1/client/allocation/${allocId}` +
    `/exec?${params}`);
  execWs = ws;
  const append = (txt) => {
    term.textContent += txt;
    term.scrollTop = term.scrollHeight;
  };
  ws.onopen = () => append(`connected: ${cmd}\n`);
  ws.onmessage = (ev) => {
    try {
      const m = JSON.parse(ev.data);
      if (m.stdout) append(atob(m.stdout));
      if (m.error) append(`\n[error] ${m.error}\n`);
      if (m.exit) append("\n[session ended]\n");
    } catch (_) {}
  };
  ws.onclose = () => {
    append("\n[disconnected]\n");
    // session over: let the alloc page resume its auto-refresh cycle
    clearTimeout(refreshTimer);
    refreshTimer = setTimeout(render, 5000);
  };
  const input = $("#xin");
  input.onkeydown = (ev) => {
    if (ev.key !== "Enter") return;
    const line = input.value + "\n";
    input.value = "";
    append(line);
    if (ws.readyState === 1)
      ws.send(JSON.stringify({ stdin: btoa(line) }));
  };
  input.focus();
}

let refreshTimer = null;
let renderGen = 0;
async function render() {
  const gen = ++renderGen;
  const hash = location.hash.replace(/^#\//, "") || "jobs";
  const parts = hash.split("/").map(decodeURIComponent);
  document.querySelectorAll("#nav a").forEach((a) =>
    a.classList.toggle("active", a.dataset.route === parts[0]));
  let fn, args;
  if (parts[0] === "jobs" && parts.length === 3) {
    fn = views.job; args = [parts[1], parts[2]];
  } else if (parts[0] === "nodes" && parts.length === 2) {
    fn = views.node; args = [parts[1]];
  } else if (parts[0] === "allocs" && parts.length === 2) {
    fn = views.alloc; args = [parts[1]];
  } else {
    fn = views[parts[0]] || views.jobs; args = [];
  }
  try {
    const html = await fn(...args);
    if (gen !== renderGen) return;  // a newer navigation won
    $("#err").textContent = "";
    $("#view").innerHTML = html;
  } catch (e) {
    if (gen !== renderGen) return;
    $("#err").textContent = String(e.message || e);
  }
  clearTimeout(refreshTimer);
  // the editor and a CONNECTED exec terminal must not be wiped by
  // auto-refresh; an alloc page without a live session still refreshes
  const termLive = execWs && execWs.readyState <= 1 &&
    parts[0] === "allocs" && parts.length === 2;
  if (parts[0] !== "run" && !termLive)
    refreshTimer = setTimeout(render, 5000);
}
window.addEventListener("hashchange", render);
render();
</script>
</body>
</html>
"""
