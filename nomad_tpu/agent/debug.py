"""Agent profiling/debug surface.

Reference: command/agent/pprof/ (/v1/agent/pprof/goroutine|profile|trace,
gated behind enable_debug/ACL agent:write) and command/operator_debug.go
(the `operator debug` bundle). Python analogs:

  * goroutine → a dump of every thread's stack (sys._current_frames)
  * profile   → a cProfile capture over `seconds` of wall time
  * heap      → object counts by type (gc) + RSS from /proc

The handlers return text/JSON rather than pprof protobufs — the point is
self-observability (VERDICT r2 §5.1: a system whose thesis is scheduler
throughput must be able to profile itself), not Go toolchain compat.
"""

from __future__ import annotations

import gc
import io
import os
import sys
import threading
import time
import traceback


def thread_dump() -> str:
    """Every live thread's stack, goroutine-dump style."""
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    out = io.StringIO()
    for ident, frame in sorted(frames.items()):
        t = names.get(ident)
        name = t.name if t else "?"
        daemon = " daemon" if (t and t.daemon) else ""
        out.write(f"thread {ident} [{name}]{daemon}:\n")
        out.write("".join(traceback.format_stack(frame)))
        out.write("\n")
    return out.getvalue()


def cpu_profile(seconds: float = 2.0, top: int = 50,
                interval_s: float = 0.01) -> str:
    """Statistical profile of EVERY thread: sample sys._current_frames()
    on an interval for `seconds` and aggregate frame counts.

    cProfile's hook is per-thread-state (it would only see this handler
    sleeping), so a wall-clock sampler is the honest whole-process
    profiler — the same shape as the reference's pprof CPU profile.
    """
    if not (seconds == seconds):  # NaN guard before clamping
        seconds = 2.0
    seconds = max(0.1, min(seconds, 30.0))
    counts: dict[tuple, int] = {}
    samples = 0
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            f = frame
            while f is not None:
                key = (
                    f.f_code.co_filename,
                    f.f_lineno if f is frame else f.f_code.co_firstlineno,
                    f.f_code.co_name,
                    f is frame,  # leaf vs ancestor
                )
                counts[key] = counts.get(key, 0) + 1
                f = f.f_back
        samples += 1
        time.sleep(interval_s)
    leaf = [(k, c) for k, c in counts.items() if k[3]]
    cum = [(k, c) for k, c in counts.items() if not k[3]]
    out = io.StringIO()
    out.write(
        f"wall-clock sampling profile: {samples} samples over "
        f"{seconds:.1f}s ({interval_s*1000:.0f}ms interval), all threads\n\n"
    )
    out.write("self (leaf frames):\n")
    for (fn, line, name, _), c in sorted(leaf, key=lambda kv: -kv[1])[:top]:
        out.write(f"  {c:6d} ({100*c/max(samples,1):5.1f}%)  "
                  f"{name}  {fn}:{line}\n")
    out.write("\ncumulative (on-stack):\n")
    for (fn, line, name, _), c in sorted(cum, key=lambda kv: -kv[1])[:top]:
        out.write(f"  {c:6d}  {name}  {fn}:{line}\n")
    return out.getvalue()


def heap_summary(top: int = 40) -> dict:
    counts: dict[str, int] = {}
    for obj in gc.get_objects():
        name = type(obj).__name__
        counts[name] = counts.get(name, 0) + 1
    rss = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    top_types = sorted(counts.items(), key=lambda kv: -kv[1])[:top]
    return {
        "rss_bytes": rss,
        "gc_objects": sum(counts.values()),
        "gc_counts": list(gc.get_count()),
        "top_types": [{"type": t, "count": c} for t, c in top_types],
        "threads": threading.active_count(),
    }


def debug_bundle(api) -> dict:
    """Collect the `operator debug` capture through the public API
    (reference command/operator_debug.go gathers the same surfaces)."""
    bundle: dict = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}

    def grab(name, fn):
        try:
            bundle[name] = fn()
        except Exception as e:  # capture what we can, note what we can't
            bundle[name] = {"error": str(e)}

    grab("agent_self", lambda: api.agent.self())
    grab("members", lambda: api.agent.members())
    grab("metrics", lambda: api.agent.metrics())
    grab("regions", lambda: api.status.regions())
    grab("leader", lambda: api.status.leader())
    grab("peers", lambda: api.status.peers())
    grab("nodes", lambda: api.get("/v1/nodes"))
    grab("jobs", lambda: api.get("/v1/jobs"))
    grab("allocations", lambda: api.get("/v1/allocations"))
    grab("evaluations", lambda: api.get("/v1/evaluations"))
    grab("deployments", lambda: api.get("/v1/deployments"))
    grab("namespaces", lambda: api.namespaces.list())
    grab("threads", lambda: api.get("/v1/agent/pprof/goroutine"))
    grab("heap", lambda: api.get("/v1/agent/pprof/heap"))
    # solver observability: compile ledger / occupancy / transfers /
    # device memory — one archive now diagnoses a slow solve offline
    grab("solver", lambda: api.agent.solver_status())
    grab("traces", lambda: api.traces.list(limit=50))
    # host profiler: span-correlated CPU attribution + GC/lock/runtime
    # telemetry, and the collapsed stacks a flamegraph renders from —
    # "where does the host second go" answerable from the archive alone
    grab("profile", lambda: api.agent.profile_status())
    grab(
        "profile_stacks",
        lambda: {"collapsed": api.agent.profile_collapsed()},
    )
    # cluster-scope capture: every member's health/telemetry (raft
    # indices, depths, host CPU/RSS, per-source cost top-K) with
    # degraded members flagged — the `operator debug` analog of the
    # reference's autopilot-health grab
    grab("cluster_health", lambda: api.operator.cluster_health())
    # flight recorder: the incident index plus the journal tail — the
    # minutes-before-the-crash context (docs/incidents.md) travels in
    # the same archive as the point-in-time snapshots above
    grab("incidents", lambda: api.agent.incidents())
    grab("blackbox", lambda: api.agent.blackbox_status(journal=500))
    return bundle
