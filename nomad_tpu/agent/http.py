"""HTTP API server.

Reference: command/agent/http.go (~60 routes at :252-360, wrap() adds
region/blocking-query/auth handling), the per-resource endpoint files
(job_endpoint.go, node_endpoint.go, …), and the NDJSON event stream
endpoint (event_endpoint.go).

JSON convention: payloads are the codec wire form of the shared structs —
plain JSON with a `$t` type tag per struct, so the SDK decodes straight
back into typed dataclasses and third-party consumers still read ordinary
JSON. Blocking queries take `?index=N&wait=SECONDS` like the reference
and respond with the `X-Nomad-Index` header.
"""

from __future__ import annotations

import contextvars
import json
import logging
import re
import select
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from .. import codec, metrics
from ..server.server import ConflictError
from ..state.store import (
    TABLE_ALLOCS,
    TABLE_DEPLOYMENTS,
    TABLE_EVALS,
    TABLE_JOBS,
    TABLE_NODES,
)
from ..stream import SubscriptionClosedError

logger = logging.getLogger("nomad_tpu.http")

# per-request ?region= (reference: wrap() parses the region query param
# and every RPC carries it for cross-region forwarding)
_REQ_REGION = contextvars.ContextVar("nomad_http_region", default="")
_REQ_TOKEN = contextvars.ContextVar("nomad_http_token", default="")


class RawResponse:
    """A handler return that bypasses the JSON encode — raw bytes with an
    explicit content type (the Prometheus exposition endpoint)."""

    def __init__(self, data: bytes, content_type: str) -> None:
        self.data = data
        self.content_type = content_type


class HTTPError(Exception):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None) -> None:
        self.status = status
        self.message = message
        # 429/503 backoff hint: surfaced as the Retry-After header
        # (integer ceil per RFC 9110) AND a float `retry_after_s` field
        # in the JSON error body (sub-second precision for the SDK).
        self.retry_after = retry_after
        super().__init__(message)


_json_default = codec.json_default


class HTTPAgentServer:
    """Routes /v1/... onto a ClusterServer (and optionally a Client).

    Route handlers get (params, query, body, token) and return a
    JSON-able wire object (codec.to_wire applied to struct returns).
    """

    def __init__(
        self,
        cluster,  # ClusterServer
        client=None,  # optional co-located node agent
        host: str = "127.0.0.1",
        port: int = 0,
        acl_resolver=None,  # installed by the ACL layer (nomad_tpu/acl)
        enable_debug: bool = False,  # pprof off unless opted in (reference)
        tls_cert: str = "",  # PEM cert+key enable HTTPS (reference:
        tls_key: str = "",   # tls { http = true } agent stanza)
        on_keyring_rotate=None,  # fn(secret) — the Agent syncs its
                                 # in-memory config so a later SIGHUP
                                 # diff is computed against the LIVE
                                 # secret, not the boot-time one
    ) -> None:
        self.cluster = cluster
        self.client = client
        self.acl_resolver = acl_resolver
        self.enable_debug = enable_debug
        self.on_keyring_rotate = on_keyring_rotate
        # Per-namespace token buckets on the HTTP front door (disabled
        # until limits{} config sets a rate; SIGHUP-reconfigurable).
        from ..ratelimit import KeyedRateLimiter

        self.limiter = KeyedRateLimiter()
        self._relay_lock = threading.Lock()
        self._relay_active = 0
        # Cap concurrent client-relay sessions: each one ties up an HTTP
        # worker thread against a possibly-slow client agent; unbounded,
        # a burst of follow-streams starves every other route.
        self._relay_max = 64
        # Single-flight guard for /v1/agent/pprof/profile: a wall-clock
        # capture occupies its handler thread for `seconds`; overlapping
        # requests coalesce to 429 + Retry-After instead of each eating
        # a thread (satellite of the host-profiling layer).
        self._pprof_capture_lock = threading.Lock()
        self._pprof_busy_until = 0.0
        # /v1/agent/monitor level refcounting (see _serve_monitor)
        self._monitor_lock = threading.Lock()
        self._monitor_levels: list = []
        self._monitor_base_level = 0
        self._routes: list[tuple[str, re.Pattern, Callable]] = []
        self._register_routes()
        handler = self._make_handler()
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.tls = bool(tls_cert and tls_key)
        self._tls_ctx = None
        if self.tls:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            # kept for SIGHUP cert rotation: new handshakes pick up
            # material re-loaded into the live context (Agent.reload)
            self._tls_ctx = ctx
            # handshake must NOT run in the accept loop: a client that
            # connects and sends nothing would block serve_forever and
            # freeze the whole API. Deferred, the handshake happens on
            # first read in the per-connection worker thread, bounded
            # by the handler's socket timeout.
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket,
                server_side=True,
                do_handshake_on_connect=False,
            )
            # plaintext probes (health checkers, LBs) fail the deferred
            # handshake inside the handler thread; socketserver would
            # print a full traceback per connection — log one line
            base_handle_error = self._httpd.handle_error

            def handle_error(request, client_address, _base=base_handle_error):
                import sys as _sys

                exc = _sys.exc_info()[1]
                if isinstance(exc, (ssl.SSLError, ConnectionError)):
                    logger.debug(
                        "https %s: %s", client_address, exc
                    )
                    return
                _base(request, client_address)

            self._httpd.handle_error = handle_error
        self.addr = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-agent", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        # socketserver.shutdown() blocks on an event that only
        # serve_forever() sets — on a constructed-but-never-started
        # agent it would wait forever; just close the listener.
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def set_rate_limits(self, http_rate: float, http_burst: float = 0.0) -> None:
        """Configure (or SIGHUP-reconfigure) the per-namespace HTTP
        front-door token buckets. rate <= 0 disables."""
        self.limiter.configure(http_rate, http_burst)

    # Routes exempt from the front-door rate limit: the observability
    # and control surfaces an operator needs DURING overload (reading
    # shed/throttle metrics, traces, health, reload/debug) — throttling
    # the dashboards that diagnose a throttling event would blind the
    # operator exactly when they need to see.
    _THROTTLE_EXEMPT = (
        "/v1/agent",
        "/v1/metrics",
        "/v1/status",
        "/v1/operator",
        "/v1/traces",
        "/v1/solver",
        "/v1/profile",
        "/v1/event/stream",
        "/v1/acl",
        "/v1/blackbox",
        "/v1/incidents",
        "/v1/timeline",
    )

    @staticmethod
    def _throttle_ns(query: dict, raw_body: bytes) -> str:
        """The namespace to charge: ?namespace= when present, else the
        payload's object namespace (job register/plan and volume
        register carry it in the body, not the query — charging
        'default' for those would let one tenant's register storm
        starve everyone else's default bucket). The JSON parse runs
        only for body-bearing requests with no query namespace."""
        ns = query.get("namespace", [""])[0]
        if ns:
            return ns
        if raw_body:
            try:
                body = json.loads(raw_body)
                if isinstance(body, dict):
                    for key in ("Job", "Volume"):
                        obj = body.get(key)
                        if isinstance(obj, dict) and obj.get("namespace"):
                            return str(obj["namespace"])
                    if body.get("Namespace"):
                        return str(body["Namespace"])
            except ValueError:
                pass
        return "default"

    def _throttle_check(self, path: str, query: dict,
                        raw_body: bytes = b"") -> None:
        """Charge the request against its namespace's bucket; raises
        HTTPError 429 with Retry-After when over."""
        if not self.limiter.enabled or not path.startswith("/v1/"):
            return
        if path.startswith(self._THROTTLE_EXEMPT):
            return
        ns = self._throttle_ns(query, raw_body)
        wait = self.limiter.check(ns)
        if wait > 0:
            metrics.incr("nomad.http.throttled")
            raise HTTPError(
                429,
                f"rate limit exceeded for namespace {ns!r}",
                retry_after=wait,
            )

    def reload_tls(self, cert_file: str, key_file: str) -> bool:
        """Rotate the HTTPS certificate without dropping the listener:
        loading new material into the live SSLContext makes every
        SUBSEQUENT handshake present it while established connections
        finish on the old session (reference Agent.Reload →
        http.Server TLS config swap). No-op (False) when HTTPS is off —
        enabling TLS on a plaintext listener needs a restart, as in the
        reference."""
        if self._tls_ctx is None:
            return False
        self._tls_ctx.load_cert_chain(cert_file, key_file)
        return True

    # -- ACL helpers (second-stage, object-namespace-aware) ------------

    def _acl_for(self, token: str):
        """None ⇒ enforcement off or management. Raises on bad token."""
        if self.acl_resolver is None:
            return None
        try:
            acl = self.cluster.server.resolve_token(token)
        except PermissionError:
            raise HTTPError(401, "ACL token not found")
        if acl is None:
            raise HTTPError(401, "missing ACL token")
        return None if acl.is_management() else acl

    def _ns_guard(self, token: str, namespace: str, cap: str) -> None:
        """Check a capability against an OBJECT's namespace — the route
        pre-check only sees the query namespace, which need not match the
        object the handler acts on (cross-namespace escalation)."""
        acl = self._acl_for(token)
        if acl is not None and not acl.allow_namespace_op(namespace, cap):
            raise HTTPError(403, f"missing {cap!r} on namespace {namespace!r}")

    def _ns_filter(self, token: str, objs: list, cap: str) -> list:
        """Drop objects in namespaces the token can't read."""
        acl = self._acl_for(token)
        if acl is None:
            return objs
        return [
            o
            for o in objs
            if acl.allow_namespace_op(getattr(o, "namespace", "default"), cap)
        ]

    def _map_throttle_error(self, e: Exception) -> Optional[HTTPError]:
        """Queue-full / rate-limited rejections -> 429 with Retry-After,
        whether raised locally (RateLimitError / BrokerSaturatedError
        from an in-process dispatch) or arriving as a leader-forwarded
        RPCError string. Centralized in the handler's generic exception
        path so EVERY route maps correctly — these used to surface as
        500s, teaching clients to back off never."""
        from ..ratelimit import (
            RateLimitError,
            is_throttle_text,
            retry_after_from_text,
        )

        if isinstance(e, RateLimitError):
            return HTTPError(429, str(e), retry_after=e.retry_after_s)
        from ..rpc.client import RPCError

        if isinstance(e, RPCError) and is_throttle_text(str(e)):
            return HTTPError(
                429,
                str(e),
                retry_after=retry_after_from_text(str(e)) or 1.0,
            )
        return None

    def _map_forward_error(self, e: Exception):
        """KeyError/ValueError raised on THIS server map directly; the
        same errors raised on the LEADER arrive as RPCError strings —
        map both so followers return 404/400 instead of 500."""
        if isinstance(e, KeyError):
            return HTTPError(404, str(e))
        if isinstance(e, ValueError):
            return HTTPError(400, str(e))
        msg = str(e)
        if "KeyError" in msg or "not found" in msg:
            return HTTPError(404, msg)
        if "ValueError" in msg or "CSIError: invalid" in msg:
            # CSIError's own "invalid <thing>" rejections are client
            # errors; a bare "invalid" substring must NOT match (ids may
            # contain the word while the fault is server-side)
            return HTTPError(400, msg)
        return None

    def rpc_region(self, method: str, args):
        """rpc_self with the request's ?region= attached, so any route
        can address a federated region (reference: Region rides every
        RPC's QueryOptions/WriteRequest). The caller's token rides along
        so the TARGET region re-authorizes against its own ACL state."""
        region = _REQ_REGION.get()
        if (
            region
            and region != self.cluster.region
            and isinstance(args, dict)
            and "region" not in args
        ):
            args = {
                **args,
                "region": region,
                "__cross_region_token__": _REQ_TOKEN.get(),
            }
        return self.cluster.rpc_self(method, args)

    # -- routing -------------------------------------------------------

    def _register_routes(self) -> None:
        srv = self.cluster.server

        def other_region():
            """The request's ?region= when it names a DIFFERENT region
            (local-state read handlers then forward over RPC instead)."""
            region = _REQ_REGION.get()
            return region if region and region != self.cluster.region else ""

        def route(method: str, pattern: str, fn: Callable) -> None:
            # per-route latency label precomputed at registration: the
            # PATTERN (with named groups collapsed to :name), never the
            # raw request path — ids in the path would make the metric
            # name set unbounded
            label = (
                "nomad.http.request_seconds." + method + "."
                + re.sub(r"\(\?P<(\w+)>[^)]*\)", r":\1", pattern)
            )
            self._routes.append(
                (method, re.compile(f"^{pattern}$"), fn, label)
            )

        def blocking(tables, query, reader):
            """Common blocking-query wrapper (reference http.go wrap +
            setMeta): ?index=N&wait=S parks on the state watch."""
            min_index = int(query.get("index", ["0"])[0])
            wait_s = _parse_wait(query.get("wait", ["0"])[0])
            if min_index > 0 and wait_s > 0:
                idx = srv.state.wait_for_index(tables, min_index + 1, wait_s)
            else:
                idx = srv.state.table_index(*tables)
            return reader(), idx

        # -- jobs ------------------------------------------------------
        def jobs_list(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            if other_region():
                return self.rpc_region(
                    "Job.list", {"namespace": None if ns == "*" else ns}
                )
            data, idx = blocking(
                [TABLE_JOBS], q, lambda: srv.state.jobs(None if ns == "*" else ns)
            )
            prefix = q.get("prefix", [""])[0]
            if prefix:
                data = [j for j in data if j.id.startswith(prefix)]
            return data, idx

        def jobs_register(p, q, body, tok):
            job = codec.from_wire(body["Job"])
            return self.rpc_region("Job.register", {"job": job})

        def job_get(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            if other_region():
                job = self.rpc_region(
                    "Job.get", {"namespace": ns, "job_id": p["id"]}
                )
            else:
                job = srv.state.job_by_id(ns, p["id"])
            if job is None:
                raise HTTPError(404, f"job {p['id']} not found")
            return job

        def job_delete(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            purge = q.get("purge", ["false"])[0] == "true"
            return self.rpc_region(
                "Job.deregister",
                {"namespace": ns, "job_id": p["id"], "purge": purge},
            )

        def job_allocs(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            if other_region():
                return self.rpc_region(
                    "Job.allocs", {"namespace": ns, "job_id": p["id"]}
                )
            data, idx = blocking(
                [TABLE_ALLOCS], q, lambda: srv.state.allocs_by_job(ns, p["id"])
            )
            return data, idx

        def job_evals(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            if other_region():
                return self.rpc_region(
                    "Job.evals", {"namespace": ns, "job_id": p["id"]}
                )
            return srv.state.evals_by_job(ns, p["id"])

        def job_summary(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            if other_region():
                s = self.rpc_region(
                    "Job.summary", {"namespace": ns, "job_id": p["id"]}
                )
            else:
                s = srv.state.job_summary_by_id(ns, p["id"])
            if s is None:
                raise HTTPError(404, "no summary")
            return s

        def job_scale(p, q, body, tok):
            # ACL: the route resolver already enforces scale-job OR
            # submit-job on this namespace (acl/enforce.py)
            ns = q.get("namespace", ["default"])[0]
            target = (body or {}).get("Target") or {}
            group = target.get("Group", "")
            count = (body or {}).get("Count")
            if count is None or not group:
                raise HTTPError(400, "Target.Group and Count are required")
            try:
                count = int(count)
            except (TypeError, ValueError):
                raise HTTPError(400, f"Count must be an integer, got {count!r}")
            try:
                eval_id = self.rpc_region(
                "Job.scale",
                {
                    "namespace": ns,
                    "job_id": p["id"],
                    "group": group,
                    "count": count,
                    "message": (body or {}).get("Message", ""),
                })
            except KeyError as e:
                raise HTTPError(404, str(e))
            except ValueError as e:
                raise HTTPError(400, str(e))
            return {"EvalID": eval_id}

        def job_scale_status(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            out = self.rpc_region(
                "Job.scale_status", {"namespace": ns, "job_id": p["id"]}
            )
            if out is None:
                raise HTTPError(404, f"job {p['id']} not found")
            return out

        def job_versions(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            if other_region():
                return self.rpc_region(
                    "Job.versions", {"namespace": ns, "job_id": p["id"]}
                )
            return srv.state.job_versions(ns, p["id"])

        def _search_ns(q, body) -> str:
            # MUST mirror the ACL resolver's derivation (body wins, then
            # query): authorizing one namespace and searching another
            # would leak ids.
            return (
                body.get("Namespace")
                or q.get("namespace", ["default"])[0]
            )

        def _filter_search(result, tok):
            """Cluster-scoped contexts need their own capabilities
            (reference search_endpoint.go sufficientSearchPerms): nodes
            require node:read; the namespaces list shrinks to ones the
            token holds any job capability on."""
            acl = self._acl_for(tok)
            if acl is None:  # enforcement off or management token
                return result
            matches = result.get("Matches") or {}
            if not acl.allow_node_read():
                matches.pop("nodes", None)
                result.get("Truncations", {}).pop("nodes", None)
            if "namespaces" in matches:
                def visible(name):
                    n = name["ID"] if isinstance(name, dict) else name
                    return acl.allow_namespace_op(
                        n, "list-jobs"
                    ) or acl.allow_namespace_op(n, "read-job")

                kept = [n for n in matches["namespaces"] if visible(n)]
                if kept:
                    matches["namespaces"] = kept
                else:
                    matches.pop("namespaces", None)
            return result

        def search(p, q, body, tok):
            return _filter_search(
                self.rpc_region(
                    "Search.prefix",
                    {
                        "prefix": body.get("Prefix", ""),
                        "context": body.get("Context", "all"),
                        "namespace": _search_ns(q, body),
                    },
                ),
                tok,
            )

        def search_fuzzy(p, q, body, tok):
            return _filter_search(
                self.rpc_region(
                    "Search.fuzzy",
                    {
                        "text": body.get("Text", ""),
                        "context": body.get("Context", "all"),
                        "namespace": _search_ns(q, body),
                    },
                ),
                tok,
            )

        def namespaces_list(p, q, body, tok):
            return self.rpc_region("Namespace.list", {})

        def namespace_upsert(p, q, body, tok):
            ns = codec.from_wire(body["Namespace"])
            return self.rpc_region(
                "Namespace.upsert", {"namespace": ns}
            )

        def namespace_get(p, q, body, tok):
            ns = self.rpc_region("Namespace.get", {"name": p["name"]})
            if ns is None:
                raise HTTPError(404, f"namespace {p['name']} not found")
            return ns

        def namespace_delete(p, q, body, tok):
            from ..rpc.client import RPCError

            try:
                return self.rpc_region(
                    "Namespace.delete", {"name": p["name"]}
                )
            except KeyError as e:
                raise HTTPError(404, str(e))
            except ValueError as e:
                raise HTTPError(409, str(e))
            except RPCError as e:
                msg = str(e)
                if "not found" in msg:
                    raise HTTPError(404, msg)
                if "jobs/volumes" in msg or "cannot be deleted" in msg:
                    raise HTTPError(409, msg)
                raise

        def volumes_list(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            return self.rpc_region(
                "Volume.list",
                {"namespace": None if ns == "*" else ns},
            )

        def volume_register(p, q, body, tok):
            vol = codec.from_wire(body["Volume"])
            self._ns_guard(tok, vol.namespace, "submit-job")
            return self.rpc_region("Volume.register", {"volume": vol})

        def volume_get(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            vol = self.rpc_region(
                "Volume.get", {"namespace": ns, "volume_id": p["id"]}
            )
            if vol is None:
                raise HTTPError(404, f"volume {p['id']} not found")
            return vol

        def volume_delete(p, q, body, tok):
            from ..rpc.client import RPCError

            ns = q.get("namespace", ["default"])[0]
            self._ns_guard(tok, ns, "submit-job")
            try:
                return self.rpc_region(
                    "Volume.deregister",
                    {"namespace": ns, "volume_id": p["id"]},
                )
            except KeyError as e:
                raise HTTPError(404, str(e))
            except ValueError as e:
                raise HTTPError(409, str(e))
            except RPCError as e:
                # leader-forwarded errors arrive as strings; keep the
                # status mapping callers rely on
                msg = str(e)
                if "not found" in msg:
                    raise HTTPError(404, msg)
                if "active claims" in msg:
                    raise HTTPError(409, msg)
                raise

        def job_plan(p, q, body, tok):
            job = codec.from_wire(body["Job"])
            self._ns_guard(tok, job.namespace, "submit-job")
            if job.id != p["id"]:
                raise HTTPError(400, "job id does not match URL")
            return self.rpc_region(
                "Job.plan",
                {"job": job, "diff": bool(body.get("Diff", True))},
            )

        def job_revert(p, q, body, tok):
            ns = body.get("Namespace", "default")
            self._ns_guard(tok, ns, "submit-job")
            return self.rpc_region(
                "Job.revert",
                {"namespace": ns, "job_id": p["id"], "version": body["JobVersion"]},
            )

        def job_dispatch(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            payload = codec.from_wire(body.get("Payload"))
            if isinstance(payload, str):
                payload = payload.encode()
            return self.rpc_region(
                "Job.dispatch",
                {
                    "namespace": ns,
                    "job_id": p["id"],
                    "meta": body.get("Meta") or {},
                    "payload": payload,
                },
            )

        def job_periodic_force(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            return self.rpc_region(
                "Job.periodic_force", {"namespace": ns, "job_id": p["id"]}
            )

        def jobs_parse(p, q, body, tok):
            # Server-side HCL parse (reference /v1/jobs/parse,
            # jobs_endpoint.go): the browser UI submits raw jobspec text
            # and gets the canonical job back for plan/register.
            from ..jobspec import parse_job

            src = body.get("JobHCL", "")
            if not src.strip():
                raise HTTPError(400, "JobHCL required")
            variables = body.get("Variables") or {}
            try:
                job = parse_job(src, variables=variables)
            except Exception as e:
                raise HTTPError(400, f"parse failed: {e}")
            # the Job dataclass rides the reply encoder once — returning
            # a pre-encoded dict here would double-encode into $map form
            return {"Job": job}

        route("GET", "/v1/jobs", jobs_list)
        route("PUT", "/v1/jobs", jobs_register)
        route("POST", "/v1/jobs", jobs_register)
        route("POST", "/v1/jobs/parse", jobs_parse)
        route("PUT", "/v1/jobs/parse", jobs_parse)
        route("GET", "/v1/job/(?P<id>[^/]+)", job_get)
        route("DELETE", "/v1/job/(?P<id>[^/]+)", job_delete)
        route("GET", "/v1/job/(?P<id>[^/]+)/allocations", job_allocs)
        route("GET", "/v1/job/(?P<id>[^/]+)/evaluations", job_evals)
        route("GET", "/v1/job/(?P<id>[^/]+)/summary", job_summary)
        route("GET", "/v1/job/(?P<id>[^/]+)/versions", job_versions)
        def job_evaluate(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            try:
                eval_id = self.rpc_region(
                    "Job.evaluate", {"namespace": ns, "job_id": p["id"]}
                )
            except Exception as e:
                mapped = self._map_forward_error(e)
                if mapped is None:
                    raise
                raise mapped
            return {"EvalID": eval_id}

        def job_deployments(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            return self.rpc_region(
                "Job.deployments", {"namespace": ns, "job_id": p["id"]}
            )

        def validate_job(p, q, body, tok):
            # reference command/agent/job_endpoint.go ValidateJobRequest:
            # canonicalize+validate server-side, report errors as data
            # (not an HTTP failure)
            if not (body or {}).get("Job"):
                raise HTTPError(400, "Job is required")
            try:
                srv.validate_job_submission(codec.from_wire(body["Job"]))
            except (ValueError, PermissionError) as e:
                return {
                    "Error": str(e),
                    "ValidationErrors": [str(e)],
                    "Warnings": "",
                }
            return {"Error": "", "ValidationErrors": [], "Warnings": ""}

        route("PUT", "/v1/validate/job", validate_job)
        route("POST", "/v1/validate/job", validate_job)
        route("PUT", "/v1/job/(?P<id>[^/]+)/evaluate", job_evaluate)
        route("POST", "/v1/job/(?P<id>[^/]+)/evaluate", job_evaluate)
        route("GET", "/v1/job/(?P<id>[^/]+)/deployments", job_deployments)
        route("POST", "/v1/job/(?P<id>[^/]+)/scale", job_scale)
        route("PUT", "/v1/job/(?P<id>[^/]+)/scale", job_scale)
        route("GET", "/v1/job/(?P<id>[^/]+)/scale", job_scale_status)
        route("PUT", "/v1/search", search)
        route("POST", "/v1/search", search)
        route("PUT", "/v1/search/fuzzy", search_fuzzy)
        route("POST", "/v1/search/fuzzy", search_fuzzy)
        route("GET", "/v1/namespaces", namespaces_list)
        route("PUT", "/v1/namespaces", namespace_upsert)
        route("POST", "/v1/namespaces", namespace_upsert)
        route("GET", "/v1/namespace/(?P<name>[^/]+)", namespace_get)
        route("DELETE", "/v1/namespace/(?P<name>[^/]+)", namespace_delete)
        def secrets_list(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            return self.rpc_region("Secrets.list", {"namespace": ns})

        def secret_get(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            entry = self.rpc_region(
                "Secrets.read",
                {"namespace": ns, "path": p["path"], "token": tok or ""},
            )
            if entry is None:
                raise HTTPError(404, f"secret {p['path']} not found")
            return entry

        def secret_put(p, q, body, tok):
            from ..structs.structs import SecretEntry

            ns = q.get("namespace", ["default"])[0]
            items = (body or {}).get("Items") or {}
            if not isinstance(items, dict):
                raise HTTPError(400, "Items must be an object")
            entry = SecretEntry(
                path=p["path"], namespace=ns,
                items={str(k): str(v) for k, v in items.items()},
            )
            return self.rpc_region("Secrets.upsert", {"entry": entry})

        def secret_delete(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            try:
                return self.rpc_region(
                    "Secrets.delete", {"namespace": ns, "path": p["path"]}
                )
            except KeyError as e:
                raise HTTPError(404, str(e))

        route("GET", "/v1/secrets", secrets_list)
        route("GET", "/v1/secret/(?P<path>.+)", secret_get)
        route("PUT", "/v1/secret/(?P<path>.+)", secret_put)
        route("POST", "/v1/secret/(?P<path>.+)", secret_put)
        route("DELETE", "/v1/secret/(?P<path>.+)", secret_delete)

        def services_list(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            return self.rpc_region(
                "Service.list",
                {"namespace": None if ns == "*" else ns},
            )

        def service_get(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            regs = self.rpc_region(
                "Service.get", {"namespace": ns, "name": p["name"]}
            )
            if not regs:
                raise HTTPError(404, f"service {p['name']} not found")
            return regs

        def service_delete(p, q, body, tok):
            # Scope the delete to the ACL-checked namespace + the named
            # service: ids are guessable, so an id-only delete would let
            # a default-namespace token deregister another namespace's
            # instances.
            ns = q.get("namespace", ["default"])[0]
            regs = self.rpc_region(
                "Service.get", {"namespace": ns, "name": p["name"]}
            )
            if not any(r.id == p["id"] for r in regs):
                raise HTTPError(
                    404,
                    f"registration {p['id']} not found for service "
                    f"{p['name']} in namespace {ns}",
                )
            n = self.rpc_region(
                "Service.deregister", {"ids": [p["id"]]}
            )
            return {"Deregistered": n}

        route("GET", "/v1/services", services_list)
        route("GET", "/v1/service/(?P<name>[^/]+)", service_get)
        route(
            "DELETE",
            "/v1/service/(?P<name>[^/]+)/(?P<id>[^/]+)",
            service_delete,
        )

        def scaling_policies(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            return self.rpc_region(
                "Scaling.list_policies",
                {"namespace": None if ns == "*" else ns},
            )

        def scaling_policy_get(p, q, body, tok):
            pol = self.rpc_region(
                "Scaling.get_policy", {"policy_id": p["id"]}
            )
            if pol is None:
                raise HTTPError(404, f"scaling policy {p['id']} not found")
            self._ns_guard(tok, pol.namespace, "read-job")
            return pol

        route("GET", "/v1/scaling/policies", scaling_policies)
        route("GET", "/v1/scaling/policy/(?P<id>.+)", scaling_policy_get)

        def plugins_list(p, q, body, tok):
            plugins = self.rpc_region("Volume.plugins", {})
            return sorted(plugins.values(), key=lambda x: x["id"])

        def plugin_get(p, q, body, tok):
            plugins = self.rpc_region("Volume.plugins", {})
            if p["id"] not in plugins:
                raise HTTPError(404, f"plugin {p['id']} not found")
            return plugins[p["id"]]

        route("GET", "/v1/plugins", plugins_list)
        route("GET", "/v1/plugin/csi/(?P<id>[^/]+)", plugin_get)
        def volume_create(p, q, body, tok):
            if not (body or {}).get("Volume"):
                raise HTTPError(400, "Volume is required")
            vol = codec.from_wire(body["Volume"])
            self._ns_guard(tok, vol.namespace, "submit-job")
            try:
                return self.rpc_region("Volume.create", {"volume": vol})
            except KeyError as e:
                raise HTTPError(404, str(e))
            except ValueError as e:
                raise HTTPError(400, str(e))

        def volume_csi_delete(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            self._ns_guard(tok, ns, "submit-job")
            try:
                self.rpc_region(
                    "Volume.delete",
                    {"namespace": ns, "volume_id": p["id"]},
                )
            except KeyError as e:
                raise HTTPError(404, str(e))
            except ValueError as e:
                raise HTTPError(409, str(e))
            return None

        def volume_snapshot_create(p, q, body, tok):
            ns = (body or {}).get("Namespace") or q.get(
                "namespace", ["default"]
            )[0]
            self._ns_guard(tok, ns, "submit-job")
            vol_id = (body or {}).get("VolumeID", "")
            if not vol_id:
                raise HTTPError(400, "VolumeID is required")
            try:
                return self.rpc_region(
                    "Volume.snapshot_create",
                    {
                        "namespace": ns,
                        "volume_id": vol_id,
                        "name": (body or {}).get("Name", ""),
                    },
                )
            except Exception as e:
                mapped = self._map_forward_error(e)
                if mapped is None:
                    raise
                raise mapped

        def volume_snapshot_delete(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            self._ns_guard(tok, ns, "submit-job")
            plugin_id = q.get("plugin_id", [""])[0]
            snap_id = q.get("snapshot_id", [""])[0]
            if not plugin_id or not snap_id:
                raise HTTPError(400, "plugin_id and snapshot_id required")
            try:
                self.rpc_region(
                    "Volume.snapshot_delete",
                    {"plugin_id": plugin_id, "snapshot_id": snap_id},
                )
            except Exception as e:
                mapped = self._map_forward_error(e)
                if mapped is None:
                    raise
                raise mapped
            return None

        def volume_snapshot_list(p, q, body, tok):
            plugin_id = q.get("plugin_id", [""])[0]
            if not plugin_id:
                raise HTTPError(400, "plugin_id required")
            try:
                return self.rpc_region(
                    "Volume.snapshot_list", {"plugin_id": plugin_id}
                )
            except Exception as e:
                mapped = self._map_forward_error(e)
                if mapped is None:
                    raise
                raise mapped

        route("PUT", "/v1/volumes/create", volume_create)
        route("POST", "/v1/volumes/create", volume_create)
        def volume_detach(p, q, body, tok):
            ns = q.get("namespace", ["default"])[0]
            self._ns_guard(tok, ns, "submit-job")
            node_id = q.get("node", [""])[0]
            if not node_id:
                raise HTTPError(400, "node required")
            try:
                return self.rpc_region(
                    "Volume.detach",
                    {
                        "namespace": ns,
                        "volume_id": p["id"],
                        "node_id": node_id,
                    },
                )
            except Exception as e:
                mapped = self._map_forward_error(e)
                if mapped is None:
                    raise
                raise mapped

        route(
            "DELETE", "/v1/volume/(?P<id>[^/]+)/detach", volume_detach
        )
        route("PUT", "/v1/volumes/snapshot", volume_snapshot_create)
        route("POST", "/v1/volumes/snapshot", volume_snapshot_create)
        route("DELETE", "/v1/volumes/snapshot", volume_snapshot_delete)
        route("GET", "/v1/volumes/snapshot", volume_snapshot_list)
        route(
            "DELETE", "/v1/volume/(?P<id>[^/]+)/delete", volume_csi_delete
        )
        route("GET", "/v1/volumes", volumes_list)
        route("PUT", "/v1/volumes", volume_register)
        route("POST", "/v1/volumes", volume_register)
        route("GET", "/v1/volume/(?P<id>[^/]+)", volume_get)
        route("DELETE", "/v1/volume/(?P<id>[^/]+)", volume_delete)
        route("PUT", "/v1/job/(?P<id>[^/]+)/plan", job_plan)
        route("POST", "/v1/job/(?P<id>[^/]+)/plan", job_plan)
        route("PUT", "/v1/job/(?P<id>[^/]+)/revert", job_revert)
        route("PUT", "/v1/job/(?P<id>[^/]+)/dispatch", job_dispatch)
        route("POST", "/v1/job/(?P<id>[^/]+)/dispatch", job_dispatch)
        route(
            "PUT", "/v1/job/(?P<id>[^/]+)/periodic/force", job_periodic_force
        )

        # -- nodes -----------------------------------------------------
        def nodes_list(p, q, body, tok):
            if other_region():
                return self.rpc_region("Node.list", {})
            data, idx = blocking([TABLE_NODES], q, srv.state.nodes)
            prefix = q.get("prefix", [""])[0]
            if prefix:
                data = [n for n in data if n.id.startswith(prefix)]
            return data, idx

        def node_get(p, q, body, tok):
            if other_region():
                node = self.rpc_region("Node.get", {"node_id": p["id"]})
                if node is None:
                    raise HTTPError(404, f"node {p['id']} not found")
                return node
            node = srv.state.node_by_id(p["id"])
            if node is None:
                raise HTTPError(404, f"node {p['id']} not found")
            return node

        def node_allocs(p, q, body, tok):
            if other_region():
                return self.rpc_region(
                    "Alloc.list_by_node", {"node_id": p["id"]}
                )
            data, idx = blocking(
                [TABLE_ALLOCS], q, lambda: srv.state.allocs_by_node(p["id"])
            )
            return data, idx

        def node_drain(p, q, body, tok):
            drain = (
                codec.from_wire(body["DrainSpec"])
                if body.get("DrainSpec") is not None
                else None
            )
            if isinstance(drain, dict):
                # raw-JSON clients (the browser UI, curl) send the
                # reference's plain shape {"Deadline": ns, ...} rather
                # than a codec-tagged struct — accept both
                from ..structs import DrainStrategy

                drain = DrainStrategy(
                    deadline_s=float(drain.get("Deadline", 0)) / 1e9,
                    ignore_system_jobs=bool(
                        drain.get("IgnoreSystemJobs", False)
                    ),
                )
            self.rpc_region(
                "Node.update_drain",
                {
                    "node_id": p["id"],
                    "drain": drain,
                    "mark_eligible": body.get("MarkEligible", False),
                },
            )
            if other_region():
                # the local index belongs to the wrong region's raft —
                # a bogus value would poison blocking queries
                return {"NodeModifyIndex": 0}
            return {"NodeModifyIndex": srv.state.latest_index()}

        def node_eligibility(p, q, body, tok):
            self.rpc_region(
                "Node.update_eligibility",
                {"node_id": p["id"], "eligibility": body["Eligibility"]},
            )
            return {}

        def node_purge(p, q, body, tok):
            self.rpc_region("Node.purge", {"node_id": p["id"]})
            return {}

        route("GET", "/v1/nodes", nodes_list)
        route("GET", "/v1/node/(?P<id>[^/]+)", node_get)
        route("GET", "/v1/node/(?P<id>[^/]+)/allocations", node_allocs)
        route("PUT", "/v1/node/(?P<id>[^/]+)/drain", node_drain)
        route("POST", "/v1/node/(?P<id>[^/]+)/drain", node_drain)
        route("PUT", "/v1/node/(?P<id>[^/]+)/eligibility", node_eligibility)
        route("PUT", "/v1/node/(?P<id>[^/]+)/purge", node_purge)

        # -- allocs / evals -------------------------------------------
        def allocs_list(p, q, body, tok):
            if other_region():
                data = self.rpc_region("Alloc.list", {})
                return self._ns_filter(tok, data, "read-job")
            data, idx = blocking([TABLE_ALLOCS], q, srv.state.allocs)
            return self._ns_filter(tok, data, "read-job"), idx

        def alloc_get(p, q, body, tok):
            a = (
                self.rpc_region("Alloc.get", {"alloc_id": p["id"]})
                if other_region()
                else srv.state.alloc_by_id(p["id"])
            )
            if a is None:
                raise HTTPError(404, f"alloc {p['id']} not found")
            self._ns_guard(tok, a.namespace, "read-job")
            return a

        def evals_list(p, q, body, tok):
            if other_region():
                data = self.rpc_region("Eval.list", {})
                return self._ns_filter(tok, data, "read-job")
            data, idx = blocking([TABLE_EVALS], q, srv.state.evals)
            return self._ns_filter(tok, data, "read-job"), idx

        def eval_get(p, q, body, tok):
            e = (
                self.rpc_region("Eval.get", {"eval_id": p["id"]})
                if other_region()
                else srv.state.eval_by_id(p["id"])
            )
            if e is None:
                raise HTTPError(404, f"eval {p['id']} not found")
            self._ns_guard(tok, e.namespace, "read-job")
            return e

        def eval_allocs(p, q, body, tok):
            # Filter by each alloc's own namespace: a token scoped to one
            # namespace must not enumerate another namespace's allocs.
            allocs = (
                self.rpc_region("Eval.allocs", {"eval_id": p["id"]})
                if other_region()
                else srv.state.allocs_by_eval(p["id"])
            )
            return self._ns_filter(tok, allocs, "read-job")

        route("GET", "/v1/allocations", allocs_list)
        route("GET", "/v1/allocation/(?P<id>[^/]+)", alloc_get)
        route("GET", "/v1/evaluations", evals_list)
        def eval_delete(p, q, body, tok):
            # the endpoint owns the terminal-only invariant (checked on
            # the leader right before the apply) and ?region= forwards
            try:
                self.rpc_region("Eval.delete", {"eval_ids": [p["id"]]})
            except KeyError as e:
                raise HTTPError(404, str(e))
            except ValueError as e:
                raise HTTPError(400, str(e))
            return None

        route("DELETE", "/v1/evaluation/(?P<id>[^/]+)", eval_delete)
        route("GET", "/v1/evaluation/(?P<id>[^/]+)", eval_get)
        route("GET", "/v1/evaluation/(?P<id>[^/]+)/allocations", eval_allocs)

        # -- deployments ----------------------------------------------
        def deployments_list(p, q, body, tok):
            if other_region():
                data = self.rpc_region("Deployment.list", {})
                return self._ns_filter(tok, data, "read-job")
            data, idx = blocking([TABLE_DEPLOYMENTS], q, srv.state.deployments)
            return self._ns_filter(tok, data, "read-job"), idx

        def deployment_get(p, q, body, tok):
            d = (
                self.rpc_region(
                    "Deployment.get", {"deployment_id": p["id"]}
                )
                if other_region()
                else srv.state.deployment_by_id(p["id"])
            )
            if d is None:
                raise HTTPError(404, f"deployment {p['id']} not found")
            self._ns_guard(tok, d.namespace, "read-job")
            return d

        def deployment_allocs(p, q, body, tok):
            return self._ns_filter(
                tok, srv.state.allocs_by_deployment(p["id"]), "read-job"
            )

        def deployment_promote(p, q, body, tok):
            d = srv.state.deployment_by_id(p["id"])
            if d is not None:
                self._ns_guard(tok, d.namespace, "submit-job")
            self.rpc_region(
                "Deployment.promote",
                {
                    "deployment_id": p["id"],
                    "groups": body.get("Groups"),
                },
            )
            return {}

        def deployment_pause(p, q, body, tok):
            d = srv.state.deployment_by_id(p["id"])
            if d is not None:
                self._ns_guard(tok, d.namespace, "submit-job")
            self.rpc_region(
                "Deployment.pause",
                {"deployment_id": p["id"], "pause": body.get("Pause", True)},
            )
            return {}

        def deployment_fail(p, q, body, tok):
            d = srv.state.deployment_by_id(p["id"])
            if d is not None:
                self._ns_guard(tok, d.namespace, "submit-job")
            self.rpc_region(
                "Deployment.fail", {"deployment_id": p["id"]}
            )
            return {}

        route("GET", "/v1/deployments", deployments_list)
        route("GET", "/v1/deployment/(?P<id>[^/]+)", deployment_get)
        route(
            "GET", "/v1/deployment/allocations/(?P<id>[^/]+)", deployment_allocs
        )
        route("PUT", "/v1/deployment/promote/(?P<id>[^/]+)", deployment_promote)
        route("PUT", "/v1/deployment/pause/(?P<id>[^/]+)", deployment_pause)
        route("PUT", "/v1/deployment/fail/(?P<id>[^/]+)", deployment_fail)

        # -- status / agent -------------------------------------------
        def status_leader(p, q, body, tok):
            if other_region():
                out = self.rpc_region("Status.leader", {})
                addr = (out or {}).get("leader")
                return f"{addr[0]}:{addr[1]}" if addr else None
            addr = self.cluster.raft.leader_addr()
            return f"{addr[0]}:{addr[1]}" if addr else None

        def status_peers(p, q, body, tok):
            return self.rpc_region("Status.peers", {})

        def regions_list(p, q, body, tok):
            return self.rpc_region("Status.regions", {})

        def _debug_gate():
            # reference: pprof 404s unless enable_debug (agent http.go)
            if not self.enable_debug:
                raise HTTPError(404, "debug endpoints disabled")

        def pprof_goroutine(p, q, body, tok):
            from . import debug as _debug

            _debug_gate()
            return {"profile": _debug.thread_dump()}

        def pprof_profile(p, q, body, tok):
            from . import debug as _debug

            _debug_gate()
            try:
                seconds = float(q.get("seconds", ["2"])[0])
            except ValueError:
                raise HTTPError(400, "seconds must be a number")
            # Single-flight: one wall-clock capture occupies a handler
            # thread for `seconds`; N concurrent captures would occupy N
            # threads sampling the SAME process for no extra signal.
            # Overlapping requests 429 with a Retry-After sized to the
            # in-flight capture's remaining time. (The always-on sampler
            # at /v1/profile/status never blocks and needs no guard.)
            # mirror cpu_profile's own clamp so Retry-After is honest
            clamped = max(0.1, min(seconds, 30.0)) if seconds == seconds else 2.0
            if not self._pprof_capture_lock.acquire(blocking=False):
                remaining = self._pprof_busy_until - time.monotonic()
                raise HTTPError(
                    429,
                    "a profile capture is already in progress",
                    retry_after=max(0.1, remaining),
                )
            try:
                # FIRST thing under the lock: a loser arriving in the
                # instant between our acquire and this store would read
                # a stale (expired) deadline and hint Retry-After 0.1s
                # against a capture that may run 30s
                self._pprof_busy_until = time.monotonic() + clamped
                return {"profile": _debug.cpu_profile(seconds)}
            finally:
                self._pprof_capture_lock.release()

        def pprof_heap(p, q, body, tok):
            from . import debug as _debug

            _debug_gate()
            return _debug.heap_summary()

        def agent_metrics(p, q, body, tok):
            # reference: /v1/metrics (command/agent/http.go MetricsRequest,
            # behind agent:read / AgentReadACL); ?format=prometheus serves
            # the text exposition format a stock Prometheus scrapes
            if q.get("format", [""])[0] == "prometheus":
                return RawResponse(
                    metrics.prometheus_text().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            return metrics.snapshot()

        def traces_list(p, q, body, tok):
            # /v1/traces: the tracing ring buffer (trace.py) — newest
            # first, filterable by eval/job id and trace name. Follows
            # the /v1/metrics pattern: agent-local observability surface.
            from .. import trace as _trace

            try:
                limit = int(q.get("limit", ["50"])[0])
            except ValueError:
                raise HTTPError(400, "limit must be an integer")
            return _trace.recorder().list(
                name=q.get("name", [""])[0],
                eval_id=q.get("eval_id", [""])[0],
                job_id=q.get("job_id", [""])[0],
                limit=max(1, min(limit, 1000)),
            )

        def trace_get(p, q, body, tok):
            from .. import trace as _trace

            t = _trace.recorder().get(p["id"])
            if t is None:
                raise HTTPError(404, f"trace {p['id']} not found")
            return t

        route("GET", "/v1/traces", traces_list)
        route("GET", "/v1/traces/(?P<id>[^/]+)", trace_get)

        def solver_status(p, q, body, tok):
            # /v1/solver/status: the solver observatory's snapshot —
            # compile ledger (bucket recompiles vs cache hits), batch
            # occupancy/padding waste, host<->device transfer bytes,
            # and device memory (solverobs.py). Same agent:read gate as
            # /v1/metrics; always on (observability, not debug).
            import sys as _sys

            from .. import solverobs

            out = solverobs.snapshot()
            # jax's own jit-cache ground truth, cross-checking the
            # ledger — only when the solver stack is already loaded in
            # this process (never drag jax into a control plane)
            kmod = _sys.modules.get("nomad_tpu.scheduler.tpu.kernels")
            out["jit_cache_sizes"] = (
                kmod.jit_cache_sizes() if kmod is not None else None
            )
            w = getattr(srv, "tpu_worker", None)
            out["worker"] = w.stats_snapshot() if w is not None else None
            # solver-pool tier: membership + per-member in-flight for
            # the operator-top panel (cheap local snapshot; the fan-out
            # aggregation lives on /v1/solver/pool)
            pool = getattr(self.cluster, "solver_pool", None)
            out["pool"] = pool.stats_snapshot() if pool is not None else None
            return out

        route("GET", "/v1/solver/status", solver_status)

        def solver_pool_status(p, q, body, tok):
            # /v1/solver/pool: the pool tracker's snapshot plus each
            # member's own SolverPool.Status, pulled with a bounded
            # per-member deadline (docs/solver-pool.md). Same agent:read
            # gate as /v1/solver/status via the /v1/solver ACL prefix.
            pool = getattr(self.cluster, "solver_pool", None)
            if pool is None:
                raise HTTPError(404, "no solver pool on this agent")
            return pool.pool_status()

        route("GET", "/v1/solver/pool", solver_pool_status)

        def profile_status(p, q, body, tok):
            # /v1/profile/status: the always-on host profiler's summary
            # (hostobs.py) — span-correlated CPU self-time sites, GC
            # pause/collection accounting, lock-wait ledger, runtime
            # gauges. Same agent:read gate as /v1/metrics; available
            # even when enable_debug 404s the raw pprof capture
            # (observability is not a debug mode).
            from .. import hostobs

            try:
                top = int(q.get("top", ["50"])[0])
            except ValueError:
                raise HTTPError(400, "top must be an integer")
            return hostobs.snapshot(top=max(1, min(top, 500)))

        def profile_collapsed(p, q, body, tok):
            # /v1/profile/collapsed: collapsed-stack flamegraph text
            # ("role;span;frame;...;leaf count" per line) — pipe into
            # flamegraph.pl / speedscope verbatim (docs/profiling.md).
            from .. import hostobs

            try:
                limit = int(q.get("limit", ["0"])[0])
            except ValueError:
                raise HTTPError(400, "limit must be an integer")
            return RawResponse(
                hostobs.collapsed(limit=max(0, limit)).encode(),
                "text/plain; charset=utf-8",
            )

        route("GET", "/v1/profile/status", profile_status)
        route("GET", "/v1/profile/collapsed", profile_collapsed)

        def blackbox_status(p, q, body, tok):
            # /v1/blackbox/status: the flight recorder's summary —
            # journal occupancy, per-kind counts, trigger catalogue with
            # last-fired ages, recent incidents. Same agent:read gate as
            # /v1/metrics; ?journal=N appends the newest N journal rows.
            from .. import blackbox as _bb

            rec = _bb.recorder()
            wiring = getattr(self.cluster, "blackbox", None)
            try:
                tail = int(q.get("journal", ["0"])[0])
            except ValueError:
                raise HTTPError(400, "journal must be an integer")
            out = {
                "enabled": _bb.enabled()
                and bool(wiring and wiring.enabled),
                "stats": rec.stats(),
                "kinds": rec.kind_counts(),
                "triggers": rec.triggers.status(),
                "incident_dir": wiring.incident_dir if wiring else "",
                "incidents": rec.incidents()[:5],
            }
            if tail:
                out["journal"] = rec.snapshot(
                    limit=max(1, min(tail, 1000))
                )
            return out

        def incidents_list(p, q, body, tok):
            # /v1/incidents: the capture index, newest first (the
            # on-disk bundles live under each record's `path`).
            from .. import blackbox as _bb

            return _bb.recorder().incidents()

        def incident_get(p, q, body, tok):
            from .. import blackbox as _bb

            import os as _os

            rec = _bb.recorder().incident(p["id"])
            if rec is None:
                raise HTTPError(404, f"incident {p['id']} not found")
            files = []
            if rec.get("path"):
                try:
                    files = sorted(_os.listdir(rec["path"]))
                except OSError:
                    pass
            rec["files"] = files
            return rec

        def timeline_get(p, q, body, tok):
            # /v1/timeline/<kind>/<id>: the causal cross-object view —
            # journal rows (broker events with extracted rel links,
            # leadership edges, sheds, trims, expiries) merged with
            # finished traces, expanded through the link graph so an
            # eval's timeline reaches its plan, allocs, and nodes.
            from .. import blackbox as _bb
            from .. import trace as _trace

            kind = p["kind"]
            if kind not in _bb.TIMELINE_KINDS:
                raise HTTPError(
                    400,
                    "kind must be one of "
                    + ", ".join(_bb.TIMELINE_KINDS),
                )
            rows = _bb.recorder().snapshot()
            # traces keep monotonic clocks (trace.py); re-base onto wall
            # time so the merged view sorts on one axis (same-process
            # alignment only, which is what the journal is too)
            off = time.time() - time.monotonic()
            for t in _trace.recorder().list(limit=200):
                attrs = t.get("attrs") or {}
                rel = []
                for a, k in (("eval_id", "eval"), ("job_id", "job"),
                             ("node_id", "node")):
                    v = attrs.get(a)
                    if v:
                        rel.append(f"{k}:{v}")
                for e in attrs.get("eval_ids") or ():
                    rel.append(f"eval:{e}")
                if not rel:
                    continue
                rows.append({
                    "ts": t["start"] / 1e9 + off,
                    "kind": "trace",
                    "key": t["id"],
                    "detail": {
                        "name": t["name"],
                        "duration_ms": t.get("duration_ms"),
                        "spans": t.get("num_spans"),
                        "rel": rel,
                    },
                })
            return _bb.build_timeline(kind, p["id"], rows)

        route("GET", "/v1/blackbox/status", blackbox_status)
        route("GET", "/v1/incidents", incidents_list)
        route("GET", "/v1/incidents/(?P<id>[^/]+)", incident_get)
        route(
            "GET",
            "/v1/timeline/(?P<kind>[^/]+)/(?P<id>[^/]+)",
            timeline_get,
        )

        def agent_members(p, q, body, tok):
            return [m.to_wire() for m in self.cluster.serf.members()]

        def agent_monitor(p, q, body, tok):
            # handled specially in _dispatch (streaming); never reached
            raise HTTPError(500, "monitor is a streaming route")

        def agent_self(p, q, body, tok):
            return {
                "member": self.cluster.serf.local.to_wire(),
                # the fabric address: SDK/CLI exec dials this directly
                "rpc_addr": list(self.cluster.rpc.addr),
                "stats": {
                    "leader": self.cluster.is_leader(),
                    "raft_last_index": self.cluster.raft.last_index,
                },
                # fabric-auth keyring state: generation, key age, and
                # whether the dual-accept rotation window is open —
                # fingerprints only, never secrets (rpc/keyring.py)
                "keyring": self.cluster.keyring.status(),
            }

        def agent_keyring(p, q, body, tok):
            return self.cluster.keyring.status()

        def agent_keyring_rotate(p, q, body, tok):
            # Rotate THIS agent's keyring in place (the API analog of
            # editing rpc_secret + SIGHUP): the new secret becomes
            # current, the old stays accepted for the window. The
            # operator runs this against each agent in turn — the
            # window plus the ConnPool previous-secret fallback keeps
            # the mixed cluster flowing either way.
            secret = (body or {}).get("Secret", "")
            if not secret:
                raise HTTPError(400, "Secret required")
            window = (body or {}).get("Window")
            try:
                rotated = self.cluster.keyring.rotate(
                    secret,
                    window_s=(
                        float(window) if window is not None else None
                    ),
                )
            except (TypeError, ValueError) as e:
                raise HTTPError(400, f"invalid rotation: {e}")
            if rotated and self.on_keyring_rotate is not None:
                self.on_keyring_rotate(secret)
            out = self.cluster.keyring.status()
            out["rotated"] = rotated
            # The keyring is process state, not persisted: the operator
            # must also put the new secret in the config file or the
            # next RESTART boots with the stale one (runbook step in
            # docs/operations.md).
            out["persisted"] = False
            return out

        def agent_health(p, q, body, tok):
            return {"server": {"ok": True}, "client": {"ok": self.client is not None}}

        def agent_join(p, q, body, tok):
            # reference agent_endpoint.go AgentJoin: gossip-join the
            # given servers (CLI `server join`)
            addrs = []
            for a in q.get("address", []):
                if a.startswith("["):  # [::1]:4647 or bare [::1]
                    if "]:" in a:
                        host, _, port = a.rpartition(":")
                    else:
                        host, port = a, ""
                    host = host.strip("[]")
                elif a.count(":") > 1:  # bare IPv6: no port to split off
                    host, port = a, ""
                else:
                    host, _, port = a.rpartition(":")
                    if not host:  # bare hostname/IPv4, default port
                        host, port = a, ""
                try:
                    addrs.append((host, int(port or 4647)))
                except ValueError:
                    raise HTTPError(400, f"invalid address {a!r}")
            if not addrs:
                raise HTTPError(400, "address required")
            joined = self.cluster.join(addrs)
            err = "" if joined else "no servers could be contacted"
            return {"num_joined": joined, "error": err}

        # -- acl -------------------------------------------------------
        def acl_bootstrap(p, q, body, tok):
            return self.rpc_region("ACL.bootstrap", {})

        def acl_policies(p, q, body, tok):
            return self.rpc_region("ACL.policy_list", {})

        def acl_policy_get(p, q, body, tok):
            pol = self.rpc_region("ACL.policy_get", {"name": p["name"]})
            if pol is None:
                raise HTTPError(404, f"policy {p['name']} not found")
            return pol

        def acl_policy_put(p, q, body, tok):
            from ..acl import ACLPolicy

            pol = ACLPolicy(
                name=p["name"],
                description=body.get("Description", ""),
                rules=body.get("Rules", ""),
            )
            self.rpc_region("ACL.policy_upsert", {"policies": [pol]})
            return {}

        def acl_policy_delete(p, q, body, tok):
            self.rpc_region("ACL.policy_delete", {"names": [p["name"]]})
            return {}

        def acl_tokens(p, q, body, tok):
            return self.rpc_region("ACL.token_list", {})

        def acl_token_put(p, q, body, tok):
            from ..acl import ACLToken

            accessor = body.get("AccessorID", "")
            if accessor:
                # update: keep identity+secret, swap the mutable fields
                # (reference acl token update)
                existing = self.rpc_region(
                    "ACL.token_get", {"accessor_id": accessor}
                )
                if existing is None:
                    raise HTTPError(404, f"token {accessor} not found")
                t = existing.copy()
                if "Name" in body:
                    t.name = body["Name"]
                if "Policies" in body:
                    t.policies = list(body["Policies"] or [])
                if "Type" in body:
                    t.type = body["Type"]
            else:
                t = ACLToken(
                    name=body.get("Name", ""),
                    type=body.get("Type", "client"),
                    policies=body.get("Policies") or [],
                )
            if "Global" in body:
                t.global_ = bool(body["Global"])
            return self.rpc_region("ACL.token_create", {"token": t})

        def acl_token_get(p, q, body, tok):
            t = self.rpc_region(
                "ACL.token_get", {"accessor_id": p["id"]}
            )
            if t is None:
                raise HTTPError(404, f"token {p['id']} not found")
            return t

        def acl_token_delete(p, q, body, tok):
            self.rpc_region(
                "ACL.token_delete", {"accessor_ids": [p["id"]]}
            )
            return {}

        def acl_token_self(p, q, body, tok):
            t = self.cluster.server.state.acl_token_by_secret(tok)
            if t is None:
                raise HTTPError(404, "token not found")
            return t

        route("PUT", "/v1/acl/bootstrap", acl_bootstrap)
        route("POST", "/v1/acl/bootstrap", acl_bootstrap)
        route("GET", "/v1/acl/policies", acl_policies)
        route("GET", "/v1/acl/policy/(?P<name>[^/]+)", acl_policy_get)
        route("PUT", "/v1/acl/policy/(?P<name>[^/]+)", acl_policy_put)
        route("DELETE", "/v1/acl/policy/(?P<name>[^/]+)", acl_policy_delete)
        route("GET", "/v1/acl/tokens", acl_tokens)
        route("PUT", "/v1/acl/token", acl_token_put)
        route("GET", "/v1/acl/token/self", acl_token_self)
        route("GET", "/v1/acl/token/(?P<id>[^/]+)", acl_token_get)
        route("DELETE", "/v1/acl/token/(?P<id>[^/]+)", acl_token_delete)

        # -- client fs (non-streaming halves) --------------------------
        def client_fs_ls(p, q, body, tok):
            alloc = self._resolve_alloc(p["id"])
            self._ns_guard(tok, alloc.namespace, "read-fs")
            msg = self._client_roundtrip(
                alloc, "FS.ls", {"path": q.get("path", [""])[0]}
            )
            return msg.get("entries", [])

        def client_fs_stat(p, q, body, tok):
            alloc = self._resolve_alloc(p["id"])
            self._ns_guard(tok, alloc.namespace, "read-fs")
            msg = self._client_roundtrip(
                alloc, "FS.stat", {"path": q.get("path", [""])[0]}
            )
            return msg.get("stat")

        route("GET", "/v1/client/fs/ls/(?P<id>[^/]+)", client_fs_ls)
        route("GET", "/v1/client/fs/stat/(?P<id>[^/]+)", client_fs_stat)

        # -- alloc lifecycle (reference client/alloc_endpoint.go + the
        # server-side Stop in nomad/alloc_endpoint.go) ----------------
        def alloc_restart(p, q, body, tok):
            alloc = self._resolve_alloc(p["id"])
            self._ns_guard(tok, alloc.namespace, "alloc-lifecycle")
            msg = self._client_roundtrip(
                alloc, "Alloc.restart",
                {"task": (body or {}).get("TaskName", "")},
            )
            return {"ok": bool(msg.get("ok"))}

        def alloc_signal(p, q, body, tok):
            alloc = self._resolve_alloc(p["id"])
            self._ns_guard(tok, alloc.namespace, "alloc-lifecycle")
            msg = self._client_roundtrip(
                alloc, "Alloc.signal",
                {
                    "task": (body or {}).get("TaskName", ""),
                    "signal": (body or {}).get("Signal", "SIGTERM"),
                },
            )
            return {"ok": bool(msg.get("ok"))}

        def alloc_stop(p, q, body, tok):
            # stop is a pure server-side raft op: resolve from STATE, not
            # the client-streaming resolver — stopping an alloc off a
            # dead/unreachable node is exactly when this gets used
            if other_region():
                eval_id = self.rpc_region(
                    "Alloc.stop", {"alloc_id": p["id"]}
                )
                return {"EvalID": eval_id}
            try:
                alloc = self.cluster.find_alloc(p["id"])
            except LookupError as e:
                raise HTTPError(404, str(e)) from None
            self._ns_guard(tok, alloc.namespace, "alloc-lifecycle")
            eval_id = self.rpc_region("Alloc.stop", {"alloc_id": alloc.id})
            return {"EvalID": eval_id}

        def alloc_stats(p, q, body, tok):
            # reference: GET /v1/client/allocation/:id/stats
            # (client/alloc_endpoint.go Stats → AllocResourceUsage)
            alloc = self._resolve_alloc(p["id"])
            self._ns_guard(tok, alloc.namespace, "read-job")
            return self._client_roundtrip(alloc, "Alloc.stats", {})

        route(
            "GET", "/v1/client/allocation/(?P<id>[^/]+)/stats", alloc_stats
        )
        route(
            "PUT", "/v1/client/allocation/(?P<id>[^/]+)/restart",
            alloc_restart,
        )
        route(
            "POST", "/v1/client/allocation/(?P<id>[^/]+)/restart",
            alloc_restart,
        )
        route(
            "PUT", "/v1/client/allocation/(?P<id>[^/]+)/signal",
            alloc_signal,
        )
        route(
            "POST", "/v1/client/allocation/(?P<id>[^/]+)/signal",
            alloc_signal,
        )
        route("PUT", "/v1/allocation/(?P<id>[^/]+)/stop", alloc_stop)
        route("POST", "/v1/allocation/(?P<id>[^/]+)/stop", alloc_stop)

        # -- system ----------------------------------------------------
        def system_gc(p, q, body, tok):
            self.rpc_region("Operator.force_gc", {})
            return None

        def system_reconcile(p, q, body, tok):
            n = self.rpc_region("System.reconcile_summaries", {})
            return {"Reconciled": n}

        route("PUT", "/v1/system/gc", system_gc)
        route("POST", "/v1/system/gc", system_gc)
        route(
            "PUT", "/v1/system/reconcile/summaries", system_reconcile
        )
        route(
            "POST", "/v1/system/reconcile/summaries", system_reconcile
        )

        # -- operator --------------------------------------------------
        def scheduler_config_get(p, q, body, tok):
            return self.rpc_region("Operator.scheduler_get_config", {})

        def scheduler_config_set(p, q, body, tok):
            return self.rpc_region(
                "Operator.scheduler_set_config", {"config": body or {}}
            )

        route(
            "GET", "/v1/operator/scheduler/configuration",
            scheduler_config_get,
        )
        route(
            "PUT", "/v1/operator/scheduler/configuration",
            scheduler_config_set,
        )
        route(
            "POST", "/v1/operator/scheduler/configuration",
            scheduler_config_set,
        )

        def operator_snapshot_save(p, q, body, tok):
            import base64

            resp = self.rpc_region("Operator.snapshot_save", {})
            return {"Snapshot": base64.b64encode(resp["snapshot"]).decode()}

        def operator_snapshot_restore(p, q, body, tok):
            import base64

            data = base64.b64decode(body["Snapshot"])
            return self.rpc_region(
                "Operator.snapshot_restore", {"data": data}
            )

        def operator_raft_remove_peer(p, q, body, tok):
            peer = q.get("id", [""])[0] or (body or {}).get("ID", "")
            if not peer:
                raise HTTPError(400, "peer id required")
            self.rpc_region(
                "Operator.raft_remove_peer", {"peer_id": peer}
            )
            return None

        def operator_raft_config(p, q, body, tok):
            return self.rpc_region("Operator.raft_configuration", {})

        route("GET", "/v1/operator/snapshot", operator_snapshot_save)
        route("PUT", "/v1/operator/snapshot", operator_snapshot_restore)
        route("POST", "/v1/operator/snapshot", operator_snapshot_restore)
        def operator_cluster_health(p, q, body, tok):
            # /v1/operator/cluster/health: leader-side telemetry
            # federation (cluster.py cluster_health) — every member's
            # raft indices / broker + plan-queue depths / host CPU+RSS /
            # per-source cost top-K, with partitioned members flagged
            # `degraded` under a bounded per-peer deadline. agent:read
            # like the other observability surfaces (acl/enforce.py),
            # throttle-exempt so the dashboard stays readable during
            # the incident it diagnoses.
            try:
                timeout_s = float(q.get("timeout", ["2.0"])[0])
            except ValueError:
                raise HTTPError(400, "timeout must be a number")
            try:
                top = int(q.get("top", ["5"])[0])
            except ValueError:
                raise HTTPError(400, "top must be an integer")
            return self.cluster.cluster_health(
                per_peer_timeout_s=timeout_s, top=top
            )

        route(
            "GET", "/v1/operator/cluster/health", operator_cluster_health
        )
        route("GET", "/v1/operator/raft/configuration", operator_raft_config)
        route(
            "DELETE", "/v1/operator/raft/peer", operator_raft_remove_peer
        )

        def autopilot_get(p, q, body, tok):
            return self.rpc_region("Operator.autopilot_get_config", {})

        def autopilot_set(p, q, body, tok):
            return self.rpc_region(
                "Operator.autopilot_set_config", {"config": body or {}}
            )

        def agent_force_leave(p, q, body, tok):
            member = q.get("node", [""])[0]
            if not member:
                raise HTTPError(400, "node query param required")
            acked = self.rpc_region(
                "Operator.force_leave", {"member_id": member}
            )
            return {"Acked": acked}

        route(
            "GET", "/v1/operator/autopilot/configuration", autopilot_get
        )
        route(
            "PUT", "/v1/operator/autopilot/configuration", autopilot_set
        )
        route(
            "POST", "/v1/operator/autopilot/configuration", autopilot_set
        )
        route("PUT", "/v1/agent/force-leave", agent_force_leave)
        route("POST", "/v1/agent/force-leave", agent_force_leave)

        route("GET", "/v1/status/leader", status_leader)
        route("GET", "/v1/status/peers", status_peers)
        route("GET", "/v1/regions", regions_list)
        route("GET", "/v1/metrics", agent_metrics)
        # pprof analogs (reference command/agent/pprof, behind agent:read
        # via the /v1/agent/ ACL prefix)
        route("GET", "/v1/agent/pprof/goroutine", pprof_goroutine)
        route("GET", "/v1/agent/pprof/profile", pprof_profile)
        route("GET", "/v1/agent/pprof/heap", pprof_heap)
        route("GET", "/v1/agent/members", agent_members)
        route("GET", "/v1/agent/self", agent_self)
        route("GET", "/v1/agent/keyring", agent_keyring)
        route("PUT", "/v1/agent/keyring/rotate", agent_keyring_rotate)
        route("POST", "/v1/agent/keyring/rotate", agent_keyring_rotate)
        route("GET", "/v1/agent/monitor", agent_monitor)
        route("GET", "/v1/agent/health", agent_health)
        route("PUT", "/v1/agent/join", agent_join)
        route("POST", "/v1/agent/join", agent_join)

    # -- event stream (long-lived NDJSON response) ---------------------

    # -- browser exec (WebSocket bridge to the fabric exec stream) ------

    def _serve_exec_ws(self, handler, alloc_id, query, token) -> None:
        """RFC6455 WebSocket endpoint bridging a browser terminal to the
        fabric's interactive exec stream (reference: the Ember UI's
        /v1/client/allocation/:id/exec websocket, bridged to the same
        streaming RPC the CLI uses). Message protocol, JSON text frames:
        client -> {"stdin": <b64>}; server -> {"stdout": <b64>},
        {"error": str}, {"exit": true}. The browser cannot set
        X-Nomad-Token on a websocket, so ?token= is accepted here (as
        the reference does for its ws_handshake)."""
        import base64
        import hashlib
        import struct
        import threading

        alloc = self._resolve_alloc(alloc_id)
        self._ns_guard(token, alloc.namespace, "alloc-exec")
        key = handler.headers.get("Sec-WebSocket-Key", "")
        if not key:
            raise HTTPError(400, "missing Sec-WebSocket-Key")
        accept = base64.b64encode(
            hashlib.sha1(
                (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
            ).digest()
        ).decode()
        conn = handler.connection
        # exec sessions are long-lived and a browser sends nothing while
        # the user watches output — the handler's 120s read timeout must
        # not tear the session down
        conn.settimeout(None)
        conn.sendall(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + accept.encode() + b"\r\n\r\n"
        )
        handler.close_connection = True
        # one writer at a time: output frames (pump thread) and pong /
        # close frames (reader thread) must never interleave mid-frame
        wlock = threading.Lock()

        def raw_send(data: bytes) -> None:
            with wlock:
                conn.sendall(data)

        def ws_send(obj) -> None:
            payload = json.dumps(obj).encode()
            head = bytearray([0x81])  # FIN + text
            n = len(payload)
            if n < 126:
                head.append(n)
            elif n < 65536:
                head.append(126)
                head += struct.pack(">H", n)
            else:
                head.append(127)
                head += struct.pack(">Q", n)
            raw_send(bytes(head) + payload)

        rfile = handler.rfile

        def ws_recv():
            """One frame -> (opcode, payload) or None on EOF."""
            hdr = rfile.read(2)
            if len(hdr) < 2:
                return None
            opcode = hdr[0] & 0x0F
            masked = hdr[1] & 0x80
            n = hdr[1] & 0x7F
            if n == 126:
                n = struct.unpack(">H", rfile.read(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", rfile.read(8))[0]
            mask = rfile.read(4) if masked else b""
            data = rfile.read(n) if n else b""
            if masked and data:
                data = bytes(
                    b ^ mask[i % 4] for i, b in enumerate(data)
                )
            return opcode, data

        cmd = query.get("command", []) or ["/bin/sh"]
        task = query.get("task", [""])[0]
        tty = query.get("tty", ["false"])[0] == "true"
        try:
            session = self.cluster.pool.stream(
                self.cluster.rpc.addr,
                "ClientExec.exec",
                {
                    "alloc_id": alloc.id,
                    "task": task,
                    "cmd": list(cmd),
                    "tty": tty,
                    "token": token,
                },
            )
        except Exception as e:
            # the 101 already went out: any failure from here on must be
            # a websocket frame, never HTTP bytes into the upgraded stream
            try:
                ws_send({"error": f"exec stream failed: {e}"})
                raw_send(b"\x88\x00")
            except OSError:
                pass
            return
        done = threading.Event()

        def pump_output() -> None:
            try:
                while not done.is_set():
                    try:
                        msg = session.recv(timeout_s=0.5)
                    except TimeoutError:
                        continue
                    except (ConnectionError, OSError):
                        break
                    if msg is None:
                        continue
                    if msg.get("error"):
                        ws_send({"error": msg["error"]})
                        break
                    data = msg.get("data")
                    if data:
                        ws_send(
                            {
                                "stdout": base64.b64encode(data).decode()
                            }
                        )
                    if msg.get("eof"):
                        ws_send({"exit": True})
                        break
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            finally:
                done.set()
                try:
                    raw_send(b"\x88\x00")  # close frame
                except OSError:
                    pass

        t = threading.Thread(
            target=pump_output, name="ws-exec-out", daemon=True
        )
        t.start()
        try:
            while not done.is_set():
                frame = ws_recv()
                if frame is None:
                    break
                opcode, data = frame
                if opcode == 0x8:  # close
                    break
                if opcode == 0x9:  # ping -> pong
                    raw_send(b"\x8a" + bytes([len(data)]) + data)
                    continue
                if opcode != 0x1 or not data:
                    continue
                try:
                    msg = json.loads(data)
                except ValueError:
                    continue
                if "stdin" in msg:
                    session.send(
                        {"stdin": base64.b64decode(msg["stdin"])}
                    )
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            done.set()
            try:
                session.send({"eof": True})
            except (ConnectionError, OSError):
                pass
            session.close()
            t.join(timeout=2)

    def _serve_event_stream(self, handler, query) -> None:
        topics: dict[str, list[str]] = {}
        for t in query.get("topic", []):
            if ":" in t:
                topic, key = t.split(":", 1)
            else:
                topic, key = t, "*"
            topics.setdefault(topic, []).append(key)
        index = int(query.get("index", ["0"])[0])
        # Namespace defaults differ by mode: with ACLs enforced the stream
        # is scoped to one namespace ("default" unless asked); "*" (all)
        # is management-only and checked by the resolver. Without ACLs,
        # default to everything — the convenient open-mode behavior.
        if self.acl_resolver is not None:
            ns = query.get("namespace", ["default"])[0]
            if ns == "*":
                ns = ""
        else:
            ns = query.get("namespace", [""])[0]
        sub = self.cluster.server.event_broker.subscribe(
            topics or None, from_index=index, namespace=ns
        )
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def write_chunk(data: bytes) -> None:
            handler.wfile.write(f"{len(data):x}\r\n".encode())
            handler.wfile.write(data + b"\r\n")
            handler.wfile.flush()

        def conn_alive() -> bool:
            # A quiet stream only touches the socket at heartbeat time,
            # so a streamer whose connection died parks its thread (and
            # its broker subscription) until the next write. Probe
            # between events: readable + empty MSG_PEEK = peer closed
            # (a streaming GET never pipelines more request bytes).
            try:
                readable, _w, _x = select.select(
                    [handler.connection], [], [], 0
                )
                if not readable:
                    return True
                return handler.connection.recv(1, socket.MSG_PEEK) != b""
            except (OSError, ValueError):
                return False

        last_write = time.monotonic()
        try:
            while True:
                try:
                    # short hold: bounds how long a dead connection can
                    # pin a subscription between liveness probes
                    events = sub.next(timeout_s=2.0)
                except SubscriptionClosedError:
                    return
                if not events:
                    if not conn_alive():
                        metrics.incr("nomad.stream.reaped")
                        return
                    if time.monotonic() - last_write >= 10.0:
                        write_chunk(b"{}\n")  # heartbeat (reference sends {})
                        last_write = time.monotonic()
                    continue
                payload = {
                    "Index": events[-1].index,
                    "Events": [
                        {
                            "Topic": e.topic,
                            "Type": e.type,
                            "Key": e.key,
                            "Namespace": e.namespace,
                            "Index": e.index,
                            "Payload": codec.to_wire(e.payload),
                        }
                        for e in events
                    ],
                }
                write_chunk(json.dumps(payload, default=_json_default).encode() + b"\n")
                last_write = time.monotonic()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            sub.close()
            try:
                write_chunk(b"")
            except OSError:
                pass

    # -- client fs/logs streaming (reference client_fs_endpoint.go) ----

    def _resolve_alloc(self, alloc_id: str):
        try:
            alloc, _ = self.cluster.find_alloc_client(alloc_id)
        except LookupError as e:
            raise HTTPError(
                400 if "ambiguous" in str(e) else 404, str(e)
            ) from e
        return alloc

    def _client_session(self, alloc, method: str, header: dict):
        """Dial the alloc's client agent (advertised node attr) and open
        a stream — the server half of the 4-boundary streaming path."""
        try:
            _, addr = self.cluster.find_alloc_client(alloc.id)
        except LookupError as e:
            raise HTTPError(404, str(e)) from e
        header = dict(header)
        header["alloc_id"] = alloc.id
        try:
            session = self.cluster.pool.stream(addr, method, header)
        except (ConnectionError, OSError) as e:
            # NAT/firewall fallback (reference client_rpc.go): open the
            # stream over a connection the client parked on this server.
            session = self.cluster.take_reverse_session(
                alloc.node_id, method, header
            )
            if session is None:
                raise HTTPError(
                    502,
                    f"client agent unreachable ({e}) and no reverse "
                    f"session parked for node {alloc.node_id[:8]}",
                )
        # Track live relay sessions (telemetry + the /v1/metrics gauge):
        # wrap close() so every exit path decrements exactly once.
        with self._relay_lock:
            if self._relay_active >= self._relay_max:
                session.close()
                raise HTTPError(
                    429,
                    f"too many concurrent client streams "
                    f"({self._relay_max}); retry shortly",
                )
            self._relay_active += 1
            metrics.set_gauge(
                "nomad.http.relay_sessions_active", self._relay_active
            )
        metrics.incr("nomad.http.relay_sessions_total")
        orig_close = session.close
        closed = [False]

        def tracked_close():
            with self._relay_lock:
                if not closed[0]:
                    closed[0] = True
                    self._relay_active -= 1
                    metrics.set_gauge(
                        "nomad.http.relay_sessions_active", self._relay_active
                    )
            orig_close()

        session.close = tracked_close
        return session

    def _serve_monitor(self, handler, query) -> None:
        """Stream the agent's own log records as NDJSON (reference
        command/agent/monitor: `nomad monitor` tails agent logs over
        HTTP). A queue-backed logging.Handler attaches for the life of
        the request; disconnect detaches it."""
        import logging as _logging
        import queue as _queue

        level = getattr(
            _logging,
            query.get("log_level", ["INFO"])[0].upper(),
            _logging.INFO,
        )
        q: "_queue.Queue" = _queue.Queue(maxsize=512)

        class _QueueHandler(_logging.Handler):
            def emit(self, record):
                try:
                    q.put_nowait({
                        "Level": record.levelname,
                        "Name": record.name,
                        "Message": record.getMessage(),
                        "Time": record.created,
                    })
                except _queue.Full:
                    pass  # slow consumer: drop, never block the logger

        qh = _QueueHandler(level=level)
        root = _logging.getLogger()
        # Concurrent monitors must not fight over the root level: keep a
        # refcounted set of requested levels; the root runs at the min
        # of (original, active requests) and restores the original only
        # when the LAST monitor detaches.
        with self._monitor_lock:
            if not self._monitor_levels:
                self._monitor_base_level = root.level
            self._monitor_levels.append(level)
            root.setLevel(min(self._monitor_base_level, *self._monitor_levels))
        root.addHandler(qh)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/x-ndjson")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def chunk(data: bytes) -> None:
                handler.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n"
                )
                handler.wfile.flush()

            while True:
                try:
                    rec = q.get(timeout=10.0)
                except _queue.Empty:
                    chunk(b"{}\n")  # keepalive; detects dead consumers
                    continue
                chunk((json.dumps(rec) + "\n").encode())
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            root.removeHandler(qh)
            with self._monitor_lock:
                self._monitor_levels.remove(level)
                if self._monitor_levels:
                    root.setLevel(
                        min(self._monitor_base_level, *self._monitor_levels)
                    )
                else:
                    root.setLevel(self._monitor_base_level)

    def _client_roundtrip(self, alloc, method: str, header: dict) -> dict:
        session = self._client_session(alloc, method, header)
        try:
            # short: a one-shot ls/stat against a local file — a slow
            # client agent must not pin an HTTP worker for 30s
            msg = session.recv(timeout_s=10)
        except TimeoutError:
            raise HTTPError(504, "client agent timed out")
        finally:
            session.close()
        if msg.get("error"):
            raise HTTPError(500, msg["error"])
        return msg

    def _serve_fs_raw(self, handler, alloc_id: str, method: str, header: dict):
        """Relay a client byte stream as a chunked HTTP response
        (logs/cat; follow=true keeps the connection open)."""
        alloc = self._resolve_alloc(alloc_id)
        session = self._client_session(alloc, method, header)
        started = False
        try:
            while True:
                try:
                    msg = session.recv(timeout_s=60)
                except (TimeoutError, ConnectionError, OSError):
                    break
                if msg.get("error"):
                    if not started:
                        raise HTTPError(500, msg["error"])
                    break
                if not started:
                    handler.send_response(200)
                    handler.send_header(
                        "Content-Type", "application/octet-stream"
                    )
                    handler.send_header("Transfer-Encoding", "chunked")
                    handler.end_headers()
                    started = True
                data = msg.get("data")
                if data:
                    handler.wfile.write(f"{len(data):x}\r\n".encode())
                    handler.wfile.write(data + b"\r\n")
                    handler.wfile.flush()
                if msg.get("eof"):
                    break
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            session.close()
            if started:
                try:
                    handler.wfile.write(b"0\r\n\r\n")
                    handler.wfile.flush()
                except OSError:
                    pass
        if not started:
            raise HTTPError(502, "no data from client agent")

    # -- the handler class ---------------------------------------------

    def _make_handler(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # bounds half-open connections AND the deferred TLS
            # handshake; long-lived streams (event stream, monitor,
            # logs -f) manage their own cadence under this
            timeout = 120

            def log_message(self, fmt, *args):  # quiet
                logger.debug("http: " + fmt, *args)

            def _dispatch(self, method: str) -> None:
                parsed = urlparse(self.path)
                query = parse_qs(parsed.query)
                _REQ_REGION.set(query.get("region", [""])[0])
                token = self.headers.get("X-Nomad-Token", "")
                _REQ_TOKEN.set(token)
                # Drain the body up front: on keep-alive connections an
                # unread body (404 path, ACL reject) would desync the
                # next request on the same socket.
                raw_body = b""
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    raw_body = self.rfile.read(length)
                # UI static shell (reference: http.go serves the Ember
                # app at /ui with / redirecting there). No auth: the
                # shell is public; every API call it makes carries the
                # operator's token.
                if method == "GET" and (
                    parsed.path == "/"
                    or parsed.path == "/ui"
                    or parsed.path.startswith("/ui/")
                ):
                    if parsed.path == "/":
                        self.send_response(307)
                        self.send_header("Location", "/ui/")
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    from .ui import INDEX_HTML

                    data = INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/html; charset=utf-8"
                    )
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                try:
                    # Front-door rate limit BEFORE token resolution and
                    # routing: during overload, rejected requests must
                    # cost as little as possible (observability routes
                    # are exempt — see _THROTTLE_EXEMPT).
                    outer._throttle_check(parsed.path, query, raw_body)
                    exec_m = re.match(
                        r"^/v1/client/allocation/(?P<id>[^/]+)/exec$",
                        parsed.path,
                    )
                    if (
                        method == "GET"
                        and exec_m
                        and "websocket"
                        in (self.headers.get("Upgrade") or "").lower()
                    ):
                        # BEFORE the generic resolver: browsers cannot
                        # set X-Nomad-Token on a websocket, so the token
                        # may ride ?token= — _serve_exec_ws enforces
                        # alloc-exec on the alloc's own namespace itself
                        outer._serve_exec_ws(
                            self,
                            exec_m.group("id"),
                            query,
                            token or query.get("token", [""])[0],
                        )
                        return
                    if outer.acl_resolver is not None:
                        from ..acl.enforce import AuthError

                        try:
                            outer.acl_resolver(
                                method, parsed.path, token, query, raw_body
                            )
                        except AuthError as ae:
                            raise HTTPError(ae.status, ae.message)
                    if parsed.path == "/v1/event/stream":
                        outer._serve_event_stream(self, query)
                        return
                    if parsed.path == "/v1/agent/monitor":
                        outer._serve_monitor(self, query)
                        return
                    fs_m = re.match(
                        r"^/v1/client/fs/(logs|cat)/(?P<id>[^/]+)$",
                        parsed.path,
                    )
                    if method == "GET" and fs_m:
                        alloc = outer._resolve_alloc(fs_m.group("id"))
                        if fs_m.group(1) == "logs":
                            outer._ns_guard(token, alloc.namespace, "read-logs")
                            hdr = {
                                "task": query.get("task", [""])[0],
                                "type": query.get("type", ["stdout"])[0],
                                "follow": query.get("follow", ["false"])[0]
                                == "true",
                                "origin": query.get("origin", ["start"])[0],
                                "offset": int(query.get("offset", ["0"])[0]),
                            }
                            outer._serve_fs_raw(self, alloc.id, "FS.logs", hdr)
                        else:
                            outer._ns_guard(token, alloc.namespace, "read-fs")
                            hdr = {"path": query.get("path", [""])[0]}
                            outer._serve_fs_raw(self, alloc.id, "FS.cat", hdr)
                        return
                    for m, pattern, fn, mlabel in outer._routes:
                        if m != method:
                            continue
                        match = pattern.match(parsed.path)
                        if match is None:
                            continue
                        t0 = time.perf_counter()
                        try:
                            self._run_route(
                                fn, match, query, raw_body, token, method,
                                parsed,
                            )
                        finally:
                            metrics.observe(
                                mlabel, time.perf_counter() - t0
                            )
                        return
                    self._reply(404, {"error": f"no route {method} {parsed.path}"})
                except HTTPError as e:
                    payload = {"error": e.message}
                    if e.retry_after is not None:
                        payload["retry_after_s"] = round(e.retry_after, 3)
                    self._reply(
                        e.status, payload, retry_after=e.retry_after
                    )
                except ConflictError as e:
                    # Expected operational rejections (e.g. re-running acl
                    # bootstrap): client error, not a 500.
                    self._reply(400, {"error": str(e)})
                except PermissionError as e:
                    # federated/endpoint-level ACL denials (e.g. the
                    # target region's cross-region precheck)
                    self._reply(403, {"error": str(e)})
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception as e:
                    throttled = outer._map_throttle_error(e)
                    if throttled is not None:
                        payload = {"error": throttled.message}
                        if throttled.retry_after is not None:
                            payload["retry_after_s"] = round(
                                throttled.retry_after, 3
                            )
                        self._reply(
                            throttled.status,
                            payload,
                            retry_after=throttled.retry_after,
                        )
                        return
                    logger.exception("http handler failed")
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def _run_route(
                self, fn, match, query, raw_body, token, method, parsed
            ) -> None:
                body = json.loads(raw_body or b"{}")
                # Write requests open a trace when tracing is on:
                # the RPC fabric forwards the context, so a
                # submit on a follower stitches through to the
                # leader's raft apply (trace.py).
                hctx = None
                if method != "GET":
                    from .. import trace as _trace

                    hctx = _trace.start_trace(
                        "http", method=method, path=parsed.path
                    )
                if hctx is not None:
                    try:
                        with _trace.use(hctx):
                            result = fn(
                                match.groupdict(), query, body, token
                            )
                    except BaseException as e:
                        # a failed write must not be recorded as
                        # status=ok — the surface exists to debug
                        # exactly these
                        hctx.set_attr("error", type(e).__name__)
                        hctx.finish("error")
                        raise
                    hctx.finish()
                else:
                    result = fn(match.groupdict(), query, body, token)
                index = None
                if isinstance(result, tuple):
                    result, index = result
                if isinstance(result, RawResponse):
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", result.content_type
                    )
                    self.send_header(
                        "Content-Length", str(len(result.data))
                    )
                    self.end_headers()
                    self.wfile.write(result.data)
                    return
                self._reply(200, codec.to_wire(result), index)

            def _reply(self, status: int, payload,
                       index: Optional[int] = None,
                       retry_after: Optional[float] = None):
                data = json.dumps(payload, default=_json_default).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                if retry_after is not None:
                    # RFC 9110 delay-seconds is integral; sub-second
                    # precision rides in the JSON body (retry_after_s)
                    import math as _math

                    self.send_header(
                        "Retry-After",
                        str(max(1, int(_math.ceil(retry_after)))),
                    )
                # gzip negotiation (reference command/agent/http.go:248
                # wraps every handler in gziphandler): list payloads at
                # cluster scale compress ~10x; tiny replies skip the
                # header+CPU cost. Vary tells caches the body depends on
                # the request encoding; q=0 is an explicit refusal.
                self.send_header("Vary", "Accept-Encoding")
                if len(data) > 1024 and _accepts_gzip(
                    self.headers.get("Accept-Encoding")
                ):
                    import gzip as _gzip

                    data = _gzip.compress(data, compresslevel=1)
                    self.send_header("Content-Encoding", "gzip")
                self.send_header("Content-Length", str(len(data)))
                if index is not None:
                    self.send_header("X-Nomad-Index", str(index))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch("GET")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

        return Handler


def _accepts_gzip(header: Optional[str]) -> bool:
    """Accept-Encoding negotiation for gzip: present and not q=0."""
    for part in (header or "").split(","):
        toks = [t.strip() for t in part.split(";")]
        if not toks or toks[0] != "gzip":
            continue
        for t in toks[1:]:
            if t.startswith("q="):
                try:
                    return float(t[2:]) > 0
                except ValueError:
                    return True
        return True
    return False


def _parse_wait(raw: str) -> float:
    """'5s' / '1m' / '500ms' / plain seconds (reference parses duration)."""
    raw = raw.strip()
    if not raw or raw == "0":
        return 0.0
    if raw.endswith("ms"):
        return float(raw[:-2]) / 1000.0
    if raw.endswith("s"):
        return float(raw[:-1])
    if raw.endswith("m"):
        return float(raw[:-1]) * 60.0
    return float(raw)
