"""The agent process: embedded server and/or client plus the HTTP API.

Reference: command/agent/agent.go + command/agent/command.go — `nomad
agent` reads config, conditionally starts an in-process server and/or
client, wires them together (a co-located client talks to its own server
first), and serves HTTP.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from ..client import Client
from ..server.cluster import ClusterRPC, ClusterServer

logger = logging.getLogger("nomad_tpu.agent")


class InProcessClusterRPC:
    """Client→server verbs dispatched through the local ClusterServer's
    forwarding endpoints (no socket hop; leader forwarding intact)."""

    def __init__(self, cluster: ClusterServer) -> None:
        self.cluster = cluster

    def reverse_addrs(self) -> list:
        """The co-located server's fabric addr: reverse sessions parked
        there serve streams even when the advertised forward-dial
        address is unreachable."""
        return [tuple(self.cluster.rpc.addr)]

    def register(self, node) -> float:
        return self.cluster.rpc_self("Node.register", {"node": node})

    def alloc_client_addr(self, alloc_id: str):
        out = self.cluster.rpc_self("Alloc.client_addr", {"alloc_id": alloc_id})
        return tuple(out) if out else (None, None)

    def heartbeat(self, node_id: str) -> float:
        return self.cluster.rpc_self("Node.heartbeat", {"node_id": node_id})

    def get_client_allocs(self, node_id: str, min_index: int, timeout_s: float):
        resp = self.cluster.rpc_self(
            "Node.get_client_allocs",
            {"node_id": node_id, "min_index": min_index, "timeout_s": timeout_s},
        )
        return resp["allocs"], resp["index"]

    def update_allocs(self, allocs) -> None:
        self.cluster.rpc_self("Node.update_allocs", {"allocs": allocs})

    def volumes_for_alloc(self, alloc_id: str) -> list:
        return self.cluster.rpc_self(
            "Volume.for_alloc", {"alloc_id": alloc_id}
        )

    def services_register(self, regs: list) -> None:
        self.cluster.rpc_self("Service.register", {"regs": regs})

    def services_deregister_alloc(self, alloc_id: str) -> None:
        self.cluster.rpc_self(
            "Service.deregister_alloc", {"alloc_id": alloc_id}
        )

    def service_lookup(self, namespace: str, name: str) -> list:
        return self.cluster.rpc_self(
            "Service.get", {"namespace": namespace, "name": name}
        )

    def secret_read(self, namespace: str, path: str, token: str = ""):
        return self.cluster.rpc_self(
            "Secrets.read",
            {"namespace": namespace, "path": path, "token": token},
        )

    def derive_token(self, alloc_id: str, task_name: str) -> dict:
        return self.cluster.rpc_self(
            "Secrets.derive_token",
            {"alloc_id": alloc_id, "task_name": task_name},
        )

    def renew_token(self, accessor_id: str) -> float:
        return self.cluster.rpc_self(
            "Secrets.renew_token", {"accessor_id": accessor_id}
        )

    def revoke_token(self, accessor_id: str) -> None:
        self.cluster.rpc_self(
            "Secrets.revoke_token", {"accessor_id": accessor_id}
        )


def _tls_fingerprint(cert_file: str, key_file: str, ca_file: str = "") -> str:
    """Content hash of the TLS material triple — reload() compares it to
    detect in-place cert rotation (same paths, new bytes)."""
    import hashlib

    hsh = hashlib.sha256()
    for path in (cert_file, key_file, ca_file):
        if path:
            try:
                with open(path, "rb") as f:
                    hsh.update(f.read())
            except OSError:
                pass
    return hsh.hexdigest()


@dataclass
class AgentConfig:
    """Reference: command/agent/config.go (subset; grows with features)."""

    node_name: str = ""
    region: str = "global"
    datacenter: str = "dc1"
    data_dir: str = "/tmp/nomad_tpu"
    bind_addr: str = "127.0.0.1"
    # server stanza
    server_enabled: bool = False
    bootstrap_expect: int = 1
    rpc_port: int = 0  # 0 = ephemeral (reference default 4647)
    # client stanza
    client_enabled: bool = False
    client_servers: list = field(default_factory=list)  # [(host, port)]
    node_class: str = ""
    # CSI plugins: plugin_id -> builtin catalog name | "module:Class" ref
    csi_plugins: dict = field(default_factory=dict)
    # exec driver chroot map {host_src: dst_in_chroot} (reference:
    # client config chroot_env — operator-owned, never jobspec)
    chroot_env: dict = field(default_factory=dict)
    # operator-registered host volumes: name -> {path, read_only}
    # (reference: client config host_volume stanzas feed
    # Node.HostVolumes for the scheduler's HostVolumeChecker)
    host_volumes: dict = field(default_factory=dict)
    # static node metadata (reference: client config meta — constraint
    # and spread targets)
    node_meta: dict = field(default_factory=dict)
    # capacity carved out for the OS/agent (reference: client config
    # reserved stanza — subtracted from what the scheduler may pack)
    reserved: dict = field(default_factory=dict)
    # external task-driver plugins: driver name -> "module:Class" factory
    # ref, launched out-of-process over the plugin fabric (reference:
    # the go-plugin catalog, plugins/serve.go + helper/pluginutils)
    driver_plugins: dict = field(default_factory=dict)
    # external device plugins: name -> "module:Class" or
    # {"factory": ref, "config": {...}} (reference: plugins/device; the
    # builtin flagship is nomad_tpu.devices.tpu:TPUDevice)
    device_plugins: dict = field(default_factory=dict)
    # http
    http_port: int = 0  # reference default 4646
    # scheduler
    num_schedulers: int = 2
    use_tpu_batch_worker: bool = False
    # which eval types this server's workers serve (reference
    # EnabledSchedulers, config.go:159); None = all
    enabled_schedulers: Optional[list] = None
    # retry_join seeds (serf)
    server_join: list = field(default_factory=list)
    # acl stanza
    acl_enabled: bool = False
    # cluster shared secret authenticating the RPC fabric (rpc/server.py
    # trust-boundary note); empty ⇒ dev-mode trust-the-network.
    # Changing it in the config file + SIGHUP rotates the LIVE keyring
    # (Agent.reload): the old secret stays accepted for
    # rpc_secret_window_s while the rollout reaches every agent
    # (rpc/keyring.py dual-accept window).
    rpc_secret: str = ""
    rpc_secret_window_s: float = 60.0
    # dev mode: in-memory raft (the reference's -dev is ephemeral too)
    dev_mode: bool = False
    # pprof surface (reference enable_debug: off unless dev mode)
    enable_debug: bool = False
    # vault stanza: operator allowlist for task-derivable secret-token
    # policies (None = unrestricted, the reference default)
    vault_allowed_policies: Optional[list] = None
    # tls stanza (reference config tls { http rpc cert_file key_file
    # ca_file }): http serves the API over HTTPS; rpc wraps the fabric
    # (below) — the shared secret still authenticates when set
    tls_http: bool = False
    tls_cert_file: str = ""
    tls_key_file: str = ""
    # tls { rpc = true }: wrap the whole RPC fabric (server<->server,
    # server<->client, reverse-dial) in TLS; ca_file enables mTLS peer
    # verification (reference verify_incoming/verify_outgoing)
    tls_rpc: bool = False
    tls_ca_file: str = ""
    # telemetry stanza (reference: telemetry { statsd_address
    # collection_interval prometheus_metrics }): prometheus is pull-mode
    # via /v1/metrics?format=prometheus (always on); statsd pushes.
    telemetry_statsd_address: str = ""
    # DogStatsD push (reference telemetry { datadog_address }): statsd
    # wire format + constant |#tags (node/region/dc)
    telemetry_datadog_address: str = ""
    telemetry_interval_s: float = 10.0
    # eval-lifecycle tracing (trace.py): OFF by default — the no-op path
    # costs nothing on the hot paths. telemetry { trace_enabled = true
    # trace_buffer = 256 } turns on span collection into a bounded ring
    # served at /v1/traces; reloadable via SIGHUP (Agent.reload).
    trace_enabled: bool = False
    trace_buffer: int = 256
    # continuous host profiling (hostobs.py): ON by default — the whole
    # point is always-on attribution (the overhead gate holds it under
    # 5%). telemetry { host_profile = false } opts out;
    # host_profile_interval tunes the busy sampling period (the sampler
    # backs off ~10x on its own when the process idles). SIGHUP-
    # reloadable (Agent.reload).
    host_profile_enabled: bool = True
    host_profile_interval_ms: float = 10.0
    # broker stanza (overload protection; SIGHUP-reloadable): the eval
    # broker's delivery/nack knobs were constructor defaults only —
    # first-class config now — plus the admission bounds. broker {
    # delivery_limit nack_delay admission_depth namespace_cap
    # blocked_cap }. admission_depth 0 = unbounded (seed behavior);
    # namespace_cap 0 = no per-namespace fairness bound; blocked_cap 0
    # = unbounded blocked-evals tracker.
    broker_delivery_limit: int = 3
    broker_nack_delay_s: float = 5.0
    broker_admission_depth: int = 0
    broker_namespace_cap: int = 0
    blocked_evals_cap: int = 0
    # limits stanza (per-namespace token buckets on the front doors;
    # SIGHUP-reloadable): limits { http_rate http_burst rpc_rate
    # rpc_burst } in requests/second per namespace; 0 disables. Burst
    # defaults to the rate when unset.
    http_rate_limit: float = 0.0
    http_rate_burst: float = 0.0
    rpc_rate_limit: float = 0.0
    rpc_rate_burst: float = 0.0
    # limits { node_register_rate node_register_burst }: the server-wide
    # Node.register admission door (reconnect-storm pacing; 429 +
    # Retry-After). 0 disables; heartbeats are never limited.
    node_register_rate: float = 0.0
    node_register_burst: float = 0.0
    # solver_pool stanza (the warm placement tier, docs/solver-pool.md;
    # SIGHUP-reloadable): solver_pool { role members sync_interval }.
    # role "solver" advertises this server as a pool member (serf tag
    # solver=1) and runs the periodic resident-state warm loop; members
    # is an optional static allowlist of node names; sync_interval is
    # the member-side delta-sync period.
    solver_pool_role: str = ""
    solver_pool_members: tuple = ()
    solver_pool_sync_interval_s: float = 2.0
    # blackbox flight recorder (blackbox.py): ON by default — always-on
    # incident capture is the point (the throughput gate holds the
    # journal under 5%). telemetry { blackbox_enabled = false } opts
    # out; incident_dir overrides the data_dir/incidents default (dev
    # mode has no data_dir, so captures stay in-memory unless set);
    # incident_max bounds the capture index. SIGHUP-reloadable
    # (Agent.reload).
    blackbox_enabled: bool = True
    incident_dir: str = ""
    incident_max: int = 16

    @staticmethod
    def dev() -> "AgentConfig":
        """-dev mode: server + client in one process (reference
        DevConfig, command.go)."""
        return AgentConfig(server_enabled=True, client_enabled=True, dev_mode=True)


class Agent:
    def __init__(self, config: AgentConfig) -> None:
        if (config.tls_http or config.tls_rpc) and not (
            config.tls_cert_file and config.tls_key_file
        ):
            # silently serving plaintext when the operator asked for
            # TLS would put tokens on the wire in the clear
            raise ValueError(
                "tls { http/rpc = true } requires cert_file and key_file"
            )
        self.config = config
        # ONE keyring for every socket this agent owns — the server's
        # listener+dialer pool and the client's streaming listener and
        # ad-hoc pools all share it, so a single rotate() (SIGHUP with
        # a new rpc_secret, or POST /v1/agent/keyring/rotate) moves the
        # whole agent atomically (rpc/keyring.py).
        from ..rpc.keyring import Keyring

        self.keyring = Keyring(
            config.rpc_secret, window_s=config.rpc_secret_window_s
        )
        self.fabric_tls = None
        if config.tls_rpc:
            from ..rpc.tls import fabric_contexts

            self.fabric_tls = fabric_contexts(
                config.tls_cert_file,
                config.tls_key_file,
                config.tls_ca_file,
            )
        # baseline TLS-material fingerprint so reload() can detect
        # in-place cert rotation (same paths, new bytes)
        if config.tls_cert_file and config.tls_key_file:
            self._tls_fp = _tls_fingerprint(
                config.tls_cert_file, config.tls_key_file, config.tls_ca_file
            )
        self.server: Optional[ClusterServer] = None
        self.client: Optional[Client] = None
        self.http = None

        if config.server_enabled:
            # A join-configured server is joining an EXISTING cluster:
            # never self-bootstrap a cluster of one (expect=0 ⇒ wait to be
            # adopted), unless a larger bootstrap_expect says otherwise.
            expect = config.bootstrap_expect
            if config.server_join and expect <= 1:
                expect = 0
            # A durable server needs a STABLE identity across restarts
            # (the raft log/vote belongs to a node id) — persist the
            # generated name like the client persists its node id.
            name = config.node_name
            if not name and not config.dev_mode and config.data_dir:
                import os
                import uuid

                name_file = os.path.join(config.data_dir, "server", "node-name")
                try:
                    with open(name_file) as f:
                        name = f.read().strip()
                except OSError:
                    pass
                if not name:
                    name = f"server-{uuid.uuid4().hex[:8]}"
                    os.makedirs(os.path.dirname(name_file), exist_ok=True)
                    with open(name_file, "w") as f:
                        f.write(name)
            self.server = ClusterServer(
                name or f"server-{id(self) & 0xFFFF:x}",
                host=config.bind_addr,
                port=config.rpc_port,
                num_workers=config.num_schedulers,
                use_tpu_batch_worker=config.use_tpu_batch_worker,
                enabled_schedulers=config.enabled_schedulers,
                region=config.region,
                bootstrap_expect=expect,
                rpc_secret=self.keyring,
                data_dir=None if config.dev_mode else config.data_dir,
                acl_enforce=config.acl_enabled,
                tls=self.fabric_tls,
                solver_pool_role=config.solver_pool_role,
                solver_pool_members=config.solver_pool_members,
                solver_pool_sync_interval_s=config.solver_pool_sync_interval_s,
                blackbox_enabled=config.blackbox_enabled,
                # dev mode passes data_dir=None to ClusterServer, so an
                # explicitly configured incident_dir is the only way a
                # dev agent writes bundles to disk
                incident_dir=config.incident_dir or None,
                incident_max=config.incident_max,
            )
            self.server.server.vault_allowed_policies = (
                list(config.vault_allowed_policies)
                if config.vault_allowed_policies is not None
                else None
            )
        if config.client_enabled:
            if self.server is not None:
                # Co-located client: in-process, but through the CLUSTER
                # endpoints so writes forward to the leader — a client on
                # a follower server agent must still register (binding
                # ServerRPC to the local core server would NotLeaderError
                # forever).
                rpc = InProcessClusterRPC(self.server)
            else:
                if not config.client_servers:
                    raise ValueError("client agent needs `servers` addresses")
                rpc = ClusterRPC(
                    [tuple(a) for a in config.client_servers],
                    rpc_secret=self.keyring,
                    tls_context=(
                        self.fabric_tls[1] if self.fabric_tls else None
                    ),
                )
            self.client = Client(
                rpc,
                driver_plugins=config.driver_plugins,
                device_plugins=config.device_plugins,
                chroot_env=config.chroot_env,
                host_volumes=config.host_volumes,
                node_meta=config.node_meta,
                reserved=config.reserved,
                data_dir=config.data_dir,
                datacenter=config.datacenter,
                node_class=config.node_class,
                rpc_secret=self.keyring,
                advertise_host=config.bind_addr,
                csi_plugins=config.csi_plugins,
                tls=self.fabric_tls,
            )
        if self.server is not None:
            from .http import HTTPAgentServer

            resolver = None
            if config.acl_enabled:
                from ..acl.enforce import make_http_resolver

                resolver = make_http_resolver(self.server.server)
            self.http = HTTPAgentServer(
                self.server,
                client=self.client,
                host=config.bind_addr,
                port=config.http_port,
                acl_resolver=resolver,
                enable_debug=config.enable_debug or config.dev_mode,
                # API rotation must move the in-memory config too, or
                # the next SIGHUP would diff against the boot secret
                # and silently rotate BACK to the config file's value
                on_keyring_rotate=lambda s: setattr(
                    self.config, "rpc_secret", s
                ),
                tls_cert=(
                    config.tls_cert_file if config.tls_http else ""
                ),
                tls_key=(
                    config.tls_key_file if config.tls_http else ""
                ),
            )
        self._apply_overload_config(config)

    def _apply_overload_config(self, cfg: AgentConfig) -> None:
        """Push the broker/limits stanzas onto the live subsystems —
        shared by construction and SIGHUP reload."""
        if self.server is not None:
            self.server.server.eval_broker.configure(
                nack_delay_s=cfg.broker_nack_delay_s,
                delivery_limit=cfg.broker_delivery_limit,
                admission_depth=cfg.broker_admission_depth,
                namespace_cap=cfg.broker_namespace_cap,
            )
            self.server.server.blocked_evals.configure(
                cap=cfg.blocked_evals_cap
            )
            self.server.set_rate_limits(
                cfg.rpc_rate_limit, cfg.rpc_rate_burst
            )
            self.server.set_node_register_limit(
                cfg.node_register_rate, cfg.node_register_burst
            )
        if self.http is not None:
            self.http.set_rate_limits(
                cfg.http_rate_limit, cfg.http_rate_burst
            )

    def start(self) -> None:
        # Resolve the native wire codec before any lock exists to pack
        # under (nomad-vet NV-lock-blocking: the lazy first pack() can
        # otherwise compile the extension while holding the raft /
        # store / RPC-write lock).
        from .. import codec

        codec.warm_native()
        if self.config.trace_enabled:
            from .. import trace

            trace.configure(
                max_traces=self.config.trace_buffer, enabled_=True
            )
            self._trace_owner = True
        if self.config.host_profile_enabled:
            # before the server boots so bootstrap cost is attributable;
            # refcounted process-global singleton (in-process test
            # clusters share one sampler thread)
            from .. import hostobs

            hostobs.configure(
                interval_s=self.config.host_profile_interval_ms / 1e3,
                flush_interval_s=self.config.telemetry_interval_s or None,
            )
            hostobs.start()
            self._hostobs_started = True
        # telemetry { collection_interval } is also the histogram window
        # width (metrics.py windowed ring): "last window" in /v1/metrics
        # and `operator top` means the last collection interval. Applied
        # BEFORE the server starts — configure_windows only affects
        # histograms created after it, and server bootstrap (raft
        # applies at leadership) creates the first ones.
        if self.config.telemetry_interval_s:
            from .. import metrics as _metrics

            _metrics.registry().configure_windows(
                interval_s=self.config.telemetry_interval_s
            )
        if self.server is not None:
            self.server.start()
            if self.config.server_join:
                self.server.join([tuple(a) for a in self.config.server_join])
        # HTTP before the client: the API must come up even if client
        # registration is still waiting on a leader.
        if self.http is not None:
            self.http.start()
        if self.client is not None:
            self.client.start()
        if self.config.telemetry_statsd_address:
            from ..metrics import StatsdSink

            self.statsd = StatsdSink(
                self.config.telemetry_statsd_address,
                self.config.telemetry_interval_s,
            )
            self.statsd.start()
        if self.config.telemetry_datadog_address:
            from ..metrics import DatadogSink

            self.datadog = DatadogSink(
                self.config.telemetry_datadog_address,
                self.config.telemetry_interval_s,
                tags={
                    "node": self.config.node_name or "agent",
                    "region": self.config.region,
                    "datacenter": self.config.datacenter,
                },
            )
            self.datadog.start()
        # Everything built so far (modules, config, stores, subsystems)
        # is process-lifetime state: freeze it out of the cyclic
        # collector so steady-state GC passes only ever walk young
        # objects (gctune.py — the complement of the hot paths' pauses).
        from ..gctune import freeze_startup_heap

        freeze_startup_heap()

    def reload(self, new_config: AgentConfig) -> list[str]:
        """Apply the RELOADABLE subset of a re-read config to the live
        agent (reference command/agent/agent.go Agent.Reload, driven by
        SIGHUP in command.go handleSignals). Hot paths:

        - TLS material rotation: new certs/keys/CA load into the LIVE
          ssl contexts shared by every fabric socket and the HTTPS
          listener — subsequent handshakes present the new chain while
          established connections keep flowing (nothing is dropped).
        - client node_meta: replaced and re-registered so schedulers see
          new constraint/spread targets.
        - vault_allowed_policies: derivation allowlist swap.

        Everything else (ports, server/client enablement, data_dir,
        enabling TLS where it was off) still needs a restart — the same
        boundary the reference draws. Returns the list of applied
        changes for operator logs."""
        changed: list[str] = []
        old = self.config
        # rpc_secret rotation (the SIGHUP keyring push): the shared
        # keyring swaps the new secret in as current and keeps the old
        # one accepted for the dual-accept window; every listener and
        # pool in this agent reads the keyring live, so nothing is
        # restarted and established connections keep flowing. Rotating
        # BACK within the window swaps the slots again; a re-SIGHUP
        # with an unchanged secret is a no-op (Keyring.rotate contract).
        if new_config.rpc_secret_window_s != old.rpc_secret_window_s:
            self.keyring.window_s = new_config.rpc_secret_window_s
            old.rpc_secret_window_s = new_config.rpc_secret_window_s
        if new_config.rpc_secret != old.rpc_secret:
            if not new_config.rpc_secret:
                # refuse rather than silently opening the fabric — see
                # Keyring.rotate; removing auth needs a restart
                raise ValueError(
                    "cannot remove rpc_secret via reload (restart "
                    "the agent to disable fabric auth)"
                )
            if self.keyring.rotate(new_config.rpc_secret):
                changed.append("rpc_secret")
            old.rpc_secret = new_config.rpc_secret
        # Always re-read the material when TLS is on: operators rotate
        # certs IN PLACE (same path, new content) at least as often as
        # they change paths, and a path compare would silently skip
        # those. Re-loading unchanged files is harmless. A fingerprint
        # of the file contents decides whether to REPORT a change.
        if new_config.tls_cert_file and new_config.tls_key_file and (
            self.fabric_tls is not None or (self.http and self.http.tls)
        ):
            new_fp = _tls_fingerprint(
                new_config.tls_cert_file,
                new_config.tls_key_file,
                new_config.tls_ca_file,
            )
            rotated = new_fp != getattr(self, "_tls_fp", None) or (
                new_config.tls_cert_file,
                new_config.tls_key_file,
                new_config.tls_ca_file,
            ) != (old.tls_cert_file, old.tls_key_file, old.tls_ca_file)
            self._tls_fp = new_fp
            if rotated and self.fabric_tls is not None:
                server_ctx, client_ctx = self.fabric_tls
                server_ctx.load_cert_chain(
                    new_config.tls_cert_file, new_config.tls_key_file
                )
                client_ctx.load_cert_chain(
                    new_config.tls_cert_file, new_config.tls_key_file
                )
                if new_config.tls_ca_file:
                    server_ctx.load_verify_locations(new_config.tls_ca_file)
                    client_ctx.load_verify_locations(new_config.tls_ca_file)
                changed.append("tls_rpc_material")
            if (
                rotated
                and self.http is not None
                and old.tls_http
                and self.http.reload_tls(
                    new_config.tls_cert_file, new_config.tls_key_file
                )
            ):
                changed.append("tls_http_material")
            old.tls_cert_file = new_config.tls_cert_file
            old.tls_key_file = new_config.tls_key_file
            old.tls_ca_file = new_config.tls_ca_file
        if (
            self.client is not None
            and new_config.node_meta != old.node_meta
        ):
            self.client.update_node_meta(new_config.node_meta)
            old.node_meta = dict(new_config.node_meta)
            changed.append("client_node_meta")
        if (
            new_config.trace_enabled != old.trace_enabled
            or new_config.trace_buffer != old.trace_buffer
        ):
            from .. import trace

            trace.configure(
                max_traces=new_config.trace_buffer,
                enabled_=new_config.trace_enabled,
            )
            self._trace_owner = new_config.trace_enabled
            old.trace_enabled = new_config.trace_enabled
            old.trace_buffer = new_config.trace_buffer
            changed.append("trace")
        if (
            new_config.host_profile_enabled != old.host_profile_enabled
            or new_config.host_profile_interval_ms
            != old.host_profile_interval_ms
        ):
            from .. import hostobs

            hostobs.configure(
                interval_s=new_config.host_profile_interval_ms / 1e3
            )
            started = getattr(self, "_hostobs_started", False)
            if new_config.host_profile_enabled and not started:
                hostobs.start()
                self._hostobs_started = True
            elif not new_config.host_profile_enabled and started:
                # drops THIS agent's refcount; the sampler thread exits
                # when the last in-process owner lets go (no leaks
                # across SIGHUP cycles — the racecheck battery asserts)
                hostobs.stop()
                self._hostobs_started = False
            old.host_profile_enabled = new_config.host_profile_enabled
            old.host_profile_interval_ms = new_config.host_profile_interval_ms
            changed.append("host_profile")
        broker_keys = (
            "broker_delivery_limit",
            "broker_nack_delay_s",
            "broker_admission_depth",
            "broker_namespace_cap",
            "blocked_evals_cap",
        )
        limit_keys = (
            "http_rate_limit",
            "http_rate_burst",
            "rpc_rate_limit",
            "rpc_rate_burst",
            "node_register_rate",
            "node_register_burst",
        )
        broker_changed = any(
            getattr(new_config, k) != getattr(old, k) for k in broker_keys
        )
        limits_changed = any(
            getattr(new_config, k) != getattr(old, k) for k in limit_keys
        )
        if broker_changed or limits_changed:
            # one apply covers both stanzas; in-flight deliveries keep
            # their attempt counts and buckets keep their fill
            for k in broker_keys + limit_keys:
                setattr(old, k, getattr(new_config, k))
            self._apply_overload_config(old)
            if broker_changed:
                changed.append("broker")
            if limits_changed:
                changed.append("limits")
        blackbox_keys = ("blackbox_enabled", "incident_dir", "incident_max")
        if self.server is not None and any(
            getattr(new_config, k) != getattr(old, k) for k in blackbox_keys
        ):
            self.server.blackbox.reload(
                enabled=new_config.blackbox_enabled,
                incident_dir=new_config.incident_dir or None,
                incident_max=new_config.incident_max,
            )
            for k in blackbox_keys:
                setattr(old, k, getattr(new_config, k))
            changed.append("blackbox")
        pool_keys = (
            "solver_pool_role",
            "solver_pool_members",
            "solver_pool_sync_interval_s",
        )
        if self.server is not None and any(
            getattr(new_config, k) != getattr(old, k) for k in pool_keys
        ):
            self.server.solver_pool.configure(
                new_config.solver_pool_role,
                members=new_config.solver_pool_members,
                sync_interval_s=new_config.solver_pool_sync_interval_s,
            )
            for k in pool_keys:
                setattr(old, k, getattr(new_config, k))
            changed.append("solver_pool")
        if (
            self.server is not None
            and new_config.vault_allowed_policies != old.vault_allowed_policies
        ):
            self.server.server.vault_allowed_policies = (
                list(new_config.vault_allowed_policies)
                if new_config.vault_allowed_policies is not None
                else None
            )
            old.vault_allowed_policies = new_config.vault_allowed_policies
            changed.append("vault_allowed_policies")
        return changed

    def shutdown(self) -> None:
        if getattr(self, "_hostobs_started", False):
            from .. import hostobs

            hostobs.stop()
            self._hostobs_started = False
        if getattr(self, "_trace_owner", False):
            # tracing state is process-global (like the metrics registry):
            # only the agent that enabled it turns it back off
            from .. import trace

            trace.set_enabled(False)
        if getattr(self, "statsd", None) is not None:
            self.statsd.stop()
            self.statsd = None
        if getattr(self, "datadog", None) is not None:
            self.datadog.stop()
            self.datadog = None
        if self.http is not None:
            self.http.shutdown()
        if self.client is not None:
            self.client.shutdown()
        if self.server is not None:
            self.server.shutdown()

    @property
    def http_addr(self) -> Optional[tuple[str, int]]:
        return None if self.http is None else self.http.addr
