"""Agent: HTTP API + embedded server/client (reference command/agent/)."""

from .agent import Agent, AgentConfig
from .http import HTTPAgentServer

__all__ = ["Agent", "AgentConfig", "HTTPAgentServer"]
