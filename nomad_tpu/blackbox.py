"""Blackbox flight recorder: always-on control-plane journal,
anomaly triggers, incident ledger, and causal timeline math.

Every observability layer before this one (traces, histogram metrics,
solverobs, hostobs, clusterobs) is PULL-based: an operator must be
watching at the moment something goes wrong, and the soak
duplicate-alloc race took four rounds to root-cause precisely because
the evidence evaporated before anyone pulled it. The reference ships a
manual capture (`command/agent/debug.go`) plus an event stream
(`nomad/stream/`); this module is the always-on variant in the
Google-flight-recording lineage (same GWP ancestry as hostobs): the
system journals its own control-plane transitions, watches its own
counters, and captures its own incidents.

Four pieces, all bounded, all process-cheap:

  * :class:`FlightRecorder` — a ring journal of control-plane
    transitions (broker events, leadership edges, dup-mint trims,
    admission sheds, heartbeat expiry batches, pool-member faults,
    periodic health frames). A record is a timestamp + kind + key +
    small detail dict; the deque maxlen IS the eviction bound and
    evictions are counted, never silent (the hostobs/clusterobs
    discipline).
  * :class:`TriggerEngine` — declarative anomaly rules over plain
    name->value inputs: ``delta`` rules fire when a monotonic counter
    rises by >= threshold inside a sliding window (leader-change
    spike, shed/429 storm, device-failover burst, invariant-counter
    increment), ``level`` rules when a sampled value crosses a
    threshold (e2e p99 breach). Firings are deduped per rule and
    rate-limited globally so a flapping trigger cannot storm captures.
  * incident ledger — a bounded index of captured incidents (the
    on-disk bundles live under ``data_dir/incidents/<ts>-<reason>/``;
    the wiring in server/blackbox_wire.py writes them).
  * :func:`build_timeline` — pure merge of journal rows (which carry
    extracted cross-object ``rel`` links) into one causal
    ``eval -> plan -> alloc -> node`` view for a seed object, by
    bounded transitive expansion over the link graph.

Deliberately a stdlib-only leaf (registered in analysis/rules.py
LEAF_MODULES): metrics/trace are never imported here — journal writes
come from hook sites that already hold their own imports, trigger
inputs arrive as plain dicts, and the ``nomad.blackbox.*`` gauges are
pull-read by a provider registered in the wiring layer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

DEFAULT_JOURNAL_CAPACITY = 4096
DEFAULT_INCIDENT_MAX = 16
DEFAULT_DEDUP_WINDOW_S = 300.0
DEFAULT_MAX_PER_HOUR = 6

# Journal kinds (the closed vocabulary hook sites record under).
KIND_EVENT = "event"              # broker event (node/eval/alloc/...)
KIND_LEADERSHIP = "leadership"    # establish/revoke edge
KIND_DUP_MINT = "dup_mint"        # plan-apply duplicate-mint trim
KIND_SHED = "shed"                # eval-broker admission shed
KIND_EXPIRY = "heartbeat_expiry"  # heartbeat wheel expiry batch
KIND_POOL_FAULT = "pool_fault"    # solver-pool member fault
KIND_HEALTH = "health"            # periodic health frame
KIND_TRIGGER = "trigger"          # a rule fired
KIND_INCIDENT = "incident"        # a capture completed

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Recording gate (GIL-atomic flag): the uninstrumented side of the
    front-door throughput comparison gate; production leaves it on."""
    global _enabled
    _enabled = bool(on)


# -- trigger rules --------------------------------------------------------


@dataclass
class TriggerRule:
    """One declarative anomaly rule.

    ``source`` names a key in the values dict the engine is evaluated
    with (the wiring feeds ``journal:<kind>`` journal-kind counts,
    ``counter:<name>`` registry counters, and ``p99:<name>`` last-window
    histogram p99s). ``kind`` is ``delta`` (rise of a monotonic value by
    >= threshold within window_s) or ``level`` (sampled value >=
    threshold)."""

    name: str
    source: str
    kind: str  # "delta" | "level"
    threshold: float
    window_s: float = 60.0
    reason: str = ""


def default_rules() -> list[TriggerRule]:
    """The stock trigger catalogue (docs/incidents.md documents each).

    Thresholds are deliberately conservative: the tier-1 false-positive
    gate asserts a clean mini-soak captures ZERO incidents, so every
    default must be unreachable without a real anomaly. The leader
    rule's threshold of 2 is what keeps a clean boot quiet: a process
    establishes leadership exactly once on a healthy cluster, so two
    transitions inside one window always means churn."""
    return [
        TriggerRule(
            "leader-churn", f"journal:{KIND_LEADERSHIP}", "delta", 2,
            window_s=120.0,
            reason="2+ leadership transitions inside the window",
        ),
        TriggerRule(
            "shed-storm", "counter:nomad.broker.shed", "delta", 50,
            window_s=60.0,
            reason="admission control shed 50+ evals in the window",
        ),
        TriggerRule(
            "throttle-storm", "counter:nomad.http.throttled", "delta",
            100, window_s=60.0,
            reason="front door returned 100+ 429s in the window",
        ),
        TriggerRule(
            "device-failover-burst",
            "counter:nomad.worker.device_failover", "delta", 3,
            window_s=60.0,
            reason="3+ solver device failovers in the window",
        ),
        TriggerRule(
            "dup-mint-invariant",
            "counter:nomad.plan_apply.dup_mint_trimmed", "delta", 1,
            window_s=3600.0,
            reason="plan-apply trimmed a duplicate mint "
                   "(invariant counter moved)",
        ),
        TriggerRule(
            "e2e-p99-breach", "p99:nomad.eval.e2e_seconds", "level",
            30.0, window_s=60.0,
            reason="eval end-to-end p99 crossed 30s",
        ),
    ]


class TriggerEngine:
    """Evaluates rules over plain name->value inputs; dedupes and
    rate-limits firings.

    History is per rule: a deque of (t, value) samples pruned to the
    rule's window, so a ``delta`` rule compares the newest sample to
    the oldest one still inside the window — a counter that rose
    before the window opened never re-fires. Dedup suppresses a rule
    that fired inside ``dedup_window_s``; the global
    ``max_per_hour`` cap bounds capture volume across ALL rules (a
    flapping cluster must not fill the disk with bundles)."""

    def __init__(
        self,
        rules: Optional[list[TriggerRule]] = None,
        dedup_window_s: float = DEFAULT_DEDUP_WINDOW_S,
        max_per_hour: int = DEFAULT_MAX_PER_HOUR,
    ) -> None:
        self._lock = threading.Lock()
        self.rules: list[TriggerRule] = (
            list(rules) if rules is not None else default_rules()
        )
        self.dedup_window_s = float(dedup_window_s)
        self.max_per_hour = int(max_per_hour)
        self._history: dict[str, deque] = {}
        self._last_fired: dict[str, float] = {}
        self._fire_times: deque = deque(maxlen=256)
        self.fired = 0
        self.deduped = 0
        self.rate_limited = 0

    def reload(self, rules: Optional[list[TriggerRule]] = None) -> None:
        """Swap the rule set live (SIGHUP path); history for rules that
        survive by name is kept so windows don't reset on reload."""
        with self._lock:
            self.rules = (
                list(rules) if rules is not None else default_rules()
            )
            keep = {r.name for r in self.rules}
            for name in [n for n in self._history if n not in keep]:
                del self._history[name]

    def evaluate(
        self, values: dict, now: Optional[float] = None
    ) -> list[dict]:
        """One evaluation pass. Returns the firings that SURVIVED dedup
        and rate limiting, each as {"rule", "source", "kind", "value",
        "delta", "threshold", "reason"}."""
        t = time.monotonic() if now is None else now
        out: list[dict] = []
        with self._lock:
            for rule in self.rules:
                v = values.get(rule.source)
                if v is None:
                    continue
                v = float(v)
                crossed = False
                delta = 0.0
                if rule.kind == "delta":
                    hist = self._history.get(rule.name)
                    if hist is None:
                        hist = self._history[rule.name] = deque()
                    hist.append((t, v))
                    while hist and hist[0][0] < t - rule.window_s:
                        hist.popleft()
                    delta = v - hist[0][1]
                    crossed = delta >= rule.threshold
                else:  # level
                    delta = v
                    crossed = v >= rule.threshold
                if not crossed:
                    continue
                last = self._last_fired.get(rule.name)
                if last is not None and t - last < self.dedup_window_s:
                    self.deduped += 1
                    continue
                recent = sum(
                    1 for ft in self._fire_times if ft > t - 3600.0
                )
                if recent >= self.max_per_hour:
                    self.rate_limited += 1
                    continue
                self._last_fired[rule.name] = t
                self._fire_times.append(t)
                self.fired += 1
                # a delta rule that fired starts a fresh window so the
                # SAME rise can't re-fire after the dedup window ends
                if rule.kind == "delta":
                    self._history[rule.name] = deque([(t, v)])
                out.append({
                    "rule": rule.name,
                    "source": rule.source,
                    "kind": rule.kind,
                    "value": v,
                    "delta": round(delta, 6),
                    "threshold": rule.threshold,
                    "reason": rule.reason,
                })
        return out

    def status(self) -> dict:
        with self._lock:
            return {
                "rules": [
                    {
                        "name": r.name,
                        "source": r.source,
                        "kind": r.kind,
                        "threshold": r.threshold,
                        "window_s": r.window_s,
                        "reason": r.reason,
                        "last_fired_ago_s": (
                            round(
                                time.monotonic()
                                - self._last_fired[r.name], 3,
                            )
                            if r.name in self._last_fired else None
                        ),
                    }
                    for r in self.rules
                ],
                "dedup_window_s": self.dedup_window_s,
                "max_per_hour": self.max_per_hour,
                "fired": self.fired,
                "deduped": self.deduped,
                "rate_limited": self.rate_limited,
            }


# -- the flight recorder --------------------------------------------------

MAX_KINDS = 64


class FlightRecorder:
    """Bounded journal ring + trigger engine + incident index.

    One instance per process in production (the module global below);
    in-process test clusters share it, which is exactly what the chaos
    "exactly one deduped incident" assertion wants — dedup state is
    cluster-wide when the cluster is one process."""

    def __init__(
        self,
        capacity: int = DEFAULT_JOURNAL_CAPACITY,
        incident_max: int = DEFAULT_INCIDENT_MAX,
    ) -> None:
        self._lock = threading.Lock()
        self.capacity = max(16, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._kind_counts: dict[str, int] = {}
        self.recorded = 0
        self.triggers = TriggerEngine()
        self.incident_max = max(1, int(incident_max))
        self._incidents: deque = deque(maxlen=self.incident_max)
        self.incidents_captured = 0
        self.incidents_suppressed = 0

    # -- journal -------------------------------------------------------

    def record(self, kind: str, key: str = "", **detail) -> None:
        """Append one journal row. Hot-path cheap: a dict build + deque
        append under the lock; the deque maxlen is the eviction bound."""
        if not _enabled:
            return
        row = {"ts": time.time(), "kind": kind, "key": key}
        if detail:
            row["detail"] = detail
        with self._lock:
            self._seq += 1
            row["seq"] = self._seq
            self.recorded += 1
            if kind in self._kind_counts:
                self._kind_counts[kind] += 1
            elif len(self._kind_counts) < MAX_KINDS:
                self._kind_counts[kind] = 1
            self._ring.append(row)

    def snapshot(
        self,
        limit: int = 0,
        kind: Optional[str] = None,
        key_contains: Optional[str] = None,
    ) -> list[dict]:
        """Journal rows oldest-first, optionally filtered; ``limit``
        keeps the NEWEST n after filtering (0 = all buffered)."""
        with self._lock:
            rows = list(self._ring)
        if kind is not None:
            rows = [r for r in rows if r["kind"] == kind]
        if key_contains:
            rows = [r for r in rows if key_contains in r["key"]]
        if limit and len(rows) > limit:
            rows = rows[-limit:]
        return rows

    def kind_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._kind_counts)

    # -- incident index ------------------------------------------------

    def add_incident(
        self, incident_id: str, reason: str, path: str, detail: dict
    ) -> dict:
        rec = {
            "id": incident_id,
            "ts": time.time(),
            "reason": reason,
            "path": path,
            "detail": detail,
        }
        with self._lock:
            self._incidents.append(rec)
            self.incidents_captured += 1
        self.record(KIND_INCIDENT, incident_id, reason=reason, path=path)
        return rec

    def set_incident_max(self, incident_max: int) -> None:
        """Resize the incident index live (SIGHUP path); existing
        records are kept newest-last up to the new bound."""
        with self._lock:
            self.incident_max = max(1, int(incident_max))
            self._incidents = deque(
                self._incidents, maxlen=self.incident_max
            )

    def suppress_incident(self) -> None:
        """A capture was skipped by the single-flight gate (concurrent
        trigger while a bundle write was in progress)."""
        with self._lock:
            self.incidents_suppressed += 1

    def incidents(self) -> list[dict]:
        """Newest first (the /v1/incidents index)."""
        with self._lock:
            return list(reversed(self._incidents))

    def incident(self, incident_id: str) -> Optional[dict]:
        with self._lock:
            for rec in self._incidents:
                if rec["id"] == incident_id:
                    return dict(rec)
        return None

    # -- stats / lifecycle ---------------------------------------------

    def stats(self) -> dict:
        """Flat provider gauges (``nomad.blackbox.*`` rides the metrics
        registry via the wiring layer's register_provider)."""
        with self._lock:
            return {
                "journal_entries": float(len(self._ring)),
                "journal_recorded": float(self.recorded),
                "journal_evicted": float(
                    max(0, self.recorded - len(self._ring))
                ),
                "triggers_fired": float(self.triggers.fired),
                "triggers_deduped": float(self.triggers.deduped),
                "triggers_rate_limited": float(
                    self.triggers.rate_limited
                ),
                "incidents_captured": float(self.incidents_captured),
                "incidents_suppressed": float(self.incidents_suppressed),
                "incidents_stored": float(len(self._incidents)),
            }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._kind_counts.clear()
            self.recorded = 0
            self._incidents.clear()
            self.incidents_captured = 0
            self.incidents_suppressed = 0
            self.triggers = TriggerEngine(
                self.triggers.rules,
                self.triggers.dedup_window_s,
                self.triggers.max_per_hour,
            )


# -- causal timeline reconstruction ---------------------------------------

TIMELINE_KINDS = ("eval", "alloc", "node", "job", "deployment", "plan")


def _tokens_of(row: dict) -> set[str]:
    """Every object token one journal row mentions: its key plus the
    extracted ``rel`` cross-links (``kind:id`` strings the wiring
    attaches when it journals a broker event)."""
    toks: set[str] = set()
    key = row.get("key") or ""
    if ":" in key:
        toks.add(key)
    det = row.get("detail") or {}
    for tok in det.get("rel") or ():
        toks.add(tok)
    return toks


def build_timeline(
    kind: str,
    obj_id: str,
    rows: list[dict],
    hops: int = 2,
    limit: int = 500,
) -> dict:
    """Merge journal rows into one causal timeline for ``kind:obj_id``.

    Pure function over plain dicts: seed with the object's token,
    collect every row that mentions it, then expand ``hops`` times
    through the rows' cross-object links — one hop reaches an eval's
    plan and allocs, two reach the allocs' nodes — so the returned view
    is the ``eval -> plan -> alloc -> node`` chain the postmortem
    needs. Bounded: expansion stops at ``limit`` rows and the frontier
    only grows through tokens of :data:`TIMELINE_KINDS` shapes."""
    seed = f"{kind}:{obj_id}"
    wanted: set[str] = {seed}
    matched: dict[int, dict] = {}
    for _ in range(max(1, hops) + 1):
        frontier: set[str] = set()
        for row in rows:
            rid = row.get("seq", id(row))
            if rid in matched:
                continue
            toks = _tokens_of(row)
            if toks & wanted or obj_id and obj_id in (row.get("key") or ""):
                matched[rid] = row
                frontier |= toks
                if len(matched) >= limit:
                    break
        new = frontier - wanted
        if not new or len(matched) >= limit:
            break
        wanted |= new
    ordered = sorted(
        matched.values(), key=lambda r: (r.get("ts", 0), r.get("seq", 0))
    )
    return {
        "kind": kind,
        "id": obj_id,
        "related": sorted(wanted),
        "rows": ordered,
        "truncated": len(matched) >= limit,
    }


# -- process-global recorder ----------------------------------------------

_global = FlightRecorder()


def recorder() -> FlightRecorder:
    return _global


def record(kind: str, key: str = "", **detail) -> None:
    """Module-level journal write — what the hook sites call (reads the
    global at call time so _install retargets them all)."""
    _global.record(kind, key, **detail)


def _install(rec: FlightRecorder) -> FlightRecorder:
    """Swap the process-global recorder (test isolation hook, mirroring
    clusterobs._install / metrics._install_registry)."""
    global _global
    old = _global
    _global = rec
    return old
