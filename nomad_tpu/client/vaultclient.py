"""Task secrets-token derivation + renewal.

Reference: client/vaultclient/vaultclient.go — DeriveToken :234,
RenewToken :287 with a renewal min-heap :543 driving one timer loop
:464, StopRenewToken :511. The tpu-native build derives CLUSTER tokens
(TTL'd ACL tokens minted by the server's Secrets endpoint) instead of
talking to an external Vault; the client-side lifecycle — derive, renew
at half-TTL via a heap-ordered loop, stop+revoke on task death — is the
same contract.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Optional

logger = logging.getLogger("nomad_tpu.vaultclient")


class VaultClientError(Exception):
    pass


class VaultClient:
    """One per client agent; tracks every derived token's renewal."""

    def __init__(self, rpc) -> None:
        self.rpc = rpc
        # heap of (next_renewal_monotonic, seq, accessor_id)
        self._heap: list[tuple[float, int, str]] = []
        self._tracked: dict[str, float] = {}  # accessor -> ttl_s
        self._seq = 0
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # tests shrink this to exercise renewals quickly
        self.renew_fraction = 0.5

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._thread is not None:
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="vault-renewal"
            )
            self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    # -- public verbs (reference VaultClient interface) ----------------

    def derive_token(self, alloc_id: str, task_name: str) -> dict:
        """Mint a task token and enroll it for renewal; returns the
        server's {"secret_id", "accessor_id", "ttl_s"}."""
        out = self.rpc.derive_token(alloc_id, task_name)
        self._track(out["accessor_id"], float(out["ttl_s"]))
        return out

    def track(self, accessor_id: str, ttl_s: float = 3600.0) -> None:
        """Enroll an existing token for renewal (the client-restart
        restore path: the accessor was persisted beside the token)."""
        self._track(accessor_id, ttl_s)

    def stop_renew(self, accessor_id: str, revoke: bool = True) -> None:
        """Stop renewing; optionally revoke server-side (reference
        StopRenewToken + the server's token revocation on task death)."""
        with self._cv:
            self._tracked.pop(accessor_id, None)
            self._cv.notify()
        if revoke:
            try:
                self.rpc.revoke_token(accessor_id)
            except Exception:
                logger.debug("revoke of %s failed", accessor_id[:8])

    def tracked(self) -> int:
        with self._cv:
            return len(self._tracked)

    # -- internals -----------------------------------------------------

    def _track(self, accessor_id: str, ttl_s: float) -> None:
        with self._cv:
            self._tracked[accessor_id] = ttl_s
            self._seq += 1
            heapq.heappush(
                self._heap,
                (
                    time.monotonic() + ttl_s * self.renew_fraction,
                    self._seq,
                    accessor_id,
                ),
            )
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop:
                    # drop heap heads that were stop_renew'd
                    while self._heap and self._heap[0][2] not in self._tracked:
                        heapq.heappop(self._heap)
                    if not self._heap:
                        self._cv.wait()
                        continue
                    delay = self._heap[0][0] - time.monotonic()
                    if delay <= 0:
                        break
                    self._cv.wait(timeout=delay)
                if self._stop:
                    return
                _, _, accessor = heapq.heappop(self._heap)
                if accessor not in self._tracked:
                    continue
            try:
                ttl = float(self.rpc.renew_token(accessor))
                self._track(accessor, ttl)
            except Exception as e:
                msg = str(e).lower()
                if "expired" in msg or "not found" in msg:
                    # token is truly dead: stop tracking (reference
                    # propagates the terminal error on the renewal chan)
                    logger.warning(
                        "token %s renewal failed terminally: %s",
                        accessor[:8], e,
                    )
                    with self._cv:
                        self._tracked.pop(accessor, None)
                else:
                    # transient (leader election, network blip): keep the
                    # token tracked and retry well before the TTL runs
                    # out — one blip must not let a running task's token
                    # silently expire
                    ttl = self._tracked.get(accessor, 60.0)
                    retry_s = min(max(ttl * 0.1, 1.0), 30.0)
                    logger.info(
                        "token %s renewal failed (%s); retrying in %.0fs",
                        accessor[:8], e, retry_s,
                    )
                    with self._cv:
                        if accessor in self._tracked:
                            self._seq += 1
                            heapq.heappush(
                                self._heap,
                                (
                                    time.monotonic() + retry_s,
                                    self._seq,
                                    accessor,
                                ),
                            )
