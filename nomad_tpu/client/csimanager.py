"""Client-side CSI volume manager.

Reference: client/pluginmanager/csimanager/ — tracks the CSI plugins
available on this node, fingerprints them onto the Node struct
(Node.CSINodePlugins; volume_manager.go owns the stage/publish refcounts,
instance manager the per-plugin health loop). One manager per client:

  * plugins are registered from client config (builtin catalog name or
    ``module:Class`` factory ref for external plugin processes);
  * ``fingerprint()`` yields the node's csi_plugins map the heartbeat
    carries to the servers (feeds scheduler feasibility and the server's
    /v1/plugins aggregation);
  * ``mount_volume`` runs controller-publish → node-stage (refcounted,
    once per volume per node) → node-publish (once per alloc) and returns
    the host path task volume_mounts bind to;
  * ``unmount_alloc`` unwinds publishes and unstages volumes whose last
    alloc left (volume_manager.go UnmountVolume → usage tracker).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from ..plugins.csi import CSIError, CSIPlugin, ExternalCSIPlugin, StageContext

logger = logging.getLogger("nomad_tpu.csimanager")


def _builtin(name: str) -> Optional[CSIPlugin]:
    if name == "hostpath":
        from ..plugins.csi import FakeCSIPlugin

        return FakeCSIPlugin(name="hostpath")
    return None


class CSIManager:
    def __init__(self, data_dir: str, node_id: str = "") -> None:
        self.data_dir = data_dir
        self.node_id = node_id
        self.plugins: dict[str, CSIPlugin] = {}
        self._lock = threading.Lock()
        # volume_id -> set of alloc ids publishing it (stage refcount)
        self._stage_users: dict[str, set[str]] = {}
        # alloc_id -> list of (plugin_id, volume, target_path)
        self._alloc_mounts: dict[str, list[tuple[str, object, str]]] = {}

    # -- plugin registry ----------------------------------------------

    def register(self, plugin_id: str, plugin: CSIPlugin) -> None:
        with self._lock:
            self.plugins[plugin_id] = plugin

    def register_from_config(self, cfg: dict[str, str]) -> None:
        """cfg: plugin_id -> builtin name | "module:Class" factory ref."""
        for plugin_id, ref in (cfg or {}).items():
            if ":" in ref:
                self.register(plugin_id, ExternalCSIPlugin(plugin_id, ref))
            else:
                p = _builtin(ref)
                if p is None:
                    logger.warning("unknown builtin CSI plugin %r", ref)
                else:
                    self.register(plugin_id, p)

    def shutdown(self) -> None:
        for p in self.plugins.values():
            if isinstance(p, ExternalCSIPlugin):
                p.shutdown_plugin()

    # -- fingerprint ---------------------------------------------------

    def fingerprint(self) -> dict[str, dict]:
        """The node's csi_plugins map (reference: instance manager
        fingerprint loop updating Node.CSINodePlugins)."""
        out: dict[str, dict] = {}
        for plugin_id, plugin in list(self.plugins.items()):
            try:
                info = plugin.plugin_info()
                healthy = plugin.probe()
                provider_id = plugin.node_get_info().get("node_id", "")
            except Exception:
                logger.exception("CSI plugin %s fingerprint failed", plugin_id)
                out[plugin_id] = {"healthy": False}
                continue
            out[plugin_id] = {
                "version": info.version,
                "healthy": healthy,
                "controller": info.controller,
                "node": info.node,
                "provider_node_id": provider_id,
            }
        return out

    # -- mount lifecycle ----------------------------------------------

    def _staging_path(self, plugin_id: str, volume_id: str) -> str:
        return os.path.join(
            self.data_dir, "csi", plugin_id, "staging", volume_id
        )

    def _target_path(self, plugin_id: str, volume_id: str,
                     alloc_id: str) -> str:
        return os.path.join(
            self.data_dir, "csi", plugin_id, "per-alloc", alloc_id, volume_id
        )

    def mount_volume(self, vol, alloc_id: str, read_only: bool) -> str:
        """Full attach for one alloc; returns the published host path.

        ``vol`` is a structs.Volume with type == "csi". Raises CSIError
        when the plugin is absent or any CSI verb fails (the alloc then
        fails setup, matching csi_hook.go's behavior).
        """
        plugin = self.plugins.get(vol.plugin_id)
        if plugin is None:
            raise CSIError(
                f"volume {vol.id}: CSI plugin {vol.plugin_id!r} "
                f"not present on this node"
            )
        plugin.validate_volume(
            vol.id, vol.external_id, vol.access_mode, vol.attachment_mode
        )
        publish_ctx = plugin.controller_publish(
            vol.id, vol.external_id, self.node_id, read_only
        )
        staging = self._staging_path(vol.plugin_id, vol.id)
        target = self._target_path(vol.plugin_id, vol.id, alloc_id)
        ctx = StageContext(
            volume_id=vol.id,
            external_id=vol.external_id,
            staging_path=staging,
            target_path=target,
            read_only=read_only,
            access_mode=vol.access_mode,
            attachment_mode=vol.attachment_mode,
            context={**vol.context, **(publish_ctx or {})},
        )
        with self._lock:
            users = self._stage_users.setdefault(vol.id, set())
            first = not users
            users.add(alloc_id)
        try:
            if first:
                plugin.node_stage(ctx)
            plugin.node_publish(ctx)
        except Exception:
            with self._lock:
                self._stage_users.get(vol.id, set()).discard(alloc_id)
            raise
        with self._lock:
            self._alloc_mounts.setdefault(alloc_id, []).append(
                (vol.plugin_id, vol, target)
            )
        return target

    def unmount_alloc(self, alloc_id: str) -> None:
        """Unpublish this alloc's volumes; unstage + controller-unpublish
        any volume it was the last user of."""
        with self._lock:
            mounts = self._alloc_mounts.pop(alloc_id, [])
        for plugin_id, vol, target in mounts:
            plugin = self.plugins.get(plugin_id)
            if plugin is None:
                continue
            try:
                plugin.node_unpublish(vol.id, target)
            except Exception:
                logger.exception("node_unpublish %s failed", vol.id)
            with self._lock:
                users = self._stage_users.get(vol.id, set())
                users.discard(alloc_id)
                last = not users
            if last:
                try:
                    plugin.node_unstage(
                        vol.id, self._staging_path(plugin_id, vol.id)
                    )
                except Exception:
                    logger.exception("node_unstage %s failed", vol.id)
                try:
                    plugin.controller_unpublish(
                        vol.id, vol.external_id, self.node_id
                    )
                except Exception:
                    logger.exception(
                        "controller_unpublish %s failed", vol.id
                    )
