"""Previous-allocation watcher + ephemeral disk migration.

Reference: client/allocwatcher/ — a replacement alloc (reschedule,
migrate, destructive update) with `ephemeral_disk { sticky = true }` or
`{ migrate = true }` waits for its predecessor to terminate and inherits
its shared data dir: moved on the same node, streamed over the client
fabric (the FS.ls/FS.cat surface) from the old node otherwise.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Callable, Optional

logger = logging.getLogger("nomad_tpu.allocwatcher")


class PrevAllocMigrator:
    def __init__(
        self,
        alloc,
        tg,
        allocdir,
        local_runner_fn: Callable[[str], Optional[object]],
        rpc=None,
        secret="",  # str | rpc.keyring.Keyring
        wait_timeout_s: float = 30.0,
        tls_context=None,
    ) -> None:
        self.tls_context = tls_context
        self.alloc = alloc
        self.tg = tg
        self.allocdir = allocdir
        self.local_runner_fn = local_runner_fn
        self.rpc = rpc
        self.secret = secret
        self.wait_timeout_s = wait_timeout_s

    def run(self) -> None:
        """Blocks (bounded) until the previous alloc's data is inherited.
        Failures degrade to an empty data dir — migration is best-effort,
        never a reason to fail the replacement (reference allocwatcher
        logs and moves on)."""
        prev_id = self.alloc.previous_allocation
        ed = self.tg.ephemeral_disk
        if not prev_id or not (ed.sticky or ed.migrate):
            return
        try:
            local = self.local_runner_fn(prev_id)
            if local is not None:
                self._wait_local(local)
                self._move_local(local)
            elif ed.migrate and self.rpc is not None:
                self._fetch_remote(prev_id)
        except Exception:
            logger.exception(
                "alloc %s: ephemeral disk migration from %s failed",
                self.alloc.id[:8],
                prev_id[:8],
            )

    # -- local (same node) ---------------------------------------------

    def _wait_local(self, runner) -> None:
        deadline = time.monotonic() + self.wait_timeout_s
        while time.monotonic() < deadline:
            if runner.alloc.client_terminal_status():
                return
            states = runner.alloc.task_states or {}
            if states and all(ts.state == "dead" for ts in states.values()):
                return
            time.sleep(0.1)
        logger.warning(
            "previous alloc %s still running after %.0fs; migrating anyway",
            runner.alloc.id[:8],
            self.wait_timeout_s,
        )

    def _move_local(self, runner) -> None:
        src = runner.allocdir.data_dir
        dst = self.allocdir.data_dir
        if not os.path.isdir(src):
            return
        os.makedirs(dst, exist_ok=True)
        moved = 0
        for name in os.listdir(src):
            shutil.move(os.path.join(src, name), os.path.join(dst, name))
            moved += 1
        logger.info(
            "alloc %s: inherited %d entries from %s (local move)",
            self.alloc.id[:8],
            moved,
            runner.alloc.id[:8],
        )

    # -- remote (cross-node, over the client fabric) -------------------

    def _prev_addr(self, prev_id: str):
        fn = getattr(self.rpc, "alloc_client_addr", None)
        if fn is None:
            return None, None
        try:
            return fn(prev_id)
        except Exception:
            return None, None

    def _fetch_remote(self, prev_id: str) -> None:
        from ..rpc import ConnPool

        deadline = time.monotonic() + self.wait_timeout_s
        prev, addr_s = None, None
        while time.monotonic() < deadline:
            prev, addr_s = self._prev_addr(prev_id)
            if prev is None:
                return  # GC'd already: nothing to inherit
            if prev.client_terminal_status():
                break
            time.sleep(0.2)
        if not addr_s:
            return
        host, _, port = str(addr_s).rpartition(":")
        addr = (host, int(port))
        pool = ConnPool(secret=self.secret, tls_context=self.tls_context)
        try:
            copied = self._fetch_tree(pool, addr, prev_id, "alloc/data", "")
            logger.info(
                "alloc %s: streamed %d files from %s@%s (migrate)",
                self.alloc.id[:8],
                copied,
                prev_id[:8],
                addr_s,
            )
        finally:
            pool.shutdown()

    def _fetch_tree(
        self, pool, addr, prev_id: str, remote_base: str, rel: str
    ) -> int:
        remote = os.path.join(remote_base, rel) if rel else remote_base
        session = pool.stream(
            addr, "FS.ls", {"alloc_id": prev_id, "path": remote}
        )
        try:
            msg = session.recv(timeout_s=30)
        finally:
            session.close()
        if msg.get("error"):
            raise OSError(f"remote ls {remote}: {msg['error']}")
        copied = 0
        for entry in msg.get("entries", []):
            child = os.path.join(rel, entry["name"]) if rel else entry["name"]
            if entry.get("is_dir"):
                os.makedirs(
                    os.path.join(self.allocdir.data_dir, child), exist_ok=True
                )
                copied += self._fetch_tree(
                    pool, addr, prev_id, remote_base, child
                )
                continue
            dst = os.path.join(self.allocdir.data_dir, child)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            cat = pool.stream(
                addr,
                "FS.cat",
                {"alloc_id": prev_id, "path": os.path.join(remote_base, child)},
            )
            try:
                with open(dst, "wb") as f:
                    while True:
                        m = cat.recv(timeout_s=30)
                        if m.get("error"):
                            raise OSError(f"remote cat {child}: {m['error']}")
                        data = m.get("data")
                        if data:
                            f.write(data)
                        if m.get("eof"):
                            break
            finally:
                cat.close()
            copied += 1
        return copied
