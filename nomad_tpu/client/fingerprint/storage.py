"""Storage fingerprinter — PERIODIC (reference
client/fingerprint/storage.go re-samples free space)."""

from __future__ import annotations

import shutil

from .base import Fingerprinter, FingerprintResponse

# Granularity keeps jitter (a few MB of disk churn) from re-registering
# the node every fingerprint period.
STORAGE_GRANULARITY_MB = 1024


class StorageFingerprint(Fingerprinter):
    name = "storage"
    periodic = True

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        try:
            disk = shutil.disk_usage(data_dir)
        except OSError:
            return resp
        free_mb = (disk.free // (1024 * 1024)) // STORAGE_GRANULARITY_MB
        free_mb *= STORAGE_GRANULARITY_MB
        total_mb = disk.total // (1024 * 1024)
        resp.attributes = {
            "unique.storage.volume": data_dir,
            "unique.storage.bytesfree": str(free_mb * 1024 * 1024),
            "unique.storage.bytestotal": str(total_mb * 1024 * 1024),
        }
        # same granularity as the attribute: disk_mb is hashed into the
        # computed node class, and raw free-byte jitter would fragment
        # the per-class feasibility memoization
        resp.resources["disk_mb"] = free_mb
        resp.detected = True
        return resp
