"""Network fingerprinter (reference client/fingerprint/network.go)."""

from __future__ import annotations

import socket

from ...structs import NetworkResource
from .base import Fingerprinter, FingerprintResponse


def default_ip() -> str:
    """The host's outbound IP (no packets are sent by a UDP connect)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 9))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class NetworkFingerprint(Fingerprinter):
    name = "network"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        ip = default_ip()
        resp.attributes = {
            "unique.network.ip-address": ip,
        }
        resp.resources["networks"] = [
            NetworkResource(
                device="lo", cidr="127.0.0.1/32", ip="127.0.0.1",
                mbits=1000,
            )
        ]
        resp.detected = True
        return resp
