"""Cgroup fingerprinter (reference client/fingerprint/cgroup_linux.go —
the exec driver's isolation depends on it)."""

from __future__ import annotations

import os

from .base import Fingerprinter, FingerprintResponse


class CgroupFingerprint(Fingerprinter):
    name = "cgroup"
    periodic = True  # mounts can appear after boot (reference: 15s period)

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        # USABLE v2 detection is the exec driver's _cgroup_available
        # (it also requires write access) — one detector, two consumers,
        # so the node attribute and driver.exec.cgroups can't disagree.
        from ...drivers.exec import _cgroup_available

        resp = FingerprintResponse()
        if _cgroup_available():
            resp.attributes["unique.cgroup.version"] = "v2"
            resp.attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
            resp.detected = True
        elif os.path.isdir("/sys/fs/cgroup/cpu"):
            resp.attributes["unique.cgroup.version"] = "v1"
            resp.attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
            resp.detected = True
        return resp
