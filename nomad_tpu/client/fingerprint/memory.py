"""Memory fingerprinter (reference client/fingerprint/memory.go)."""

from __future__ import annotations

from .base import Fingerprinter, FingerprintResponse


def total_memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 1024


class MemoryFingerprint(Fingerprinter):
    name = "memory"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        mb = total_memory_mb()
        resp.attributes["memory.totalbytes"] = str(mb * 1024 * 1024)
        resp.resources["memory_mb"] = mb
        resp.detected = True
        return resp
