"""Framework-version fingerprinter (reference
client/fingerprint/nomad.go)."""

from __future__ import annotations

from .base import Fingerprinter, FingerprintResponse


class NomadFingerprint(Fingerprinter):
    name = "nomad"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        resp.attributes["nomad.version"] = "0.1.0"
        resp.detected = True
        return resp
