"""Framework-version fingerprinter (reference
client/fingerprint/nomad.go)."""

from __future__ import annotations

from ... import __version__
from .base import Fingerprinter, FingerprintResponse


class NomadFingerprint(Fingerprinter):
    name = "nomad"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        resp.attributes["nomad.version"] = __version__
        resp.detected = True
        return resp
