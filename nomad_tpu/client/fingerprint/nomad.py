"""Framework-version fingerprinter (reference
client/fingerprint/nomad.go)."""

from __future__ import annotations

from ... import __version__
from .base import Fingerprinter, FingerprintResponse


class NomadFingerprint(Fingerprinter):
    name = "nomad"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        import os
        import sys

        import nomad_tpu

        resp = FingerprintResponse()
        resp.attributes["nomad.version"] = __version__
        # Where THIS node can run framework-owned helper tasks (the
        # connect sidecar): its own interpreter and package root — the
        # server must never bake its paths into injected tasks.
        resp.attributes["unique.nomad.python"] = sys.executable
        resp.attributes["unique.nomad.pkg_root"] = os.path.dirname(
            os.path.dirname(os.path.abspath(nomad_tpu.__file__))
        )
        resp.detected = True
        return resp
