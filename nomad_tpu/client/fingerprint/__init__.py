"""Host fingerprinting: what does this machine offer?

Reference: client/fingerprint/ (30+ files) — a registry of
fingerprinters (fingerprint.go:31-48 builtinFingerprintMap), each
contributing attributes/resources to the Node; periodic ones re-run on
a cadence and push node updates. The same shape here: one module per
fingerprinter, a registry, and two entry points the client uses —
``fingerprint_node`` (full pass at boot) and ``dynamic_attributes``
(periodic re-sample, consumed by the client's re-fingerprint loop).
"""

from __future__ import annotations

import logging
import socket
import uuid

logger = logging.getLogger("nomad_tpu.fingerprint")

from ...structs import Node, NodeResources
from ...structs.node_class import compute_node_class
from .base import Fingerprinter, FingerprintResponse
from .cgroup import CgroupFingerprint
from .cpu import CPUFingerprint
from .env_cloud import (
    EnvAWSFingerprint,
    EnvAzureFingerprint,
    EnvGCEFingerprint,
)
from .host import HostFingerprint
from .memory import MemoryFingerprint
from .network import NetworkFingerprint
from .nomad import NomadFingerprint
from .storage import StorageFingerprint

# registration order matters only for attribute collisions (last wins),
# mirroring the reference's map ordering by name
BUILTIN_FINGERPRINTERS: list[Fingerprinter] = [
    HostFingerprint(),
    CPUFingerprint(),
    MemoryFingerprint(),
    StorageFingerprint(),
    NetworkFingerprint(),
    CgroupFingerprint(),
    NomadFingerprint(),
    EnvAWSFingerprint(),
    EnvGCEFingerprint(),
    EnvAzureFingerprint(),
]


def fingerprint_node(
    node_id: str = "",
    datacenter: str = "dc1",
    node_class: str = "",
    data_dir: str = "/tmp",
) -> Node:
    """Run every fingerprinter and assemble the Node."""
    attributes: dict[str, str] = {}
    # Start from ZERO capacity, not the struct defaults: a failed
    # resource fingerprinter must leave the node advertising nothing in
    # that dimension (under-advertising wastes capacity; the defaults
    # would OVER-advertise and place allocs that fail at runtime).
    resources = NodeResources(cpu=0, memory_mb=0, disk_mb=0, networks=[])
    for fp in BUILTIN_FINGERPRINTERS:
        try:
            resp = fp.fingerprint(data_dir)
        except Exception:
            # one broken fingerprinter must not sink the node, but it
            # must be VISIBLE — silence here cost real capacity
            logger.exception("fingerprinter %s failed", fp.name)
            continue
        if not resp.detected:
            continue
        attributes.update(resp.attributes)
        if "cpu" in resp.resources:
            resources.cpu = resp.resources["cpu"]
        if "total_cores" in resp.resources:
            resources.total_cores = resp.resources["total_cores"]
        if "memory_mb" in resp.resources:
            resources.memory_mb = resp.resources["memory_mb"]
        if "disk_mb" in resp.resources:
            resources.disk_mb = resp.resources["disk_mb"]
        if "networks" in resp.resources:
            resources.networks = resp.resources["networks"]
    node = Node(
        id=node_id or str(uuid.uuid4()),
        name=socket.gethostname(),
        datacenter=datacenter,
        node_class=node_class,
        attributes=attributes,
        resources=resources,
    )
    node.computed_class = compute_node_class(node)
    return node


def dynamic_attributes(data_dir: str = "/tmp") -> dict[str, str]:
    """Re-run the PERIODIC fingerprinters (reference: each periodic
    fingerprinter pushes node updates on its cadence; the client's one
    re-fingerprint loop consumes this)."""
    out: dict[str, str] = {}
    for fp in BUILTIN_FINGERPRINTERS:
        if not fp.periodic:
            continue
        try:
            resp = fp.fingerprint(data_dir)
        except Exception:
            logger.exception("periodic fingerprinter %s failed", fp.name)
            continue
        if resp.detected:
            out.update(resp.attributes)
    return out
