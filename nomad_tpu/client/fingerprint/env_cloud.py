"""Cloud environment fingerprinters: AWS / GCE / Azure metadata.

Reference: client/fingerprint/env_aws.go:1 (EC2 metadata keys :90,
platform.aws.* attributes :124, link-speed estimate), env_gce.go,
env_azure.go. Each probes the cloud's link-local metadata service with a
short timeout; a machine not on that cloud simply reports undetected.

The metadata URL is overridable through the same environment variables
the reference honors (AWS_ENV_URL / GCE_ENV_URL / AZURE_ENV_URL), which
is also how tests point the fingerprinters at a fake metadata server.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

from .base import Fingerprinter, FingerprintResponse

#: seconds to wait on the metadata service; the reference uses 2s, but a
#: non-cloud host pays this at every boot per cloud, so stay snappy
DEFAULT_TIMEOUT_S = 0.25


def _get(
    url: str,
    headers: dict[str, str],
    timeout: float,
    method: str = "GET",
) -> str | None:
    req = urllib.request.Request(url, method=method)
    for k, v in headers.items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", "replace").strip()
    except (urllib.error.URLError, OSError, ValueError):
        return None


class EnvAWSFingerprint(Fingerprinter):
    """EC2 instance metadata → platform.aws.* attributes
    (reference env_aws.go:90 keys / :124 attribute naming)."""

    name = "env_aws"

    #: metadata key -> is node-unique (reference env_aws.go:90)
    KEYS = {
        "ami-id": False,
        "hostname": True,
        "instance-id": True,
        "instance-type": False,
        "local-hostname": True,
        "local-ipv4": True,
        "public-hostname": True,
        "public-ipv4": True,
        "mac": True,
        "placement/availability-zone": False,
    }

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        base = os.environ.get(
            "AWS_ENV_URL", "http://169.254.169.254/latest/meta-data/"
        )
        timeout = float(os.environ.get("AWS_ENV_TIMEOUT", DEFAULT_TIMEOUT_S))
        # IMDSv2 first (required on current-default EC2 launches): a
        # session token from PUT /latest/api/token; fall back to the
        # headerless v1 GETs when the token endpoint is absent.
        headers: dict[str, str] = {}
        token_url = base.rsplit("/meta-data", 1)[0].rstrip("/")
        if token_url.endswith("/latest"):
            token = _get(
                token_url + "/api/token",
                {"X-aws-ec2-metadata-token-ttl-seconds": "60"},
                timeout,
                method="PUT",
            )
            if token:
                headers["X-aws-ec2-metadata-token"] = token
        # cheap liveness probe first (reference isAWS :286 reads ami-id)
        if _get(base + "ami-id", headers, timeout) is None:
            return resp
        for key, unique in self.KEYS.items():
            val = _get(base + key, headers, timeout)
            if val is None or "\n" in val:
                continue
            attr = "platform.aws." + key.replace("/", ".")
            if unique:
                attr = "unique." + attr
            resp.attributes[attr] = val
        if resp.attributes:
            resp.attributes["platform.aws"] = "true"
            resp.detected = True
        return resp


class EnvGCEFingerprint(Fingerprinter):
    """GCE instance metadata → platform.gce.* attributes (reference
    env_gce.go; requires the Metadata-Flavor: Google header)."""

    name = "env_gce"

    KEYS = {
        "id": True,
        "hostname": True,
        "name": True,
        "machine-type": False,
        "zone": False,
        "cpu-platform": False,
    }
    HEADERS = {"Metadata-Flavor": "Google"}

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        base = os.environ.get(
            "GCE_ENV_URL",
            "http://169.254.169.254/computeMetadata/v1/instance/",
        )
        timeout = float(os.environ.get("GCE_ENV_TIMEOUT", DEFAULT_TIMEOUT_S))
        if _get(base + "id", self.HEADERS, timeout) is None:
            return resp
        for key, unique in self.KEYS.items():
            val = _get(base + key, self.HEADERS, timeout)
            if val is None:
                continue
            # zone/machine-type come as full resource paths; keep the leaf
            if key in ("zone", "machine-type"):
                val = val.rsplit("/", 1)[-1]
            attr = "platform.gce." + key
            if unique:
                attr = "unique." + attr
            resp.attributes[attr] = val
        if resp.attributes:
            resp.attributes["platform.gce"] = "true"
            resp.detected = True
        return resp


class EnvAzureFingerprint(Fingerprinter):
    """Azure IMDS compute metadata → platform.azure.* attributes
    (reference env_azure.go; requires the Metadata: true header)."""

    name = "env_azure"

    #: compute-document field -> is node-unique
    KEYS = {
        "name": True,
        "vmId": True,
        "vmSize": False,
        "location": False,
        "resourceGroupName": False,
    }
    HEADERS = {"Metadata": "true"}

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        base = os.environ.get(
            "AZURE_ENV_URL", "http://169.254.169.254/metadata/instance/"
        )
        timeout = float(
            os.environ.get("AZURE_ENV_TIMEOUT", DEFAULT_TIMEOUT_S)
        )
        raw = _get(
            base + "compute?api-version=2021-02-01&format=json",
            self.HEADERS,
            timeout,
        )
        if raw is None:
            return resp
        try:
            doc = json.loads(raw)
        except ValueError:
            return resp
        for key, unique in self.KEYS.items():
            val = doc.get(key)
            if not isinstance(val, str) or not val:
                continue
            attr = "platform.azure." + key
            if unique:
                attr = "unique." + attr
            resp.attributes[attr] = val
        if resp.attributes:
            resp.attributes["platform.azure"] = "true"
            resp.detected = True
        return resp
