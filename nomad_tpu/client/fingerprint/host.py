"""Host/kernel/OS fingerprinter (reference client/fingerprint/host.go
+ arch.go + signal.go)."""

from __future__ import annotations

import platform
import signal as _signal
import socket

from .base import Fingerprinter, FingerprintResponse


class HostFingerprint(Fingerprinter):
    name = "host"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        supported = sorted(
            s.name for s in _signal.Signals
            if s.name.startswith("SIG") and not s.name.startswith("SIGRT")
        )
        resp.attributes = {
            "kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "arch": platform.machine(),
            "os.name": platform.system().lower(),
            "os.version": platform.version(),
            "unique.hostname": socket.gethostname(),
            # drivers consult this for `signal`/change_signal support
            # (reference fingerprint/signal.go)
            "os.signals": ",".join(supported),
        }
        resp.detected = True
        return resp
