"""CPU fingerprinter (reference client/fingerprint/cpu.go)."""

from __future__ import annotations

import os
import platform

from .base import Fingerprinter, FingerprintResponse


def cpu_mhz_total() -> int:
    cores = os.cpu_count() or 1
    mhz = 2000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except OSError:
        pass
    return int(cores * mhz)


def cpu_model() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return ""


class CPUFingerprint(Fingerprinter):
    name = "cpu"

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        resp = FingerprintResponse()
        cores = os.cpu_count() or 1
        total = cpu_mhz_total()
        resp.attributes = {
            "cpu.numcores": str(cores),
            "cpu.totalcompute": str(total),
            "cpu.arch": platform.machine(),
            "cpu.modelname": cpu_model(),
            "cpu.frequency": str(total // cores),
        }
        resp.resources["cpu"] = total
        resp.resources["total_cores"] = cores
        resp.detected = True
        return resp
