"""Fingerprinter contract.

Reference: client/fingerprint/fingerprint.go — each fingerprinter
implements Fingerprint(request, response) adding attributes/resources
to the node; periodic ones re-run on their own cadence (:31-48
builtinFingerprintMap + periodic dispatch).
"""

from __future__ import annotations


class FingerprintResponse:
    """What one fingerprinter contributes."""

    def __init__(self) -> None:
        self.attributes: dict[str, str] = {}
        self.resources: dict = {}  # cpu / memory_mb / disk_mb / networks
        self.detected = False


class Fingerprinter:
    name = "base"
    #: periodic fingerprinters re-run in the client's re-fingerprint
    #: loop (reference: Periodic() (bool, time.Duration))
    periodic = False

    def fingerprint(self, data_dir: str) -> FingerprintResponse:
        raise NotImplementedError
