"""Bridge networking: a network namespace per alloc with enforced port
mapping.

Reference: client/allocrunner/networking_bridge_linux.go:1 (+
networking_cni.go): bridge mode gives each alloc its own netns, a veth
pair onto a shared bridge, and host-port → container-port forwards.

Deliberate departure from the reference's CNI/iptables pipeline: port
forwards here are USERSPACE TCP relays (the approach of Docker's
userland-proxy) run by the client process. That removes the iptables/CNI
plugin dependency — which sandboxed and minimal hosts often lack — while
enforcing exactly the same contract: the workload binds its container
port inside the netns; outside traffic reaches it only through the
host port the scheduler granted.

Everything shells out to ip(8); `available()` probes for root +
netns capability once and bridge mode degrades with a clear error when
the host can't do it.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import threading
from typing import Optional

logger = logging.getLogger("nomad_tpu.network")

BRIDGE_NAME = "nomadtpu0"
SUBNET_PREFIX = "172.26.64"  # /24 carved for alloc addresses
GATEWAY = f"{SUBNET_PREFIX}.1"
NETNS_DIR = "/var/run/netns"


def _ip(*args: str, check: bool = True) -> subprocess.CompletedProcess:
    proc = subprocess.run(
        ["ip", *args], capture_output=True, text=True, timeout=10
    )
    if check and proc.returncode != 0:
        raise NetworkError(
            f"ip {' '.join(args)}: {proc.stderr.strip() or proc.returncode}"
        )
    return proc


class NetworkError(Exception):
    pass


class AllocNetwork:
    """One alloc's namespace + its port forwards."""

    def __init__(self, ns_name: str, ip: str) -> None:
        self.ns_name = ns_name
        self.ip = ip
        self.ns_path = f"{NETNS_DIR}/{ns_name}"
        self.proxies: list[PortProxy] = []

    def close(self) -> None:
        for p in self.proxies:
            p.stop()
        self.proxies.clear()


class BridgeNetwork:
    """Manages the shared bridge and per-alloc namespaces."""

    _probe: Optional[bool] = None

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._used_ips: set[int] = set()
        self._allocs: dict[str, AllocNetwork] = {}

    @classmethod
    def available(cls) -> bool:
        """Can this host do netns + bridge? Probed once per process."""
        if cls._probe is None:
            if os.geteuid() != 0:
                cls._probe = False
            else:
                name = f"ntprobe{os.getpid() % 10000}"
                try:
                    _ip("netns", "add", name)
                    _ip("netns", "del", name)
                    cls._probe = True
                except (NetworkError, FileNotFoundError, OSError):
                    cls._probe = False
        return cls._probe

    def ensure_bridge(self) -> None:
        probe = _ip("link", "show", BRIDGE_NAME, check=False)
        if probe.returncode != 0:
            _ip("link", "add", BRIDGE_NAME, "type", "bridge")
        _ip("addr", "replace", f"{GATEWAY}/24", "dev", BRIDGE_NAME)
        _ip("link", "set", BRIDGE_NAME, "up")
        self._setup_egress()

    _egress_done = False

    def _setup_egress(self) -> None:
        """Best-effort outbound path for bridge allocs: enable forwarding
        and, when an nftables/iptables binary exists, masquerade the
        subnet. Hosts with neither (this build's sandbox) still get
        host↔alloc and alloc↔alloc connectivity plus inbound service
        traffic via the port relays — egress NAT is logged as absent,
        never silently faked."""
        if BridgeNetwork._egress_done:
            return
        BridgeNetwork._egress_done = True
        try:
            with open("/proc/sys/net/ipv4/ip_forward", "w") as f:
                f.write("1")
        except OSError:
            pass
        subnet = f"{SUBNET_PREFIX}.0/24"
        import shutil as _shutil

        if _shutil.which("iptables"):
            subprocess.run(
                ["iptables", "-t", "nat", "-C", "POSTROUTING", "-s",
                 subnet, "-j", "MASQUERADE"],
                capture_output=True,
            ).returncode == 0 or subprocess.run(
                ["iptables", "-t", "nat", "-A", "POSTROUTING", "-s",
                 subnet, "-j", "MASQUERADE"],
                capture_output=True,
            )
        elif _shutil.which("nft"):
            script = (
                "add table ip nomadtpu\n"
                "add chain ip nomadtpu post { type nat hook postrouting "
                "priority 100 ; }\n"
                f"add rule ip nomadtpu post ip saddr {subnet} masquerade\n"
            )
            subprocess.run(
                ["nft", "-f", "-"], input=script, text=True,
                capture_output=True,
            )
        else:
            logger.warning(
                "no iptables/nft: bridge allocs have no egress NAT "
                "(inbound service traffic still flows via port relays)"
            )

    # -- alloc lifecycle ------------------------------------------------

    def create(self, alloc_id: str) -> AllocNetwork:
        """netns + veth onto the bridge + addressing; idempotent per
        alloc. A namespace surviving from a previous agent incarnation
        (tasks outlive the agent) is ADOPTED, never recreated — deleting
        it would sever the live task's connectivity."""
        with self._lock:
            existing = self._allocs.get(alloc_id)
            if existing is not None:
                return existing
            self.ensure_bridge()
            short = alloc_id.replace("-", "")[:8]
            ns = f"nt-{short}"
            host_if = f"vh{short}"  # veth names cap at 15 chars
            peer_if = f"vp{short}"
            if _ip("netns", "list", check=False).stdout.find(ns) >= 0:
                adopted = self._adopt(alloc_id, ns)
                if adopted is not None:
                    return adopted
                # unusable leftover (no eth0/address): rebuild it
                _ip("netns", "del", ns, check=False)
            octet = self._pick_octet(alloc_id)
            ip = f"{SUBNET_PREFIX}.{octet}"
            try:
                _ip("netns", "add", ns)
                _ip(
                    "link", "add", host_if, "type", "veth",
                    "peer", "name", peer_if,
                )
                _ip("link", "set", host_if, "master", BRIDGE_NAME, "up")
                _ip("link", "set", peer_if, "netns", ns)
                _ip("-n", ns, "link", "set", peer_if, "name", "eth0")
                _ip("-n", ns, "addr", "add", f"{ip}/24", "dev", "eth0")
                _ip("-n", ns, "link", "set", "eth0", "up")
                _ip("-n", ns, "link", "set", "lo", "up")
                _ip("-n", ns, "route", "add", "default", "via", GATEWAY)
            except NetworkError:
                self._cleanup(ns, host_if)
                self._used_ips.discard(octet)
                raise
            net = AllocNetwork(ns, ip)
            self._allocs[alloc_id] = net
            return net

    def _adopt(self, alloc_id: str, ns: str) -> Optional[AllocNetwork]:
        """Reclaim a live namespace from a previous agent incarnation:
        read its eth0 address back instead of reassigning."""
        probe = _ip("-n", ns, "-4", "addr", "show", "eth0", check=False)
        if probe.returncode != 0:
            return None
        for tok in probe.stdout.split():
            if tok.startswith(SUBNET_PREFIX + "."):
                ip = tok.split("/")[0]
                self._used_ips.add(int(ip.rsplit(".", 1)[1]))
                net = AllocNetwork(ns, ip)
                self._allocs[alloc_id] = net
                logger.info("adopted existing netns %s (%s)", ns, ip)
                return net
        return None

    def destroy(self, alloc_id: str) -> None:
        with self._lock:
            net = self._allocs.pop(alloc_id, None)
            if net is None:
                return
            net.close()
            self._cleanup(net.ns_name, f"vh{alloc_id.replace('-', '')[:8]}")
            octet = int(net.ip.rsplit(".", 1)[1])
            self._used_ips.discard(octet)

    def shutdown(self, keep_namespaces: bool = False) -> None:
        """keep_namespaces=True is agent-restart semantics: stop the
        in-process port relays (they die with us anyway; the next
        incarnation adopts the netns and restarts them) but leave every
        namespace — its task is still running inside."""
        if keep_namespaces:
            for net in self._allocs.values():
                net.close()
            self._allocs.clear()
            return
        for alloc_id in list(self._allocs):
            try:
                self.destroy(alloc_id)
            except Exception:
                logger.exception("network teardown failed for %s", alloc_id)

    @staticmethod
    def _cleanup(ns: str, host_if: str) -> None:
        # deleting the ns destroys the veth peer; the host side follows,
        # but belt-and-suspenders in case the move never happened
        _ip("netns", "del", ns, check=False)
        _ip("link", "del", host_if, check=False)

    def _pick_octet(self, alloc_id: str) -> int:
        # stable-ish address per alloc with linear probing (2..254)
        start = (int(alloc_id.replace("-", "")[:8], 16) % 253) + 2
        for i in range(253):
            octet = ((start - 2 + i) % 253) + 2
            if octet not in self._used_ips:
                self._used_ips.add(octet)
                return octet
        raise NetworkError("bridge subnet exhausted")


class PortProxy:
    """Host-port forward into an alloc namespace: a TcpRelay with a
    fixed target (reference: the CNI portmap; approach: Docker's
    userland-proxy)."""

    def __init__(self, host_port: int, target_ip: str, target_port: int) -> None:
        from ..tcprelay import TcpRelay

        self.host_port = host_port
        self.target = (target_ip, target_port)
        self._relay = TcpRelay(host_port, lambda: self.target)

    def stop(self) -> None:
        self._relay.stop()
