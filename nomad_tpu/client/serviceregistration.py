"""Client-side service registration + health checking.

Reference: client/serviceregistration/ (the workload-services hook,
nsd/ provider) and command/agent/consul/check_watcher.go — the consul
sync's check scheduling, rebuilt against the cluster's OWN catalog (the
native-service-discovery design): registrations ride raft into the
services table, and this watcher pushes aggregate check status updates
the same way the consul agent would flip a check to critical.

One ServiceWatcher per alloc covers group services and every task's
services. Checks supported: ``http`` (2xx = passing), ``tcp``
(connect = passing) and ``script`` (command exec'd INSIDE the task via
the driver, exit 0 = passing — reference structs.go ServiceCheck
Command); intervals honor the check's ``interval``/``timeout``
(defaults 10s/2s, floors 1s/0.1s). A ``check_restart`` stanza
(reference command/agent/consul/check_watcher.go) restarts the task
after ``limit`` consecutive failures once ``grace`` has elapsed from
watch start; the restart consumes the task's restart-policy budget, so
a permanently sick task eventually fails instead of flapping forever.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import urllib.request
from typing import Optional

from ..structs.structs import ServiceRegistration

logger = logging.getLogger("nomad_tpu.services")


def _parse_secs(v, default: float) -> float:
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    from ..jobspec.hcl import parse_duration

    try:
        return parse_duration(str(v))
    except Exception:
        return default


def build_registrations(alloc, node, with_services: bool = False):
    """Materialize the alloc's service stanzas into catalog rows.

    Address selection (reference serviceregistration.GetAddress): the
    node's advertised IP; port from the alloc's allocated network ports
    by label, falling back to a literal numeric port_label.

    with_services=True also returns the source Service stanza per row
    (parallel list) so callers can pair checks without re-deriving the
    mapping."""
    job = alloc.job
    tg = job.lookup_task_group(alloc.task_group) if job else None
    if tg is None:
        return ([], []) if with_services else []
    address = ""
    if node is not None:
        address = node.attributes.get("unique.network.ip-address", "")
        if not address and node.http_addr:
            address = node.http_addr.rsplit(":", 1)[0]

    # label -> allocated HOST port value across task network asks AND
    # the group's shared networks (bridge-mode ports live there)
    ports: dict[str, int] = {}
    if alloc.resources is not None:
        nets = list(alloc.resources.shared_networks)
        for tr in alloc.resources.tasks.values():
            nets.extend(tr.networks)
        for net in nets:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                ports[p.label] = p.value

    def port_for(label: str) -> int:
        if label in ports:
            return ports[label]
        try:
            return int(label)
        except (TypeError, ValueError):
            return 0

    regs: list[ServiceRegistration] = []
    sources: list = []

    def add(svc, task_name: str) -> None:
        sources.append(svc)
        regs.append(
            ServiceRegistration(
                id=(
                    f"_nomad-{alloc.id[:8]}-{task_name or 'group'}-"
                    f"{svc.name}-{svc.port_label}"
                ),
                service_name=svc.name,
                namespace=alloc.namespace,
                node_id=node.id if node is not None else "",
                datacenter=node.datacenter if node is not None else "",
                job_id=alloc.job_id,
                alloc_id=alloc.id,
                task_name=task_name,
                tags=list(svc.tags),
                address=address,
                port=port_for(svc.port_label),
            )
        )

    for svc in tg.services:
        if svc.name:
            add(svc, "")
    for task in tg.tasks:
        for svc in task.services:
            if svc.name:
                add(svc, task.name)
    return (regs, sources) if with_services else regs


class ServiceWatcher:
    """Registers an alloc's services, keeps their check status fresh,
    deregisters on stop."""

    def __init__(self, alloc, node, rpc,
                 poll_interval_s: Optional[float] = None,
                 exec_fn=None, restart_fn=None, started_fn=None) -> None:
        import os

        self.alloc = alloc
        self.node = node
        self.rpc = rpc
        # exec_fn(task_name, cmd: list, timeout_s) -> exit_code — script
        # checks; restart_fn(task_name, reason) — check_restart trips;
        # started_fn(task_name) -> start stamp (any monotone value that
        # CHANGES on restart) so grace re-arms per instance
        self.exec_fn = exec_fn
        self.restart_fn = restart_fn
        self.started_fn = started_fn
        self._started_at = time.monotonic()
        # (reg.id, check idx) -> consecutive failures since last pass
        self._fail_counts: dict[tuple[str, int], int] = {}
        # task -> last seen start stamp (re-arms counters on change)
        self._grace_base: dict[str, int] = {}
        self.regs, sources = build_registrations(
            alloc, node, with_services=True
        )
        # reg.id -> its source stanza's check dicts, paired by
        # construction (a name registered on two ports keeps its own
        # checks; a key-based lookup couldn't tell them apart)
        self._checks = {
            reg.id: list(svc.checks)
            for reg, svc in zip(self.regs, sources)
        }
        self.poll_interval_s = (
            poll_interval_s
            if poll_interval_s is not None
            else float(os.environ.get("NOMAD_CHECK_POLL_INTERVAL", "10.0"))
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if not self.regs:
            return
        self._register(initial=True)
        if any(self._checks.values()):
            self._thread = threading.Thread(
                target=self._check_loop, daemon=True,
                name=f"svc-checks-{self.alloc.id[:8]}",
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.regs:
            try:
                self.rpc.services_deregister_alloc(self.alloc.id)
            except Exception:
                logger.exception(
                    "service deregister for alloc %s failed", self.alloc.id
                )

    # -- internals -----------------------------------------------------

    def _register(self, initial: bool = False) -> None:
        try:
            self.rpc.services_register(self.regs)
        except Exception:
            if initial:
                logger.exception(
                    "service register for alloc %s failed", self.alloc.id
                )

    def _run_check(self, reg: ServiceRegistration, check: dict) -> bool:
        ctype = check.get("type", "tcp")
        # the parser stores seconds under timeout_s; accept the raw
        # jobspec key too for hand-built check dicts
        timeout = _parse_secs(
            check.get("timeout_s", check.get("timeout")), 2.0
        )
        timeout = max(timeout, 0.1)
        addr = check.get("address") or reg.address or "127.0.0.1"
        port = reg.port
        if check.get("port"):
            try:
                port = int(check["port"])
            except (TypeError, ValueError):
                pass
        try:
            if ctype == "script":
                # group-service checks name their exec task via the
                # check's `task` field (reference ServiceCheck.TaskName)
                task = check.get("task") or reg.task_name
                if self.exec_fn is None or not task:
                    logger.warning(
                        "script check on %s has no exec context: critical",
                        reg.service_name,
                    )
                    return False
                cmd = [check.get("command", "")] + list(
                    check.get("args") or []
                )
                return self.exec_fn(task, cmd, timeout) == 0
            if ctype == "http":
                path = check.get("path", "/")
                proto = check.get("protocol", "http")
                url = f"{proto}://{addr}:{port}{path}"
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    return 200 <= resp.status < 300
            if ctype == "tcp":
                with socket.create_connection((addr, port), timeout=timeout):
                    return True
        except Exception:
            return False
        logger.warning("unsupported check type %r: marking critical", ctype)
        return False

    def _check_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            changed = False
            for reg in self.regs:
                checks = self._checks.get(reg.id) or []
                if not checks:
                    continue
                passing = True
                for i, c in enumerate(checks):
                    ok = self._run_check(reg, c)
                    passing = passing and ok
                    self._track_restart(reg, i, c, ok)
                status = "passing" if passing else "critical"
                if reg.status != status:
                    reg.status = status
                    changed = True
            if changed and not self._stop.is_set():
                self._register()

    def _track_restart(self, reg, idx: int, check: dict, ok: bool) -> None:
        """check_restart accounting: `limit` consecutive failures after
        `grace` from watch start trip a task restart (reference
        check_watcher.go checkRestart.apply)."""
        cr = check.get("check_restart") or {}
        limit = int(cr.get("limit", 0))
        if limit <= 0 or self.restart_fn is None:
            return
        key = (reg.id, idx)
        if ok:
            self._fail_counts[key] = 0
            return
        # grace counts from the task's LAST start, not watcher birth:
        # a restarted instance gets its full startup window again and
        # the previous instance's failures don't carry over (reference
        # check_watcher.go re-arms on task restart)
        target = check.get("task") or reg.task_name
        grace = float(cr.get("grace_s", 1.0))
        stamp = (
            self.started_fn(target)
            if self.started_fn is not None
            else 0
        )
        if stamp:
            prev = self._grace_base.get(target)
            if prev != stamp:
                # new instance observed: EVERY check that RESOLVES to
                # this task sheds the previous instance's failures —
                # including group-service checks naming it via `task`
                self._grace_base[target] = stamp
                for r in self.regs:
                    for i, c in enumerate(self._checks.get(r.id) or []):
                        if (c.get("task") or r.task_name) == target:
                            self._fail_counts[(r.id, i)] = 0
            # grace runs from the task's REAL start, so a long-running
            # instance's first failure counts immediately (reference
            # check_watcher: grace shields startup, not steady state)
            if (time.time_ns() - stamp) / 1e9 < grace:
                return
        elif time.monotonic() - self._started_at < grace:
            return
        n = self._fail_counts.get(key, 0) + 1
        self._fail_counts[key] = n
        if n < limit:
            return
        self._fail_counts[key] = 0
        reason = (
            f"check {check.get('name') or check.get('type')!r} "
            f"unhealthy {n}x"
        )
        logger.warning(
            "alloc %s task %s: %s — restarting",
            self.alloc.id[:8], target or "(group)", reason,
        )
        try:
            self.restart_fn(target, reason)
        except Exception:
            logger.exception("check_restart restart failed")
