"""Client-side service registration + health checking.

Reference: client/serviceregistration/ (the workload-services hook,
nsd/ provider) and command/agent/consul/check_watcher.go — the consul
sync's check scheduling, rebuilt against the cluster's OWN catalog (the
native-service-discovery design): registrations ride raft into the
services table, and this watcher pushes aggregate check status updates
the same way the consul agent would flip a check to critical.

One ServiceWatcher per alloc covers group services and every task's
services. Checks supported: ``http`` (2xx = passing) and ``tcp``
(connect = passing); intervals honor the check's ``interval``/``timeout``
(defaults 10s/2s, floors 1s/0.1s).
"""

from __future__ import annotations

import logging
import socket
import threading
import urllib.request
from typing import Optional

from ..structs.structs import ServiceRegistration

logger = logging.getLogger("nomad_tpu.services")


def _parse_secs(v, default: float) -> float:
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    from ..jobspec.hcl import parse_duration

    try:
        return parse_duration(str(v))
    except Exception:
        return default


def build_registrations(alloc, node, with_services: bool = False):
    """Materialize the alloc's service stanzas into catalog rows.

    Address selection (reference serviceregistration.GetAddress): the
    node's advertised IP; port from the alloc's allocated network ports
    by label, falling back to a literal numeric port_label.

    with_services=True also returns the source Service stanza per row
    (parallel list) so callers can pair checks without re-deriving the
    mapping."""
    job = alloc.job
    tg = job.lookup_task_group(alloc.task_group) if job else None
    if tg is None:
        return ([], []) if with_services else []
    address = ""
    if node is not None:
        address = node.attributes.get("unique.network.ip-address", "")
        if not address and node.http_addr:
            address = node.http_addr.rsplit(":", 1)[0]

    # label -> allocated port value across all task network asks
    ports: dict[str, int] = {}
    if alloc.resources is not None:
        for tr in alloc.resources.tasks.values():
            for net in tr.networks:
                for p in list(net.reserved_ports) + list(net.dynamic_ports):
                    ports[p.label] = p.value

    def port_for(label: str) -> int:
        if label in ports:
            return ports[label]
        try:
            return int(label)
        except (TypeError, ValueError):
            return 0

    regs: list[ServiceRegistration] = []
    sources: list = []

    def add(svc, task_name: str) -> None:
        sources.append(svc)
        regs.append(
            ServiceRegistration(
                id=(
                    f"_nomad-{alloc.id[:8]}-{task_name or 'group'}-"
                    f"{svc.name}-{svc.port_label}"
                ),
                service_name=svc.name,
                namespace=alloc.namespace,
                node_id=node.id if node is not None else "",
                datacenter=node.datacenter if node is not None else "",
                job_id=alloc.job_id,
                alloc_id=alloc.id,
                task_name=task_name,
                tags=list(svc.tags),
                address=address,
                port=port_for(svc.port_label),
            )
        )

    for svc in tg.services:
        if svc.name:
            add(svc, "")
    for task in tg.tasks:
        for svc in task.services:
            if svc.name:
                add(svc, task.name)
    return (regs, sources) if with_services else regs


class ServiceWatcher:
    """Registers an alloc's services, keeps their check status fresh,
    deregisters on stop."""

    def __init__(self, alloc, node, rpc,
                 poll_interval_s: Optional[float] = None) -> None:
        import os

        self.alloc = alloc
        self.node = node
        self.rpc = rpc
        self.regs, sources = build_registrations(
            alloc, node, with_services=True
        )
        # reg.id -> its source stanza's check dicts, paired by
        # construction (a name registered on two ports keeps its own
        # checks; a key-based lookup couldn't tell them apart)
        self._checks = {
            reg.id: list(svc.checks)
            for reg, svc in zip(self.regs, sources)
        }
        self.poll_interval_s = (
            poll_interval_s
            if poll_interval_s is not None
            else float(os.environ.get("NOMAD_CHECK_POLL_INTERVAL", "10.0"))
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if not self.regs:
            return
        self._register(initial=True)
        if any(self._checks.values()):
            self._thread = threading.Thread(
                target=self._check_loop, daemon=True,
                name=f"svc-checks-{self.alloc.id[:8]}",
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.regs:
            try:
                self.rpc.services_deregister_alloc(self.alloc.id)
            except Exception:
                logger.exception(
                    "service deregister for alloc %s failed", self.alloc.id
                )

    # -- internals -----------------------------------------------------

    def _register(self, initial: bool = False) -> None:
        try:
            self.rpc.services_register(self.regs)
        except Exception:
            if initial:
                logger.exception(
                    "service register for alloc %s failed", self.alloc.id
                )

    def _run_check(self, reg: ServiceRegistration, check: dict) -> bool:
        ctype = check.get("type", "tcp")
        timeout = _parse_secs(check.get("timeout"), 2.0)
        timeout = max(timeout, 0.1)
        addr = check.get("address") or reg.address or "127.0.0.1"
        port = reg.port
        if check.get("port"):
            try:
                port = int(check["port"])
            except (TypeError, ValueError):
                pass
        try:
            if ctype == "http":
                path = check.get("path", "/")
                proto = check.get("protocol", "http")
                url = f"{proto}://{addr}:{port}{path}"
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    return 200 <= resp.status < 300
            if ctype == "tcp":
                with socket.create_connection((addr, port), timeout=timeout):
                    return True
        except Exception:
            return False
        logger.warning("unsupported check type %r: marking critical", ctype)
        return False

    def _check_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            changed = False
            for reg in self.regs:
                checks = self._checks.get(reg.id) or []
                if not checks:
                    continue
                passing = all(self._run_check(reg, c) for c in checks)
                status = "passing" if passing else "critical"
                if reg.status != status:
                    reg.status = status
                    changed = True
            if changed and not self._stop.is_set():
                self._register()
