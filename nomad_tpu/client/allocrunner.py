"""Alloc runner: one allocation's lifecycle on a node.

Reference: client/allocrunner/alloc_runner.go — Run :292, task-state fan-in
handleTaskStateUpdates :479, Update :802, Destroy :956; the hook pipeline
(alloc dir, networking, …) is a fixed inline sequence in round 1.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

from ..drivers import Driver
from ..structs import Allocation, TaskState
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_RUN,
    JOB_TYPE_BATCH,
    JOB_TYPE_SYSBATCH,
)
from .allocdir import AllocDir
from .allochealth import HealthTracker, new_deployment_status
from .taskrunner import TaskRunner

logger = logging.getLogger("nomad_tpu.allocrunner")


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        drivers: dict[str, Driver],
        data_dir: str,
        on_update: Callable[[Allocation], None],
        node=None,
        state_db=None,
        restore: bool = False,
        client=None,  # the owning Client: prev-alloc lookups + rpc
    ) -> None:
        self._client = client
        self.alloc = alloc.copy()
        self.drivers = drivers
        self.allocdir = AllocDir(data_dir, alloc.id)
        self.alloc_dir = self.allocdir.alloc_dir
        self.on_update = on_update
        self.node = node
        self.state_db = state_db  # persists task handles for reattach
        self.restore = restore
        self.task_runners: dict[str, TaskRunner] = {}
        self._lock = threading.Lock()
        self._destroyed = False
        self._health: Optional[HealthTracker] = None
        self._services = None
        self._network = None  # AllocNetwork when bridge mode

    # ------------------------------------------------------------------

    def _on_handle(self, task_name: str, handle: dict) -> None:
        if self.state_db is not None:
            self.state_db.put_task_handle(self.alloc.id, task_name, handle)

    def _fail_all(self, tg, reason: str) -> None:
        logger.error("alloc %s: %s", self.alloc.id, reason)
        self.alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
        for task in tg.tasks:
            self.alloc.task_states[task.name] = TaskState(
                state="dead", failed=True
            )
        self.on_update(self.alloc)

    def _port_mappings(self) -> list[tuple[int, int]]:
        """(host port, container port) pairs this alloc was granted —
        ports with a `to` mapping forward; unmapped ports relay to the
        same number inside the namespace."""
        out: list[tuple[int, int]] = []
        res = self.alloc.resources
        if res is None:
            return out
        nets = list(res.shared_networks)
        for tr in res.tasks.values():
            nets.extend(tr.networks)
        for net in nets:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                if p.value:
                    out.append((p.value, p.to or p.value))
        return out

    def run(self) -> None:
        self.allocdir.build()
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        if tg is None:
            logger.error("alloc %s: unknown task group", self.alloc.id)
            self.alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
            self.on_update(self.alloc)
            return
        # Volume hook (reference alloc_runner_hooks.go csi_hook/volume_hook):
        # resolve group volume asks to host paths — host volumes from the
        # node fingerprint, CSI volumes via claim fetch + plugin mount —
        # before any task starts. Failure fails the alloc, not the node.
        try:
            volume_paths = self._resolve_volumes(tg)
        except Exception as e:
            self._fail_all(tg, f"volume setup failed: {e}")
            return
        # Bridge networking (reference alloc_runner_hooks.go
        # network_hook → networking_bridge_linux.go): a netns per alloc,
        # veth onto the shared bridge, and host→container port relays
        # for every granted port with a `to` mapping.
        if (
            tg.networks
            and tg.networks[0].mode == "bridge"
            and self._client is not None
        ):
            from .network import BridgeNetwork, NetworkError, PortProxy

            if not BridgeNetwork.available():
                self._fail_all(tg, "bridge networking unavailable on host")
                return
            try:
                net = self._client.bridge_network.create(self.alloc.id)
                for host_port, to_port in self._port_mappings():
                    net.proxies.append(
                        PortProxy(host_port, net.ip, to_port)
                    )
                self._network = net
            except (NetworkError, OSError) as e:
                self._client.bridge_network.destroy(self.alloc.id)
                self._fail_all(tg, f"network setup failed: {e}")
                return
        # Sticky/migrate ephemeral disk: inherit the previous alloc's
        # shared data before any task starts (reference allocwatcher;
        # restored allocs already own their dir).
        if (
            not self.restore
            and self.alloc.previous_allocation
            and self._client is not None
            and (tg.ephemeral_disk.sticky or tg.ephemeral_disk.migrate)
        ):
            from .allocwatcher import PrevAllocMigrator

            PrevAllocMigrator(
                self.alloc,
                tg,
                self.allocdir,
                lambda aid: self._client.alloc_runners.get(aid),
                rpc=self._client.rpc,
                secret=self._client.endpoints.rpc.keyring,
                tls_context=(
                    self._client.tls[1] if self._client.tls else None
                ),
            ).run()
        batch = job.type in (JOB_TYPE_BATCH, JOB_TYPE_SYSBATCH)
        restored_states = (
            self.state_db.get_task_states(self.alloc.id)
            if (self.restore and self.state_db is not None)
            else {}
        )
        for task in tg.tasks:
            driver = self.drivers.get(task.driver)
            if driver is None:
                self.alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
                self.alloc.task_states[task.name] = TaskState(
                    state="dead", failed=True
                )
                self.on_update(self.alloc)
                return
            restore_handle = None
            if self.restore and self.state_db is not None:
                restore_handle = self.state_db.get_task_handle(
                    self.alloc.id, task.name
                )
            tr = TaskRunner(
                self.alloc,
                task,
                driver,
                self.allocdir,
                self._task_state_updated,
                batch=batch,
                node=self.node,
                on_handle=self._on_handle,
                restore_handle=restore_handle,
                restore_state=restored_states.get(task.name),
                device_manager=(
                    self._client.device_manager
                    if self._client is not None
                    else None
                ),
                volume_paths=volume_paths,
                service_fn=(
                    (
                        lambda name: self._client.rpc.service_lookup(
                            self.alloc.namespace, name
                        )
                    )
                    if self._client is not None
                    else None
                ),
                secret_fn=(
                    (
                        lambda path, token="": self._client.rpc.secret_read(
                            self.alloc.namespace, path, token
                        )
                    )
                    if self._client is not None
                    else None
                ),
                vault_client=(
                    self._client.vault_client
                    if self._client is not None
                    else None
                ),
                network_ns=(
                    self._network.ns_path if self._network is not None else ""
                ),
            )
            self.task_runners[task.name] = tr
        for tr in self.task_runners.values():
            tr.start()
        # Service registration + checks (reference: the group/task
        # services hook via client/serviceregistration; catalog rows ride
        # raft into the cluster's own services table)
        if self._client is not None:
            from .serviceregistration import ServiceWatcher

            self._services = ServiceWatcher(
                self.alloc, self.node, self._client.rpc,
                exec_fn=self._check_exec,
                restart_fn=self._check_restart,
                started_fn=self._task_started_stamp,
            )
            self._services.start()
        # Deployment allocs get a health watcher (reference
        # alloc_runner_hooks.go: allocHealthWatcherHook → client/allochealth).
        # Canaries arrive with a deployment_status already attached
        # (canary=True, healthy=None) — "not yet judged" is healthy=None,
        # not status=None.
        ds = self.alloc.deployment_status
        if self.alloc.deployment_id and (ds is None or ds.healthy is None):
            self._health = HealthTracker(
                self.alloc, self._task_states, self._set_health
            )
            self._health.start()
        self._task_state_updated()

    def _resolve_volumes(self, tg) -> dict[str, tuple[str, bool]]:
        """Group volume name -> (host path, read_only).

        Host volumes come straight from the node fingerprint; CSI volumes
        are fetched by claim (Volume.for_alloc) and mounted through the
        node's CSI plugin. An unsatisfiable CSI ask raises — feasibility
        screened nodes, so this means the plugin died since placement."""
        paths: dict[str, tuple[str, bool]] = {}
        mounted = {vm.volume for t in tg.tasks for vm in t.volume_mounts}
        csi_vols = None
        for name, req in tg.volumes.items():
            if req.type in ("", "host"):
                hv = self.node.host_volumes.get(req.source) if self.node else None
                if hv is not None and hv.path:
                    paths[name] = (hv.path, hv.read_only or req.read_only)
                elif name in mounted:
                    # Feasibility placed us here because the fingerprint
                    # advertised the volume; if it's gone (or pathless)
                    # by run time, a task mount can't be satisfied —
                    # fail the alloc, not a per-task restart loop.
                    raise RuntimeError(
                        f"volume {name}: host volume {req.source!r} "
                        f"not present on this node"
                    )
            elif req.type == "csi":
                if self._client is None:
                    raise RuntimeError(
                        f"volume {name}: CSI mounts need a client context"
                    )
                if csi_vols is None:
                    csi_vols = self._client.rpc.volumes_for_alloc(self.alloc.id)
                match = next(
                    (
                        v
                        for v in csi_vols
                        if v.name == req.source and v.type == "csi"
                    ),
                    None,
                )
                if match is None:
                    raise RuntimeError(
                        f"volume {name}: no claimed CSI volume for source "
                        f"{req.source!r}"
                    )
                target = self._client.csi_manager.mount_volume(
                    match, self.alloc.id, req.read_only
                )
                paths[name] = (target, req.read_only)
        return paths

    def _task_states(self) -> dict:
        with self._lock:
            return {name: tr.state for name, tr in self.task_runners.items()}

    def _set_health(self, healthy: bool) -> None:
        with self._lock:
            status = new_deployment_status(healthy)
            # the canary marker rides the same struct — never clobber it
            prev = self.alloc.deployment_status
            if prev is not None:
                status.canary = prev.canary
            self.alloc.deployment_status = status
        self.on_update(self.alloc)

    def _task_state_updated(self) -> None:
        """Fan task states into the alloc's client status
        (reference alloc_runner.go:479)."""
        with self._lock:
            states = {name: tr.state for name, tr in self.task_runners.items()}
            self.alloc.task_states = {k: v.copy() for k, v in states.items()}
            if self.state_db is not None:
                for name, st in states.items():
                    self.state_db.put_task_state(self.alloc.id, name, st)
            failed = any(s.failed for s in states.values())
            all_dead = all(s.state == "dead" for s in states.values()) and states
            any_running = any(s.state == "running" for s in states.values())
            leader = next(
                (
                    name
                    for name, tr in self.task_runners.items()
                    if tr.task.leader
                ),
                None,
            )
            if failed:
                status = ALLOC_CLIENT_STATUS_FAILED
            elif all_dead:
                status = ALLOC_CLIENT_STATUS_COMPLETE
            elif any_running:
                status = ALLOC_CLIENT_STATUS_RUNNING
            else:
                status = ALLOC_CLIENT_STATUS_PENDING
            self.alloc.client_status = status
            # leader death kills followers (reference task_hook_coordinator)
            if leader and states.get(leader, TaskState()).state == "dead":
                for name, tr in self.task_runners.items():
                    if name != leader:
                        tr.kill()
            # tasks exited on their own (batch completion, failure):
            # deregister services and stop the check loop — the catalog
            # must not advertise a dead instance
            services = None
            teardown_net = False
            if status in (
                ALLOC_CLIENT_STATUS_COMPLETE, ALLOC_CLIENT_STATUS_FAILED
            ):
                services, self._services = self._services, None
                teardown_net = self._network is not None
        if services is not None:
            services.stop()
        if teardown_net:
            self._teardown_network()
        # Always sync: task_states changed even when status didn't, and the
        # client's alloc-sync loop batches/dedups by alloc id anyway.
        self.on_update(self.alloc)

    # ------------------------------------------------------------------

    def _lifecycle_targets(self, task_name: str):
        """Runners an operator lifecycle verb applies to: the named task
        (must exist), or every RUNNING task — a dead prestart task must
        not fail a whole-alloc restart (reference alloc restart only
        errors for an explicitly named non-running task)."""
        with self._lock:
            runners = dict(self.task_runners)
        if task_name:
            tr = runners.get(task_name)
            if tr is None:
                raise KeyError(f"task {task_name!r} not in alloc")
            return [tr]
        running = [
            tr for tr in runners.values() if tr.state.state == "running"
        ]
        if not running:
            raise RuntimeError("no running tasks in allocation")
        return running

    def restart(self, task_name: str = "") -> None:
        """Restart one task or every running task (reference
        alloc_endpoint.go Restart → task runner restart without budget)."""
        for tr in self._lifecycle_targets(task_name):
            tr.trigger_restart()

    # -- health-check hooks (serviceregistration.ServiceWatcher) -------

    def _check_exec(self, task_name: str, cmd: list, timeout_s: float):
        """Script checks run INSIDE the task's context via the driver
        (reference command/agent/consul/check_watcher.go execs through
        the driver's ExecTask). Non-zero on any failure to exec."""
        from ..drivers.base import DriverError

        tr = self.task_runners.get(task_name)
        if tr is None or tr.state.state != "running":
            return 1
        try:
            _out, code = tr.driver.exec_task(
                tr.task_id, cmd, timeout_s=max(timeout_s, 0.1)
            )
            return code
        except (DriverError, OSError):
            return 1

    def _task_started_stamp(self, task_name: str):
        """Start stamp for check_restart grace re-arming: changes on
        every (re)start of the task. Group services ("" task) re-arm on
        ANY task's restart — a group trip bounces every task."""
        if not task_name:
            return max(
                (tr.state.started_at_ns
                 for tr in self.task_runners.values()),
                default=0,
            )
        tr = self.task_runners.get(task_name)
        return tr.state.started_at_ns if tr is not None else 0

    def _check_restart(self, task_name: str, reason: str) -> None:
        """check_restart tripped: bounce the owning task (group service
        → every task, matching the reference's group-level semantics),
        consuming restart-policy budget. A NAMED task that doesn't
        exist is a config error — restarting the whole healthy group
        for it would burn every task's budget."""
        if task_name and task_name not in self.task_runners:
            logger.error(
                "alloc %s: check_restart names unknown task %r — ignoring",
                self.alloc.id[:8], task_name,
            )
            return
        targets = (
            [self.task_runners[task_name]]
            if task_name
            else list(self.task_runners.values())
        )
        for tr in targets:
            tr.trigger_failure_restart(reason)

    def signal(self, sig: str, task_name: str = "") -> None:
        for tr in self._lifecycle_targets(task_name):
            tr.signal(sig)

    def update(self, updated: Allocation) -> None:
        """Server pushed a new version of this alloc (reference Update :802)."""
        with self._lock:
            self.alloc.desired_status = updated.desired_status
            self.alloc.desired_description = updated.desired_description
            self.alloc.modify_index = updated.modify_index
        if updated.desired_status != ALLOC_DESIRED_STATUS_RUN:
            self.stop()

    def stop(self) -> None:
        # A server-initiated stop must not race the health tracker into
        # reporting a killed (dead, not failed) alloc as healthy.
        if self._health is not None:
            self._health.stop()
        if self._services is not None:
            self._services.stop()
            self._services = None
        for tr in self.task_runners.values():
            tr.kill()

    def _teardown_network(self) -> None:
        """Release the netns and its host-port relays (reference:
        network_hook Postrun)."""
        net, self._network = self._network, None
        if net is not None and self._client is not None:
            try:
                self._client.bridge_network.destroy(self.alloc.id)
            except Exception:
                logger.exception(
                    "alloc %s: network teardown failed", self.alloc.id
                )

    def destroy(self) -> None:
        self._destroyed = True
        self.stop()
        self._teardown_network()
        if self._client is not None:
            # unwind CSI publishes (reference: csi_hook Postrun)
            try:
                self._client.csi_manager.unmount_alloc(self.alloc.id)
            except Exception:
                logger.exception(
                    "alloc %s: CSI unmount failed", self.alloc.id
                )
        if self.state_db is not None:
            self.state_db.delete_alloc(self.alloc.id)

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return all(tr.wait(timeout_s) for tr in self.task_runners.values())

    def is_terminal(self) -> bool:
        with self._lock:
            return self.alloc.client_terminal_status()
