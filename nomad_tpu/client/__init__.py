from .allocrunner import AllocRunner
from .client import Client, ServerRPC
from .fingerprint import fingerprint_node
from .restarts import RestartTracker
from .taskrunner import TaskRunner
