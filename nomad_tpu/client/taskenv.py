"""Task environment construction + interpolation.

Reference: client/taskenv/ (~1,200 LoC) — env.go Builder assembles the
NOMAD_* environment from alloc/task/node state; taskenv.ReplaceEnv
interpolates ``${...}`` references in task config, constraints, and
templates. Same surface here: `build_env` and `interpolate`.
"""

from __future__ import annotations

import re
from typing import Any, Optional

from ..structs import Allocation, Node, Task

_VAR_RE = re.compile(r"\$\{([^}]+)\}")


def build_env(
    alloc: Allocation,
    task: Task,
    node: Optional[Node] = None,
    alloc_dir: str = "",
    task_dir: str = "",
    secrets_dir: str = "",
) -> dict[str, str]:
    job = alloc.job
    env: dict[str, str] = {
        "NOMAD_ALLOC_ID": alloc.id,
        "NOMAD_ALLOC_NAME": alloc.name,
        "NOMAD_ALLOC_INDEX": str(alloc.index()),
        "NOMAD_TASK_NAME": task.name,
        "NOMAD_GROUP_NAME": alloc.task_group,
        "NOMAD_JOB_ID": alloc.job_id,
        "NOMAD_JOB_NAME": job.name if job else "",
        "NOMAD_JOB_PARENT_ID": job.parent_id if job else "",
        "NOMAD_NAMESPACE": alloc.namespace,
        "NOMAD_REGION": job.region if job else "",
        "NOMAD_CPU_LIMIT": str(task.resources.cpu),
        "NOMAD_MEMORY_LIMIT": str(task.resources.memory_mb),
    }
    # oversubscription cap: set only when memory_max survived the
    # scheduler-config gate (reference NOMAD_MEMORY_MAX_LIMIT)
    if task.resources.memory_max_mb:
        env["NOMAD_MEMORY_MAX_LIMIT"] = str(task.resources.memory_max_mb)
    # dedicated cores the scheduler granted (reference NOMAD_CPU_CORES,
    # taskenv/env.go — comma list of core ids; drivers pin to them)
    if alloc.resources is not None:
        granted = alloc.resources.tasks.get(task.name)
        if granted is not None and granted.reserved_cores:
            env["NOMAD_CPU_CORES"] = ",".join(
                str(c) for c in granted.reserved_cores
            )
    if alloc_dir:
        env["NOMAD_ALLOC_DIR"] = alloc_dir
    if task_dir:
        env["NOMAD_TASK_DIR"] = task_dir
    if secrets_dir:
        env["NOMAD_SECRETS_DIR"] = secrets_dir
    if node is not None:
        env["NOMAD_DC"] = node.datacenter
        # the node's advertised IP (same selection as service
        # registration): lets netns'd tasks — the connect sidecar —
        # recognize "this host's own address", which is invisible from
        # inside the namespace
        host_ip = node.attributes.get("unique.network.ip-address", "")
        if not host_ip and node.http_addr:
            host_ip = node.http_addr.rsplit(":", 1)[0]
        if host_ip:
            env["NOMAD_HOST_IP"] = host_ip
        env["node.unique.id"] = node.id
        env["node.datacenter"] = node.datacenter
        env["node.unique.name"] = node.name
        env["node.class"] = node.node_class
        for k, v in node.attributes.items():
            env[f"attr.{k}"] = str(v)
        for k, v in node.meta.items():
            env[f"meta.{k}"] = str(v)
    # merged meta: job < group < task (reference CombinedTaskMeta)
    meta: dict[str, str] = {}
    if job is not None:
        meta.update(job.meta)
        tg = job.lookup_task_group(alloc.task_group)
        if tg is not None:
            meta.update(tg.meta)
    meta.update(task.meta)
    for k, v in meta.items():
        env[f"NOMAD_META_{k.upper().replace('-', '_')}"] = v
        env[f"NOMAD_META_{k}"] = v
    # network ports (reference: NOMAD_PORT_<label> / NOMAD_ADDR_<label>);
    # group-level (shared) networks are visible to every task
    if alloc.resources is not None:
        tr = alloc.resources.tasks.get(task.name)
        nets = list(alloc.resources.shared_networks)
        if tr is not None:
            nets.extend(tr.networks)
        if nets:
            for net in nets:
                for p in list(net.reserved_ports) + list(net.dynamic_ports):
                    # with a `to` mapping (bridge mode) the task binds the
                    # container-side port; NOMAD_HOST_PORT carries the
                    # host side (reference taskenv: AddrPrefix/HostPort)
                    env[f"NOMAD_PORT_{p.label}"] = str(p.to or p.value)
                    env[f"NOMAD_IP_{p.label}"] = net.ip
                    env[f"NOMAD_ADDR_{p.label}"] = f"{net.ip}:{p.value}"
                    env[f"NOMAD_HOST_PORT_{p.label}"] = str(p.value)
    # connect upstreams: tasks reach the mesh through the sidecar's
    # local listener (reference taskenv: NOMAD_UPSTREAM_ADDR_<dest>)
    tg = job.lookup_task_group(alloc.task_group) if job is not None else None
    if tg is not None:
        for svc in tg.services:
            if svc.connect is None or svc.connect.sidecar_service is None:
                continue
            for up in svc.connect.sidecar_service.upstreams:
                key = up.destination_name.upper().replace("-", "_")
                env[f"NOMAD_UPSTREAM_ADDR_{key}"] = (
                    f"127.0.0.1:{up.local_bind_port}"
                )
                env[f"NOMAD_UPSTREAM_PORT_{key}"] = str(up.local_bind_port)
    for k, v in task.env.items():
        env[k] = interpolate(v, env)
    return env


def interpolate(value: Any, env: dict[str, str]) -> Any:
    """Replace ``${...}`` references with env values, recursively through
    lists/dicts (reference taskenv.ReplaceEnv). Unknown references stay
    literal, matching the reference's pass-through behavior."""
    if isinstance(value, str):

        def sub(m: re.Match) -> str:
            key = m.group(1).strip()
            if key in env:
                return env[key]
            if key.startswith("env."):
                return env.get(key[4:], m.group(0))
            return m.group(0)

        return _VAR_RE.sub(sub, value)
    if isinstance(value, list):
        return [interpolate(v, env) for v in value]
    if isinstance(value, dict):
        return {k: interpolate(v, env) for k, v in value.items()}
    return value
