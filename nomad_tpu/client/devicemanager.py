"""Client-side device plugin framework.

Reference: client/devicemanager/ + plugins/device/ — device plugins
fingerprint accelerator groups onto the node so the scheduler's
DeviceAllocator (scheduler/device.py) has real instances to assign, and
the task runner turns assigned instance ids into the visibility env vars
the workload expects.

Builtin plugins:
  * tpu    — detects TPU chips by their /dev/accel* (or /dev/vfio) device
             files, the tpu-native analog of the reference's nvidia plugin
  * nvidia — nvidia-smi when present (reference drivers/../nvidia)

The interface is the same Fingerprint/Reserve split as the reference's
device plugin API; out-of-process plugins can slot in behind it later.
"""

from __future__ import annotations

import glob
import logging
import os
import subprocess
from typing import Optional

from ..structs.structs import NodeDeviceInstance, NodeDeviceResource

logger = logging.getLogger("nomad_tpu.devicemanager")


class DevicePlugin:
    """One device family's detector (reference plugins/device/device.go)."""

    name = "base"

    def fingerprint(self) -> list[NodeDeviceResource]:
        raise NotImplementedError

    def env_var(self) -> str:
        """The visibility variable workloads read for this device type."""
        return f"NOMAD_DEVICE_{self.name.upper()}"

    def reserve(self, instance_ids: list[str]) -> dict:
        """Reservation response for granted instances (reference
        plugins/device Reserve → ContainerReservation): {"env": {...}}."""
        return {"env": {self.env_var(): ",".join(instance_ids)}}

    def stats(self) -> dict:
        """instance id -> {stat: value} (reference Stats stream)."""
        return {}


class TPUDevicePlugin(DevicePlugin):
    """TPU chips appear as /dev/accel<N> (PCI) or /dev/vfio devices."""

    name = "tpu"

    def __init__(self, dev_glob: str = "/dev/accel*") -> None:
        self.dev_glob = dev_glob

    def fingerprint(self) -> list[NodeDeviceResource]:
        paths = sorted(glob.glob(self.dev_glob))
        if not paths:
            return []
        instances = [
            NodeDeviceInstance(id=os.path.basename(p), healthy=True)
            for p in paths
        ]
        return [
            NodeDeviceResource(
                vendor="google",
                type="tpu",
                name="tpu",
                instances=instances,
                attributes={"count": len(instances)},
            )
        ]

    def env_var(self) -> str:
        return "TPU_VISIBLE_DEVICES"


class NvidiaDevicePlugin(DevicePlugin):
    name = "nvidia"

    def fingerprint(self) -> list[NodeDeviceResource]:
        try:
            out = subprocess.run(
                [
                    "nvidia-smi",
                    "--query-gpu=uuid,name",
                    "--format=csv,noheader",
                ],
                capture_output=True,
                timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            return []
        if out.returncode != 0:
            return []
        by_model: dict[str, list[NodeDeviceInstance]] = {}
        for line in out.stdout.decode(errors="replace").splitlines():
            parts = [p.strip() for p in line.split(",", 1)]
            if len(parts) != 2 or not parts[0]:
                continue
            by_model.setdefault(parts[1], []).append(
                NodeDeviceInstance(id=parts[0], healthy=True)
            )
        return [
            NodeDeviceResource(
                vendor="nvidia", type="gpu", name=model, instances=insts
            )
            for model, insts in by_model.items()
        ]

    def env_var(self) -> str:
        return "CUDA_VISIBLE_DEVICES"


class DeviceManager:
    """Aggregates plugins for node fingerprinting, task env wiring, and
    stats collection (reference client/devicemanager/manager.go).

    external: name -> "module:Class" factory refs (or
    {"factory": ref, "config": {...}} dicts) launched out-of-process
    over the device-plugin fabric (nomad_tpu/devices/plugin.py) — the
    reference's go-plugin device catalog."""

    def __init__(
        self,
        plugins: Optional[list[DevicePlugin]] = None,
        external: Optional[dict] = None,
    ) -> None:
        self.plugins = (
            plugins
            if plugins is not None
            else [TPUDevicePlugin(), NvidiaDevicePlugin()]
        )
        self._external = []
        for name, spec in (external or {}).items():
            from ..devices.plugin import ExternalDevicePlugin

            if isinstance(spec, dict):
                ref, config = spec.get("factory", ""), spec.get("config")
            else:
                ref, config = str(spec), None
            if ref:
                ext = ExternalDevicePlugin(name, ref, config)
                # an external plugin REPLACES a same-named builtin (the
                # driver-plugin catalog overlays builtins the same way)
                self.plugins = [p for p in self.plugins if p.name != name]
                self.plugins.append(ext)
                self._external.append(ext)

    def shutdown(self) -> None:
        for ext in self._external:
            try:
                ext.shutdown_plugin()
            except Exception:
                logger.exception("device plugin %s shutdown failed", ext.name)

    def fingerprint(self) -> list[NodeDeviceResource]:
        out: list[NodeDeviceResource] = []
        for plugin in self.plugins:
            try:
                out.extend(plugin.fingerprint())
            except Exception:
                logger.exception("device plugin %s failed", plugin.name)
        return out

    def stats(self) -> dict[str, dict]:
        """plugin name -> {instance id -> {stat: value}}."""
        out: dict[str, dict] = {}
        for plugin in self.plugins:
            try:
                s = plugin.stats()
            except Exception:
                logger.exception("device plugin %s stats failed", plugin.name)
                continue
            if s:
                out[plugin.name] = s
        return out

    def task_env(self, task_resources) -> dict[str, str]:
        """Visibility env vars for a task's ASSIGNED device instances
        (the scheduler's DeviceAllocator picked the ids; reference:
        the nvidia plugin's Reserve returns CUDA_VISIBLE_DEVICES)."""
        env: dict[str, str] = {}
        if task_resources is None:
            return env
        by_type: dict[str, list[str]] = {}
        for dev in getattr(task_resources, "devices", []) or []:
            dev_id = dev.get("id", "")  # vendor/type/name
            parts = dev_id.split("/")
            dtype = parts[1] if len(parts) > 1 else dev_id
            by_type.setdefault(dtype, []).extend(dev.get("device_ids", []))
        for dtype, ids in by_type.items():
            plugin = next(
                (
                    p
                    for p in self.plugins
                    if dtype in (p.name, getattr(p, "type", None))
                    or (dtype == "gpu" and p.name == "nvidia")
                ),
                None,
            )
            if plugin is not None:
                try:
                    env.update(plugin.reserve(ids).get("env", {}))
                    continue
                except Exception:
                    logger.exception(
                        "device plugin %s reserve failed", plugin.name
                    )
            env[f"NOMAD_DEVICE_{dtype.upper()}"] = ",".join(ids)
        return env
