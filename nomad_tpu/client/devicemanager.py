"""Client-side device plugin framework.

Reference: client/devicemanager/ + plugins/device/ — device plugins
fingerprint accelerator groups onto the node so the scheduler's
DeviceAllocator (scheduler/device.py) has real instances to assign, and
the task runner turns assigned instance ids into the visibility env vars
the workload expects.

Builtin plugins:
  * tpu    — detects TPU chips by their /dev/accel* (or /dev/vfio) device
             files, the tpu-native analog of the reference's nvidia plugin
  * nvidia — nvidia-smi when present (reference drivers/../nvidia)

The interface is the same Fingerprint/Reserve split as the reference's
device plugin API; out-of-process plugins can slot in behind it later.
"""

from __future__ import annotations

import glob
import logging
import os
import subprocess
from typing import Optional

from ..structs.structs import NodeDeviceInstance, NodeDeviceResource

logger = logging.getLogger("nomad_tpu.devicemanager")


class DevicePlugin:
    """One device family's detector (reference plugins/device/device.go)."""

    name = "base"

    def fingerprint(self) -> list[NodeDeviceResource]:
        raise NotImplementedError

    def env_var(self) -> str:
        """The visibility variable workloads read for this device type."""
        return f"NOMAD_DEVICE_{self.name.upper()}"


class TPUDevicePlugin(DevicePlugin):
    """TPU chips appear as /dev/accel<N> (PCI) or /dev/vfio devices."""

    name = "tpu"

    def __init__(self, dev_glob: str = "/dev/accel*") -> None:
        self.dev_glob = dev_glob

    def fingerprint(self) -> list[NodeDeviceResource]:
        paths = sorted(glob.glob(self.dev_glob))
        if not paths:
            return []
        instances = [
            NodeDeviceInstance(id=os.path.basename(p), healthy=True)
            for p in paths
        ]
        return [
            NodeDeviceResource(
                vendor="google",
                type="tpu",
                name="tpu",
                instances=instances,
                attributes={"count": len(instances)},
            )
        ]

    def env_var(self) -> str:
        return "TPU_VISIBLE_DEVICES"


class NvidiaDevicePlugin(DevicePlugin):
    name = "nvidia"

    def fingerprint(self) -> list[NodeDeviceResource]:
        try:
            out = subprocess.run(
                [
                    "nvidia-smi",
                    "--query-gpu=uuid,name",
                    "--format=csv,noheader",
                ],
                capture_output=True,
                timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            return []
        if out.returncode != 0:
            return []
        by_model: dict[str, list[NodeDeviceInstance]] = {}
        for line in out.stdout.decode(errors="replace").splitlines():
            parts = [p.strip() for p in line.split(",", 1)]
            if len(parts) != 2 or not parts[0]:
                continue
            by_model.setdefault(parts[1], []).append(
                NodeDeviceInstance(id=parts[0], healthy=True)
            )
        return [
            NodeDeviceResource(
                vendor="nvidia", type="gpu", name=model, instances=insts
            )
            for model, insts in by_model.items()
        ]

    def env_var(self) -> str:
        return "CUDA_VISIBLE_DEVICES"


class DeviceManager:
    """Aggregates plugins for node fingerprinting and task env wiring
    (reference client/devicemanager/manager.go)."""

    def __init__(self, plugins: Optional[list[DevicePlugin]] = None) -> None:
        self.plugins = (
            plugins
            if plugins is not None
            else [TPUDevicePlugin(), NvidiaDevicePlugin()]
        )

    def fingerprint(self) -> list[NodeDeviceResource]:
        out: list[NodeDeviceResource] = []
        for plugin in self.plugins:
            try:
                out.extend(plugin.fingerprint())
            except Exception:
                logger.exception("device plugin %s failed", plugin.name)
        return out

    def task_env(self, task_resources) -> dict[str, str]:
        """Visibility env vars for a task's ASSIGNED device instances
        (the scheduler's DeviceAllocator picked the ids; reference:
        the nvidia plugin's Reserve returns CUDA_VISIBLE_DEVICES)."""
        env: dict[str, str] = {}
        if task_resources is None:
            return env
        by_type: dict[str, list[str]] = {}
        for dev in getattr(task_resources, "devices", []) or []:
            dev_id = dev.get("id", "")  # vendor/type/name
            parts = dev_id.split("/")
            dtype = parts[1] if len(parts) > 1 else dev_id
            by_type.setdefault(dtype, []).extend(dev.get("device_ids", []))
        for dtype, ids in by_type.items():
            plugin = next(
                (
                    p
                    for p in self.plugins
                    if dtype in (p.name, getattr(p, "type", None))
                    or (dtype == "gpu" and p.name == "nvidia")
                ),
                None,
            )
            var = plugin.env_var() if plugin else f"NOMAD_DEVICE_{dtype.upper()}"
            env[var] = ",".join(ids)
        return env
