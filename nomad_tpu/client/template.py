"""Template rendering.

Reference: client/allocrunner/taskrunner/template/template.go (759 LoC,
consul-template). Without Consul/Vault in the tree, the supported
function set is the env-shaped subset real jobspecs rely on:

    {{ env "NOMAD_ALLOC_ID" }}
    {{ key "path" }}          -> empty string (no Consul KV)
    {{ meta "k" }}            -> NOMAD_META_k
    ${NOMAD_...}              -> plain interpolation

change_mode restart/signal/noop is honored by the task runner on
re-render; templates render once before task start (the reference's
initial render gate — prestart blocks until all templates render).
"""

from __future__ import annotations

import os
import re

from ..structs.structs import Template

_FUNC_RE = re.compile(r"\{\{\s*(env|key|meta)\s+\"([^\"]+)\"\s*\}\}")


class TemplateError(Exception):
    pass


def render_template(
    tmpl: Template, task_dir: str, env: dict[str, str]
) -> str:
    """Render to task_dir/<dest_path>; returns the destination path."""
    from .allocdir import EscapeError, alloc_sandbox, confine
    from .taskenv import interpolate

    sandbox = alloc_sandbox(task_dir)

    if tmpl.embedded_tmpl:
        src = tmpl.embedded_tmpl
    elif tmpl.source_path:
        path = interpolate(tmpl.source_path, env)
        if not os.path.isabs(path):
            path = os.path.join(task_dir, path)
        try:
            path = confine(sandbox, path)
        except EscapeError as e:
            raise TemplateError(str(e)) from e
        try:
            with open(path) as f:
                src = f.read()
        except OSError as e:
            raise TemplateError(f"template source: {e}") from e
    else:
        raise TemplateError("template has neither data nor source")

    def repl(m: re.Match) -> str:
        fn, arg = m.group(1), m.group(2)
        if fn == "env":
            return env.get(arg, "")
        if fn == "meta":
            return env.get(f"NOMAD_META_{arg}", env.get(f"meta.{arg}", ""))
        return ""  # key: no Consul KV backend

    rendered = _FUNC_RE.sub(repl, src)
    rendered = interpolate(rendered, env)

    dest = interpolate(tmpl.dest_path, env)
    if not dest:
        raise TemplateError("template missing destination")
    if not os.path.isabs(dest):
        dest = os.path.join(task_dir, dest)
    try:
        dest = confine(sandbox, dest)
    except EscapeError as e:
        raise TemplateError(str(e)) from e
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    with open(dest, "w") as f:
        f.write(rendered)
    try:
        os.chmod(dest, int(tmpl.perms or "0644", 8))
    except ValueError:
        pass
    return dest
